"""Front router: one endpoint over N serving replicas, least-loaded.

The thin request-routing tier the TF-paper systems framing calls for:
capacity (replica count) and versions (rollouts) change UNDER this
server without clients noticing. Design:

- **Least-loaded selection.** A scraper thread polls every replica's
  ``/metrics.json`` (its own port — the per-process registry) every
  ``scrape_interval_s`` and reads the serving gauges: queue depth
  (``hops_tpu_serving_batch_queue_depth``), in-flight executions
  (``hops_tpu_serving_inflight``) and the shed counter
  (``hops_tpu_serving_shed_total`` — its delta per scrape is the shed
  *rate*). The routing score adds the router's OWN per-replica
  in-flight count (exact and instant, where scrapes are stale by up to
  one interval — without it a burst between scrapes dogpiles the
  replica that looked idle last time). Lowest score wins; ties
  round-robin.
- **Routing around failure.** Each replica gets a
  ``resilience.CircuitBreaker``; a forward that fails at the transport
  (connect refused/reset/timeout) or with a replica-side 5xx records a
  failure and the request RETRIES on the next-best replica (predict is
  idempotent), so a dead or dying replica costs latency, not errors. A
  replica-side 503 (shedding, draining) retries elsewhere WITHOUT
  feeding the breaker — overload is load, not failure. 4xx is the
  client's problem and relays verbatim.
- **Per-tenant token buckets** (the layer above PR 5's per-replica
  load shedder): requests carry ``X-Tenant``; an empty bucket answers
  429 + ``Retry-After`` before any replica is touched.

Every forward passes through the ``router.forward`` fault point and an
explicit timeout (the ``blocking-call-no-deadline`` lint rule holds
this module to that).

**Gray-failure tolerance** (docs/operations.md "Tail latency & QoS").
Crash failures were already routed around (breakers, retries); the
mechanisms below keep the p99 honest when a component is *slow but
alive* — answering 200s at 20x the fleet median, which no breaker ever
sees:

- **Adaptive hedging** (:class:`HedgePolicy`): when a forward is still
  unanswered after an adaptive timer — the median across replicas of
  each replica's recent-latency p95, so one gray replica cannot
  inflate the timer that defends against it — a second attempt fires
  at the next-best replica; first response wins, the loser is
  abandoned WITHOUT a breaker strike (slow is not down). A hard hedge
  budget (``budget_frac``, default ≤5% of traffic, small burst) means
  hedging can never amplify an overload into a retry storm.
- **Outlier ejection** (:class:`EjectionPolicy`): each replica's
  latency EWMA is compared against the median of its peers; a replica
  answering far above the fleet (slow-but-200) is EJECTED into
  *probation* — distinct from breaker-open: the breaker opens on
  failures and heals on half-open successes, probation opens on
  latency and heals only when periodic **shadow probes** (copies of
  live requests, responses discarded) come back at fleet-normal
  latency ``readmit_probes`` times in a row. Ejections are capped
  (``max_ejected_frac``, never the last replica) so the detector can
  never empty the fleet.
- **QoS classes + brownout**: requests resolve to ``interactive`` or
  ``batch`` (``X-Priority`` header / tenant config, header can only
  demote — see :mod:`hops_tpu.runtime.qos`); per-class token buckets
  gate admission, and under sustained SLO burn a
  :class:`~hops_tpu.runtime.qos.BrownoutController` walks the fleet
  through *degrade* (downstream layers serve defaults / shrink decode
  budgets; forwards carry ``X-Hops-Brownout``) into *shed* (batch
  refused at the front door) — lowest class always sheds first.

**Zero-copy relay.** The forward path streams request and response
bodies through as raw bytes: the client's body goes onto the replica
wire unparsed, and the replica's response body returns to the client
byte-for-byte (2xx and 4xx/5xx alike) — no ``json.loads``/``json.dumps``
round-trip per hop (the ``relay-json-roundtrip`` lint rule keeps it
that way). Routing needs only the status code, headers and the
router's own scrape state; the body is parsed lazily in exactly two
places that need the object — the workload recorder's shape summaries
(armed captures only, after the reply is written) and the
``X-Hops-Debug: timeline`` merge (explicit operator ask). Tenant
extraction is header-based (``X-Tenant``). ``_reply`` recomputes only
the framing headers ``_relay_headers`` already owned.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import math
import statistics
import threading
import time
import urllib.error
from typing import Any

from hops_tpu.runtime import faultinject, flight, qos, wirecodec
from hops_tpu.runtime.httpclient import HTTPPool
from hops_tpu.runtime.httpserver import HTTPServer
from hops_tpu.runtime.logging import get_logger
from hops_tpu.runtime.resilience import CircuitBreaker, with_deadline
from hops_tpu.telemetry import export as telemetry_export
from hops_tpu.telemetry import tracing
from hops_tpu.telemetry import workload
from hops_tpu.telemetry.metrics import REGISTRY
from hops_tpu.telemetry.spans import span

log = get_logger(__name__)

_m_requests = REGISTRY.counter(
    "hops_tpu_fleet_requests_total",
    "Requests received by the fleet router, per endpoint",
    labels=("model",),
)
_m_forwards = REGISTRY.counter(
    "hops_tpu_fleet_forwards_total",
    "Forwards per endpoint and replica (the balance to watch)",
    labels=("model", "replica"),
)
_m_retries = REGISTRY.counter(
    "hops_tpu_fleet_retries_total",
    "Forwards retried on another replica, per endpoint and reason "
    "(connect | error | shed)",
    labels=("model", "reason"),
)
_m_rate_limited = REGISTRY.counter(
    "hops_tpu_fleet_rate_limited_total",
    "Requests answered 429 by the per-tenant token bucket",
    labels=("tenant",),
)
_m_unrouted = REGISTRY.counter(
    "hops_tpu_fleet_unrouted_total",
    "Requests that exhausted every replica (503/5xx to the client)",
    labels=("model",),
)
_m_hedges = REGISTRY.counter(
    "hops_tpu_fleet_hedges_total",
    "Hedged forwards per endpoint and outcome (won = the hedge "
    "answered first, lost = the primary did, denied = the hedge "
    "budget refused to fire one)",
    labels=("model", "outcome"),
)
_m_ejections = REGISTRY.counter(
    "hops_tpu_fleet_ejections_total",
    "Replicas ejected into latency probation (gray-failure outliers), "
    "per endpoint",
    labels=("model",),
)
_m_readmissions = REGISTRY.counter(
    "hops_tpu_fleet_readmissions_total",
    "Probation replicas re-admitted after healthy shadow probes, per "
    "endpoint",
    labels=("model",),
)
_m_probation = REGISTRY.gauge(
    "hops_tpu_fleet_probation_replicas",
    "Replicas currently in latency probation, per endpoint",
    labels=("model",),
)
_m_synthetic_probes = REGISTRY.counter(
    "hops_tpu_fleet_synthetic_probes_total",
    "Shadow probes fired with bodies materialized from the "
    "probe_workload capture artifact (probation re-admission when no "
    "live traffic flows), per endpoint",
    labels=("model",),
)
_m_qos_shed = REGISTRY.counter(
    "hops_tpu_fleet_qos_shed_total",
    "Requests refused by QoS policy, per endpoint, class, and reason "
    "(rate = class token bucket, brownout = batch shed under SLO burn)",
    labels=("model", "priority", "reason"),
)
_m_brownout = REGISTRY.gauge(
    "hops_tpu_fleet_brownout_level",
    "Current brownout level per endpoint (0 normal, 1 degrade, "
    "2 shed-batch)",
    labels=("model",),
)
_m_request_seconds = REGISTRY.histogram(
    "hops_tpu_fleet_latency_seconds",
    "Router end-to-end request latency per endpoint and QoS class "
    "(the SLO histogram the autoscaler's p99 signal reads)",
    labels=("model", "priority"),
)


#: Headers never relayed from a replica response: the body travels
#: through the router as VERBATIM bytes, but ``_reply`` still frames it
#: itself (one Content-Length it computed, one Content-Type it owns), so
#: passing the replica's framing through would send two (possibly
#: conflicting) Content-Lengths and truncate or hang clients. These
#: framing headers are the ONLY thing the relay recomputes.
_NO_RELAY_HEADERS = frozenset({
    "content-length", "content-type", "transfer-encoding", "connection",
    "keep-alive", "server", "date",
})


def _relay_headers(headers: Any) -> dict[str, str]:
    return {k: v for k, v in dict(headers).items()
            if k.lower() not in _NO_RELAY_HEADERS}


def _relayed_with_ctype(headers: Any) -> dict[str, str]:
    """Relay headers for a VERBATIM byte body: the non-framing headers
    plus the replica's own Content-Type — the bytes are the replica's
    serialization, so its declared type must travel with them
    (``_reply`` honors a caller-supplied Content-Type and recomputes
    only Content-Length)."""
    out = _relay_headers(headers)
    # Case-insensitive lookup: HTTP headers may arrive in any casing
    # (proxies/h2 commonly lowercase), and _relay_headers already
    # filtered every variant out.
    ctype = next(
        (v for k, v in dict(headers).items() if k.lower() == "content-type"),
        None,
    )
    if ctype:
        out["Content-Type"] = ctype
    return out


class TokenBucket:
    """Per-tenant rate limit: ``rate_rps`` tokens/s, ``burst`` deep.

    ``acquire()`` returns 0.0 when admitted (one token consumed) or the
    seconds until a token will exist — the 429's ``Retry-After``.
    Injectable clock for deterministic refill tests.
    """

    def __init__(self, rate_rps: float, burst: float,
                 clock=time.monotonic):
        if rate_rps <= 0 or burst <= 0:
            raise ValueError("rate_rps and burst must be > 0")
        self.rate_rps = float(rate_rps)
        self.burst = float(burst)
        self._clock = clock
        self._lock = threading.Lock()
        self._tokens = float(burst)  # guarded by: self._lock
        self._last = clock()  # guarded by: self._lock

    def acquire(self, n: float = 1.0) -> float:
        with self._lock:
            now = self._clock()
            self._tokens = min(
                self.burst, self._tokens + (now - self._last) * self.rate_rps)
            self._last = now
            if self._tokens >= n:
                self._tokens -= n
                return 0.0
            return (n - self._tokens) / self.rate_rps

    @property
    def tokens(self) -> float:
        with self._lock:
            now = self._clock()
            return min(
                self.burst, self._tokens + (now - self._last) * self.rate_rps)

    @property
    def last_used(self) -> float:
        """Clock time of the last ``acquire`` — the LRU key the
        limiter's bucket-map eviction sorts on."""
        with self._lock:
            return self._last


class TenantRateLimiter:
    """``{tenant: {"rate_rps": r, "burst": b}}`` with an optional
    ``"default"`` entry covering unnamed tenants; no entry = unlimited.

    ``X-Tenant`` is untrusted client input, so the bucket map is
    HARD-bounded at ``max_buckets``: buckets that have refilled to
    full burst are pruned first (a full bucket admits exactly like a
    fresh one, so that eviction never changes an answer), and when a
    spray of unique tenants leaves nothing refilled, the
    least-recently-used bucket is evicted anyway. An evicted mid-limit
    tenant returns later at full burst — under attack, bounded memory
    beats exact answers; real tenants keep acquiring, stay recent, and
    survive the LRU pass.
    """

    def __init__(self, limits: dict[str, dict[str, float]] | None,
                 clock=time.monotonic, max_buckets: int = 4096):
        self._clock = clock
        self._limits = dict(limits or {})
        self.max_buckets = max_buckets
        self._lock = threading.Lock()
        self._buckets: dict[str, TokenBucket] = {}  # guarded by: self._lock

    def acquire(self, tenant: str) -> float:
        """0.0 = admitted, else seconds until this tenant has a token."""
        spec = self._limits.get(tenant, self._limits.get("default"))
        if spec is None or not spec.get("rate_rps"):
            # No entry — or a QoS-only entry ({"priority": ...} with no
            # rate): unlimited here, the class buckets still apply.
            return 0.0
        with self._lock:
            bucket = self._buckets.get(tenant)
            if bucket is None:
                if len(self._buckets) >= self.max_buckets:
                    for name in [t for t, b in self._buckets.items()
                                 if b.tokens >= b.burst]:
                        del self._buckets[name]
                while len(self._buckets) >= self.max_buckets:
                    # Unique-tenant spray: nothing has refilled, but
                    # the cap is a hard bound — evict the coldest.
                    lru = min(self._buckets,
                              key=lambda t: self._buckets[t].last_used)
                    del self._buckets[lru]
                bucket = self._buckets[tenant] = TokenBucket(
                    spec["rate_rps"], spec.get("burst", spec["rate_rps"]),
                    clock=self._clock,
                )
        return bucket.acquire()

    def label_for(self, tenant: str) -> str:
        """Metric-safe tenant label: the tenant's own name only when
        it has an explicitly configured limit; everyone admitted under
        the ``"default"`` spec collapses to ``default`` — an untrusted
        ``X-Tenant`` spray must not mint unbounded counter children in
        the registry the router itself exports."""
        return tenant if tenant in self._limits else "default"

    def priority_for(self, tenant: str) -> str | None:
        """The tenant's configured QoS class (``{"priority": "batch"}``
        in its limit spec), or None when unconfigured — the header /
        default resolution in :func:`hops_tpu.runtime.qos.
        parse_priority` takes over."""
        spec = self._limits.get(tenant, self._limits.get("default"))
        return spec.get("priority") if spec else None


@dataclasses.dataclass(frozen=True)
class HedgePolicy:
    """Adaptive request hedging (docs/operations.md "Tail latency &
    QoS"). The budget is the safety property: hedges consume a token
    bucket refilled at ``budget_frac`` tokens per routed request, so
    over any window hedges stay ≤ ``budget_frac`` of traffic (plus the
    small ``budget_burst``) — hedging can never amplify an overload."""

    enabled: bool = True
    #: Hard hedge budget as a fraction of routed requests.
    budget_frac: float = 0.05
    #: Tokens the budget may bank (burst headroom at cold start).
    budget_burst: float = 5.0
    #: Recent latency samples the fleet needs before hedging arms
    #: (an adaptive timer from no data is a guess).
    min_samples: int = 16
    #: Clamp on the adaptive timer (median-across-replicas of p95s).
    delay_floor_s: float = 0.001
    delay_cap_s: float = 1.0

    def __post_init__(self) -> None:
        if not 0.0 < self.budget_frac <= 0.5:
            raise ValueError("budget_frac must be in (0, 0.5]")
        if self.delay_floor_s > self.delay_cap_s:
            raise ValueError("delay_floor_s must be <= delay_cap_s")


@dataclasses.dataclass(frozen=True)
class EjectionPolicy:
    """Gray-failure outlier ejection. A replica whose latency EWMA sits
    above ``factor`` × the median of its peers (and above ``floor_ms``
    absolutely, so microsecond jitter on an idle fleet never ejects) is
    moved to probation; shadow probes re-admit it once it answers at
    ≤ ``readmit_factor`` × the healthy median + ``readmit_slack_ms``
    for ``readmit_probes`` consecutive probes."""

    enabled: bool = True
    factor: float = 3.0
    floor_ms: float = 25.0
    min_samples: int = 20
    #: Never leave fewer than one replica, never eject more than this
    #: fraction of the ready fleet.
    max_ejected_frac: float = 0.5
    probe_interval_s: float = 0.5
    probe_timeout_s: float = 10.0
    readmit_probes: int = 3
    readmit_factor: float = 2.0
    readmit_slack_ms: float = 10.0

    def __post_init__(self) -> None:
        if self.factor <= 1.0:
            raise ValueError("ejection factor must be > 1")
        if not 0.0 < self.max_ejected_frac < 1.0:
            raise ValueError("max_ejected_frac must be in (0, 1)")
        if self.readmit_probes < 1:
            raise ValueError("readmit_probes must be >= 1")


class _LatencyStats:
    """Per-replica forward-latency tracker: EWMA (the ejection signal)
    plus a recent-sample ring (the hedge timer's p95 source)."""

    def __init__(self, window: int = 256, alpha: float = 0.2):
        self._lock = threading.Lock()
        self._ring: collections.deque[float] = collections.deque(maxlen=window)  # guarded by: self._lock
        self._ewma: float | None = None  # guarded by: self._lock
        self._alpha = alpha
        self.count = 0  # guarded by: self._lock

    def observe(self, seconds: float) -> None:
        with self._lock:
            self._ring.append(seconds)
            self.count += 1
            self._ewma = (
                seconds if self._ewma is None
                else self._alpha * seconds + (1 - self._alpha) * self._ewma
            )

    @property
    def ewma_ms(self) -> float | None:
        with self._lock:
            return self._ewma * 1e3 if self._ewma is not None else None

    def p95_ms(self) -> float | None:
        with self._lock:
            window = sorted(self._ring)
        if not window:
            return None
        return window[min(len(window) - 1, int(len(window) * 0.95))] * 1e3

    def sample_count(self) -> int:
        with self._lock:
            return self.count

    def reset(self) -> None:
        """Forget history (on readmission: the probation-era samples
        must not immediately re-eject a healed replica)."""
        with self._lock:
            self._ring.clear()
            self._ewma = None
            self.count = 0


class _ReplicaView:
    """The router's read model of one replica: breaker, local inflight,
    last scraped load."""

    def __init__(self, rid: str, breaker_failures: int, breaker_reset_s: float):
        self.rid = rid
        self.breaker = CircuitBreaker(
            name=f"fleet-{rid}",
            failure_threshold=breaker_failures,
            reset_timeout_s=breaker_reset_s,
        )
        # += on an attribute is load/add/store bytecodes, NOT atomic:
        # two handler threads can lose an increment while both
        # decrements land, driving the count negative and permanently
        # skewing least-loaded selection toward this replica.
        self._count_lock = threading.Lock()
        self.inflight = 0  # guarded by: self._count_lock
        self.queue_depth = 0.0
        self.scraped_inflight = 0.0
        self.shed_rate = 0.0
        self._last_shed_total: float | None = None
        self.scrape_ok = True
        # Monotonic time of the last SUCCESSFUL scrape: `GET /fleet`
        # serves its age so a stale scrape (wedged or unreachable
        # replica) is distinguishable from a healthy idle one whose
        # numbers just happen to sit at zero.
        self.last_scrape_mono: float | None = None
        # Scraped hops_tpu_workload_capture_active: `GET /fleet`
        # reports which replica processes are capturing their streams.
        self.capture_active = 0.0
        # Gray-failure state: forward latencies feed the EWMA/p95; a
        # latency outlier moves to PROBATION (unroutable, distinct
        # from breaker-open) until shadow probes heal it.
        self.latency = _LatencyStats()
        self.probation = False
        self.probation_since: float | None = None
        self.probe_oks = 0
        self.last_probe_mono = 0.0

    def inflight_inc(self) -> None:
        with self._count_lock:
            self.inflight += 1

    def inflight_dec(self) -> None:
        with self._count_lock:
            self.inflight -= 1

    def score(self) -> float:
        with self._count_lock:
            inflight = self.inflight
        s = inflight + self.queue_depth + self.scraped_inflight \
            + self.shed_rate
        if not self.scrape_ok:
            s += 1.0  # deprioritize a replica we cannot see into
        return s


class _HedgeRace:
    """First-response-wins coordination between a primary forward and
    its hedge. Attempts ``post()`` their outcome; the first *terminal*
    one (kind ``"ok"``) becomes the winner, later posts learn they were
    abandoned (``post`` returns False) and skip all breaker/retry
    bookkeeping."""

    def __init__(self) -> None:
        self._cv = threading.Condition()
        self._winner: tuple | None = None  # guarded by: self._cv
        self._failures: list[tuple] = []  # guarded by: self._cv
        self._launched = 0  # guarded by: self._cv
        self._finished = 0  # guarded by: self._cv

    def register_launch(self) -> None:
        with self._cv:
            self._launched += 1

    def post(self, outcome: tuple) -> bool:
        """Record an attempt's outcome; True = this post was LIVE (no
        winner existed yet — its bookkeeping counts)."""
        with self._cv:
            self._finished += 1
            live = self._winner is None
            if live and outcome[0] == "ok":
                self._winner = outcome
            elif live and outcome[0] == "fail":
                self._failures.append(outcome)
            self._cv.notify_all()
            return live

    def wait(self, timeout: float | None) -> tuple | None:
        """Block until a winner exists or every launched attempt has
        finished (or ``timeout``); returns the winner if any."""
        with self._cv:
            self._cv.wait_for(
                lambda: self._winner is not None
                or (self._launched > 0 and self._finished >= self._launched),
                timeout=timeout,
            )
            return self._winner

    def settled(self) -> bool:
        with self._cv:
            return self._finished >= self._launched

    def last_failure(self) -> tuple | None:
        with self._cv:
            return self._failures[-1] if self._failures else None


class Router:
    """The fleet's front HTTP server (``POST /predict``).

    ``manager`` needs only ``.name`` and ``.replicas()`` returning
    objects with ``rid`` / ``port`` / ``state`` — the real
    :class:`~hops_tpu.modelrepo.fleet.replicas.ReplicaManager` in
    production, a stub in router unit tests.
    """

    def __init__(
        self,
        manager: Any,
        *,
        rate_limits: dict[str, dict[str, float]] | None = None,
        class_limits: dict[str, dict[str, float]] | None = None,
        scrape_interval_s: float = 0.25,
        forward_timeout_s: float = 30.0,
        max_attempts: int | None = None,
        breaker_failures: int = 3,
        breaker_reset_s: float = 5.0,
        hedge: HedgePolicy | dict[str, Any] | None = None,
        ejection: EjectionPolicy | dict[str, Any] | None = None,
        brownout: qos.BrownoutPolicy | dict[str, Any] | None = None,
        attempt_workers: int = 128,
        probe_workload: Any = None,
        port: int = 0,
        clock=time.monotonic,
    ):
        self.manager = manager
        self.name = manager.name
        #: Capture/synthesis artifact dir (telemetry.workload) whose
        #: recorded requests become SYNTHETIC shadow-probe bodies: a
        #: probation replica on a quiet fleet would otherwise never be
        #: probed again (probes piggyback on live traffic) and sit
        #: ejected forever. None = live-traffic probes only.
        self.probe_workload = probe_workload
        self._probe_bodies: list[bytes] | None = None  # lazy; [] = unusable
        self._probe_body_idx = 0
        self.scrape_interval_s = scrape_interval_s
        self.forward_timeout_s = forward_timeout_s
        self.max_attempts = max_attempts
        self.breaker_failures = breaker_failures
        self.breaker_reset_s = breaker_reset_s
        self.limiter = TenantRateLimiter(rate_limits, clock=clock)
        # Per-QoS-class token buckets: a flooded batch class runs out of
        # tokens while interactive traffic keeps flowing — the first
        # shed-lowest-first layer, ahead of any replica capacity.
        self._class_buckets: dict[str, TokenBucket] = {
            cls: TokenBucket(spec["rate_rps"],
                             spec.get("burst", spec["rate_rps"]), clock=clock)
            for cls, spec in (class_limits or {}).items()
            if spec.get("rate_rps")
        }
        if isinstance(hedge, dict):
            hedge = HedgePolicy(**hedge)
        self.hedge = hedge if hedge is not None else HedgePolicy(enabled=False)
        if isinstance(ejection, dict):
            ejection = EjectionPolicy(**ejection)
        self.ejection = (
            ejection if ejection is not None else EjectionPolicy(enabled=False))
        if isinstance(brownout, dict):
            brownout = qos.BrownoutPolicy(**brownout)
        self._brownout = (
            qos.BrownoutController(brownout) if brownout is not None else None)
        self._m_brownout = _m_brownout.labels(model=self.name)
        self._m_probation = _m_probation.labels(model=self.name)
        # Hedge budget: tokens accrue per routed request, capped —
        # guarded by: self._hedge_lock.
        self._hedge_lock = threading.Lock()
        self._hedge_tokens = self.hedge.budget_burst
        #: Keep-alive connection pool for every router->replica hop
        #: (forwards, hedges, scrapes, shadow probes): a hedge must not
        #: pay a fresh handshake on top of the latency it is rescuing.
        self.pool = HTTPPool(identity="router")
        # Worker pools for raced attempts (a thread per forward would
        # be creation churn at request rate; lazily built because
        # un-hedged routers never race attempts). Hedges get their OWN
        # small pool: under a load spike that saturates the primary
        # pool, the rescue path must not queue behind the very
        # primaries it exists to rescue.
        self.attempt_workers = int(attempt_workers)
        self._attempt_pool = None  # guarded by: self._hedge_lock
        self._hedge_pool = None  # guarded by: self._hedge_lock
        self._scrape_pool = None  # guarded by: self._hedge_lock
        self._views_lock = threading.Lock()
        self._views: dict[str, _ReplicaView] = {}  # guarded by: self._views_lock
        self._rr = 0  # guarded by: self._views_lock
        self._lat_lock = threading.Lock()
        self._latencies: list[float] = []  # guarded by: self._lat_lock
        # Per-QoS-class rolling windows (guarded by: self._lat_lock).
        self._class_latencies: dict[str, list[float]] = {}
        # Periodic bucket-count snapshots of the per-class SLO
        # histogram; histogram_p99_ms() takes deltas against the oldest
        # in-window snapshot.
        self._hist_lock = threading.Lock()
        self._hist_ring: collections.deque = collections.deque(maxlen=64)  # guarded by: self._hist_lock
        self._stop = threading.Event()
        name = self.name
        router = self

        m_requests = _m_requests.labels(model=name)
        m_unrouted = _m_unrouted.labels(model=name)

        def _reply(code: int, body: dict[str, Any] | bytes,
                   headers: dict[str, str] | None = None):
            # Relay path hands bytes straight through (zero-copy:
            # the replica's serialized body is the response);
            # router-authored payloads (errors, /fleet) are dicts.
            # A relayed byte body keeps the REPLICA's declared
            # Content-Type (route() passes it through) — stamping
            # application/json on, say, an HTML error page from the
            # replica's HTTP stack would lie to the client; only
            # Content-Length is always recomputed (by the transport
            # core's assemble()).
            # bytes bodies (incl. packed frames) relay untouched; only
            # the router's OWN dict responses serialize as JSON here.
            data = body if isinstance(body, bytes) \
                else json.dumps(body).encode()  # graftlint: disable=json-on-hot-wire
            hdrs = dict(headers or {})
            ctype = hdrs.pop("Content-Type", "application/json")
            out = {"Content-Type": ctype}
            out.update(hdrs)
            return code, out, data

        def _do_get(path_full: str, headers: Any):
            try:
                resp = telemetry_export.metrics_response(path_full)
                if resp is None:
                    # Debug surfaces on the router's own port: ITS span
                    # ring (for in-process fleets this includes replica
                    # spans — one shared ring) and flight recorder.
                    resp = telemetry_export.debug_response(path_full)
                if resp is not None:
                    return resp
                path = path_full.rstrip("/")
                if path == "/healthz":
                    ready = router.routable()
                    if ready:
                        return _reply(200, {"status": "ok",
                                            "ready_replicas": len(ready)})
                    return _reply(503, {"status": "unready",
                                        "ready_replicas": 0},
                                  headers={"Retry-After": "1"})
                if path == "/fleet":
                    return _reply(200, router.describe())
                return _reply(404, {"error": f"unknown path {path_full}"})
            except Exception as e:  # noqa: BLE001 — server must stay up
                return _reply(500, {"error": f"{type(e).__name__}: {e}"})

        def _do_post(path_full: str, headers: Any, body_in: bytes):
            # Workload capture stamps the fleet-front-door ARRIVAL
            # — the recorded stream is what clients sent, with
            # rate-limited, unrouted, and handler-crash outcomes
            # included (their status IS the outcome). Defined
            # before any work so the outer except can record the
            # 500s it answers.
            t_arr_mono, t_arr_wall = time.monotonic(), time.time()
            body = body_in or b"{}"
            state = {"is_predict": False}

            def capture(status: int, tspan: Any = None) -> None:
                if not (state["is_predict"] and workload.capturing()):
                    return
                # Format-aware lazy parse: the relay never decoded the
                # body, so the summarizer must sniff the framing. A
                # packed body gets a header-only shape summary — armed
                # capture on a packed-body fleet records shapes, it
                # does not log a JSON decode warning per request.
                payload_obj, wire_format, summary = None, "json", None
                if wirecodec.is_packed(body):
                    wire_format = "packed"
                    try:
                        fs = wirecodec.frame_summary(body)
                    except wirecodec.WireCodecError:
                        fs = {"bytes": len(body), "format": "packed"}
                    summary = {"bytes": fs["bytes"]}
                    tensor = next(
                        (c for c in fs.get("columns", ())
                         if c.get("name") == "instances" and "shape" in c),
                        None)
                    if tensor is not None:
                        shape = tensor["shape"]
                        summary["instances"] = shape[0] if shape else 1
                        summary["instance"] = {"kind": "list",
                                               "shape": shape[1:]}
                        summary["dtype"] = tensor["dtype"]
                else:
                    try:
                        # Capture is the relay's one lazy-parse
                        # consumer: runs post-reply, only while armed,
                        # and only on non-packed bodies.
                        payload_obj = json.loads(body)  # graftlint: disable=json-on-hot-wire
                    except ValueError:
                        payload_obj = None
                workload.record_request(
                    surface="router",
                    endpoint=name,
                    path=path_full.rstrip("/"),
                    tenant=headers.get("X-Tenant"),
                    payload=payload_obj,
                    instances=(
                        payload_obj.get("instances")
                        if isinstance(payload_obj, dict) else None
                    ),
                    status=status,
                    latency_ms=(time.monotonic() - t_arr_mono) * 1e3,
                    trace_id=(
                        tspan.trace_id
                        if getattr(tspan, "sampled", False) else None
                    ),
                    t_mono=t_arr_mono,
                    t_wall=t_arr_wall,
                    wire_format=wire_format,
                    payload_summary=summary,
                )

            def done(resp, tspan: Any = None,
                     probe_headers: dict[str, str] | None = None):
                # Capture and shadow probes run as the route's `after`
                # callback — after the reply is queued for write, so
                # neither may delay the response.
                code = resp[0]

                def after() -> None:
                    capture(code, tspan)
                    if probe_headers is not None:
                        router._maybe_shadow_probe(body, probe_headers)

                return resp[0], resp[1], resp[2], after

            try:
                path = path_full.rstrip("/")
                if path.startswith("/admin/capture/"):
                    # Workload-capture control plane on the fleet's
                    # front door (status: GET /debug/workload).
                    try:
                        admin_payload = json.loads(body)  # graftlint: disable=json-on-hot-wire
                    except ValueError:
                        admin_payload = {}
                    return _reply(*workload.admin_action(path, admin_payload))
                if path not in ("/predict", f"/v1/models/{name}:predict"):
                    return _reply(404, {"error": f"unknown path {path_full}"})
                state["is_predict"] = True
                m_requests.inc()
                tenant = headers.get("X-Tenant", "default")
                wait = router.limiter.acquire(tenant)
                if wait > 0:
                    _m_rate_limited.inc(
                        tenant=router.limiter.label_for(tenant))
                    return done(_reply(
                        429,
                        {"error": f"tenant {tenant!r} rate limited"},
                        headers={"Retry-After": f"{math.ceil(wait)}"},
                    ))
                # QoS class: tenant config is authoritative; the
                # untrusted header can only demote relative to it.
                priority = qos.parse_priority(
                    headers.get(qos.PRIORITY_HEADER),
                    router.limiter.priority_for(tenant),
                )
                # Brownout shed BEFORE the class bucket is charged:
                # a request that will be refused anyway must not
                # drain batch tokens — the bucket would sit empty
                # when the brownout lifts, turning recovery into a
                # burst of spurious 429s.
                if (router.brownout_level >= qos.SHED
                        and qos.rank(priority) > 0):
                    # Brownout shed: the lowest class yields first
                    # so the interactive SLO survives the burn.
                    _m_qos_shed.inc(model=name, priority=priority,
                                    reason="brownout")
                    return done(_reply(
                        503,
                        {"error": f"{priority} traffic shed "
                                  "(brownout; SLO burn)"},
                        headers={"Retry-After": "1"},
                    ))
                cwait = router._class_acquire(priority)
                if cwait > 0:
                    _m_qos_shed.inc(model=name, priority=priority,
                                    reason="rate")
                    return done(_reply(
                        429,
                        {"error": f"{priority} class rate limited"},
                        headers={"Retry-After": f"{math.ceil(cwait)}"},
                    ))
                t0 = time.perf_counter()
                # The trace starts (or, with an incoming
                # `traceparent`, extends) at the fleet's front
                # door; every forward hop below becomes a child,
                # and the chosen sampling decision rides the
                # injected header to the replicas.
                debug = (headers.get(tracing.DEBUG_HEADER) or "")
                # The resolved class rides every forward (replicas
                # must not re-derive it from the untrusted client
                # header); a brownout level rides too so
                # subprocess replicas degrade with the fleet.
                relay_headers = {qos.PRIORITY_HEADER: priority}
                # Wire-format negotiation is end-to-end: the client's
                # Content-Type/Accept ride the relay verbatim so the
                # replica decides the framing (the router never decodes
                # the body either way).
                ctype = headers.get("Content-Type")
                if ctype:
                    relay_headers["Content-Type"] = ctype
                accept = headers.get("Accept")
                if accept:
                    relay_headers["Accept"] = accept
                if debug:
                    relay_headers[tracing.DEBUG_HEADER] = debug
                lvl = router.brownout_level
                if lvl > 0:
                    relay_headers[qos.BROWNOUT_HEADER] = str(lvl)
                # An explicit timeline ask force-samples: the
                # operator debugging a request must get the
                # breakdown whatever the ambient sample rate.
                tspan = tracing.start_trace(
                    "fleet.request", headers=headers, model=name,
                    force_sample=debug.strip().lower() == "timeline")
                with tspan:
                    with span("hops_tpu_fleet_request", model=name):
                        code, payload, rheaders = router.route(
                            body, extra_headers=relay_headers)
                    if debug.strip().lower() == "timeline":
                        # The ONE relay path that needs the object:
                        # the inline timeline merges the router's
                        # own spans into the replica's breakdown.
                        payload = router._merge_debug(payload, tspan)
                # Rolling window behind recent_p99_ms(): the
                # autoscaler's latency trigger reads this; the
                # per-class SLO histogram feeds histogram_p99_ms()
                # and the brownout controller.
                dt = time.perf_counter() - t0
                router.observe_latency(dt, priority=priority)
                _m_request_seconds.observe(
                    dt, model=name, priority=priority)
                if code >= 500:
                    m_unrouted.inc()
                return done(_reply(code, payload, headers=rheaders),
                            tspan, relay_headers)
            except Exception as e:  # noqa: BLE001 — server must stay up
                # A handler crash is a client-visible 500: it
                # belongs in the recorded error mix (capture()
                # never raises past the recorder's drop counter).
                return done(_reply(500, {"error": f"{type(e).__name__}: {e}"}))

        def handler_route(method: str, path: str, headers: Any, body: bytes):
            if method == "GET":
                return _do_get(path, headers)
            if method == "POST":
                return _do_post(path, headers, body)
            return _reply(404, {"error": f"unknown path {path}"})

        self._server = HTTPServer(
            handler_route, bind="127.0.0.1", port=port,
            name=f"fleet-router-{name}", workers=32)
        self._scraper = threading.Thread(
            target=self._scrape_loop, daemon=True,
            name=f"fleet-scraper-{name}",
        )
        self._scraper.start()
        log.info("fleet router for %s listening on 127.0.0.1:%d",
                 name, self.port)

    # -- views / telemetry scrape ---------------------------------------------

    @staticmethod
    def _rep_host(rep: Any) -> str:
        """Where a replica's serving port lives. Placed replicas carry
        their host's address; local (and duck-typed test) replicas
        default to loopback."""
        return getattr(rep, "host", None) or "127.0.0.1"

    def _view(self, rid: str) -> _ReplicaView:
        with self._views_lock:
            view = self._views.get(rid)
            if view is None:
                view = self._views[rid] = _ReplicaView(
                    rid, self.breaker_failures, self.breaker_reset_s)
            return view

    def _scrape_loop(self) -> None:
        interval = self.scrape_interval_s
        while not self._stop.wait(interval):
            try:
                self.scrape_once()
            except Exception:  # noqa: BLE001 — the scraper must survive
                log.exception("fleet %s: scrape cycle failed", self.name)
            try:
                self._eject_tick()
                self._brownout_tick()
                self._synthetic_probe_tick()
            except Exception:  # noqa: BLE001 — detectors must not kill the loop
                log.exception("fleet %s: gray-failure tick failed", self.name)

    def scrape_once(self) -> None:
        """One COALESCED pass over every routable replica's
        ``/metrics.json``: all scrapes fire concurrently through the
        shared keep-alive pool (one persistent connection per replica,
        reused every 0.25 s cycle — no re-dialing), so the pass's
        wall-time is the slowest replica, not the sum. Each scrape
        still runs under its own deadline and its own
        ``router.scrape`` fault point — a wedged or chaos-stalled
        replica fails ONLY its own scrape.

        Also prunes views whose replica no longer exists (reaped,
        killed, or failed): every rollout and autoscale churn mints
        fresh rids, so without this the ``_views`` dict — a breaker and
        counters per rid ever seen — grows for the router's lifetime.
        """
        reps = self.manager.replicas()
        live = {rep.rid for rep in reps}
        with self._views_lock:
            for rid in [r for r in self._views if r not in live]:
                del self._views[rid]
        targets = [rep for rep in reps
                   if rep.state in ("ready", "starting")
                   and rep.port is not None]
        if not targets:
            return
        if len(targets) == 1:
            snaps = [self._scrape_replica(
                self._rep_host(targets[0]), targets[0].port)]
        else:
            ex = self._scrape_executor()
            snaps = list(ex.map(
                lambda rep: self._scrape_replica(
                    self._rep_host(rep), rep.port),
                targets))
        for rep, snap in zip(targets, snaps):
            view = self._view(rep.rid)
            if snap is None:
                view.scrape_ok = False
                continue
            view.scrape_ok = True
            view.last_scrape_mono = time.monotonic()
            view.queue_depth = snap["queue_depth"]
            view.scraped_inflight = snap["inflight"]
            view.capture_active = snap["capture_active"]
            shed = snap["shed_total"]
            if view._last_shed_total is not None:
                view.shed_rate = max(0.0, shed - view._last_shed_total)
            view._last_shed_total = shed

    def _scrape_executor(self):
        from concurrent.futures import ThreadPoolExecutor

        with self._hedge_lock:
            if self._scrape_pool is None:
                self._scrape_pool = ThreadPoolExecutor(
                    max_workers=8,
                    thread_name_prefix=f"fleet-scrape-{self.name}",
                )
            return self._scrape_pool

    #: The only families the routing score reads — the scrape asks the
    #: replica for exactly these, so each poll renders and parses a
    #: four-family view instead of the replica's full registry snapshot
    #: (which grows with every instrumented subsystem).
    _SCRAPE_FAMILIES = (
        "hops_tpu_serving_batch_queue_depth",
        "hops_tpu_serving_inflight",
        "hops_tpu_serving_shed_total",
        "hops_tpu_workload_capture_active",
    )

    def _scrape_replica(self, host: str,
                        port: int) -> dict[str, float] | None:
        timeout = max(0.5, self.scrape_interval_s * 2)

        def fetch() -> tuple[int, bytes, dict[str, str]]:
            # Chaos point: latency here models a gray metrics path.
            faultinject.fire("router.scrape", key=port)
            return self.pool.request(
                "GET",
                f"http://{host}:{port}/metrics.json"
                f"?families={','.join(self._SCRAPE_FAMILIES)}",
                timeout_s=timeout,
            )

        try:
            # The WHOLE fetch runs under the deadline (not just the
            # socket): a wedged scrape path — injected or real — makes
            # this scrape fail, the view goes stale (deprioritized by
            # score, age surfaced on GET /fleet), and routing itself
            # never stalls. DeadlineExceeded is a TimeoutError, which
            # the OSError arm catches.
            code, raw, _ = with_deadline(fetch, timeout, op="router.scrape")
            if code != 200:
                return None
            # Metrics scrape of a replica's /metrics — telemetry
            # control plane, not the request/response data wire.
            families = json.loads(raw).get("metrics", {})  # graftlint: disable=json-on-hot-wire
        except (OSError, ValueError, RuntimeError):
            return None

        def gauge(family: str) -> float:
            rows = families.get(family, {}).get("samples", [])
            return float(sum(
                r["value"] for r in rows
                if r["labels"].get("model", self.name) == self.name
                and not r.get("suffix")
            ))

        def counter(family: str) -> float:
            rows = families.get(family, {}).get("samples", [])
            return float(sum(
                r["value"] for r in rows
                if r["labels"].get("model", self.name) == self.name
            ))

        return {
            "queue_depth": gauge("hops_tpu_serving_batch_queue_depth"),
            "inflight": gauge("hops_tpu_serving_inflight"),
            "shed_total": counter("hops_tpu_serving_shed_total"),
            "capture_active": gauge("hops_tpu_workload_capture_active"),
        }

    # -- selection / forwarding -----------------------------------------------

    def routable(self) -> list[Any]:
        """Replicas a request may go to right now: ready, with a port,
        breaker not open, not in latency probation."""
        out = []
        for rep in self.manager.replicas():
            if rep.state != "ready" or rep.port is None:
                continue
            view = self._view(rep.rid)
            if view.breaker.state == "open" or view.probation:
                continue
            out.append(rep)
        return out

    def pick(self, exclude: set[str] = frozenset()) -> Any | None:
        """Least-loaded routable replica not in ``exclude``."""
        candidates = [r for r in self.routable() if r.rid not in exclude]
        if not candidates:
            return None
        with self._views_lock:
            self._rr += 1
            rr = self._rr
        scored = sorted(
            (self._view(r.rid).score(), (rr + i) % len(candidates), i)
            for i, r in enumerate(candidates)
        )
        return candidates[scored[0][2]]

    def route(
        self, body: bytes, extra_headers: dict[str, str] | None = None
    ) -> tuple[int, dict[str, Any] | bytes, dict[str, str]]:
        """Forward ``body`` to the best replica, retrying the next-best
        on transport failure / replica 5xx / shed-503 until attempts or
        replicas run out. Returns ``(status, payload, headers)`` where
        ``payload`` is the replica's response body as VERBATIM bytes —
        the zero-copy relay contract: the forward path never parses or
        re-serializes either body (routing needs only the status code
        and headers), so 2xx and 4xx/5xx alike reach the client
        byte-for-byte as the replica sent them. Only the router's own
        no-replica 503 is a dict (it authored it).

        With hedging enabled (and latency data + budget available),
        each attempt may race a second forward at the next-best replica
        after the adaptive timer: first response wins, the loser is
        abandoned — it still finishes on its own thread (latency
        recorded: an abandoned-slow completion is exactly the gray
        signal the ejector wants) but never strikes a breaker, never
        counts a retry, and never double-answers the client.

        Tracing: each forward attempt is a ``fleet.forward`` child span
        of the caller's active trace, tagged with the replica id, the
        attempt index, and the replica breaker's state at selection
        time — so retries read as SIBLING hops under one request, and
        the ``traceparent`` injected on the wire makes the replica's
        own ``serving.request`` span a child of the hop that reached
        it. Hedge attempts additionally carry ``hedge=True``."""
        attempts = self.max_attempts or max(3, len(self.manager.replicas()) + 1)
        hedging = self.hedge.enabled
        if hedging:
            self._hedge_accrue()
        tried: set[str] = set()
        last: tuple[int, Any, dict[str, str]] | None = None
        for attempt in range(attempts):
            rep = self.pick(exclude=tried)
            if rep is None:
                break
            tried.add(rep.rid)
            view = self._view(rep.rid)
            if not view.breaker.allow():
                continue  # raced open, or half-open probe budget spent
            delay = self._hedge_delay_s() if hedging else None
            if delay is None:
                kind, code, payload, headers = self._attempt_sync(
                    rep, view, body, extra_headers, attempt)
            else:
                kind, code, payload, headers = self._attempt_hedged(
                    rep, view, body, extra_headers, attempt, tried, delay)
            if kind == "ok":
                return code, payload, headers
            if kind == "fail":
                last = (code, payload, headers)
            # kind == "transport": unanswered — retry invisible to the
            # client beyond latency.
        if last is not None:
            return last
        return (
            503,
            {"error": f"no routable replicas for {self.name!r}"},
            {"Retry-After": "1"},
        )

    # -- attempt machinery ----------------------------------------------------
    #
    # Outcome kinds: "ok" = terminal, relay to the client (2xx and
    # plain 4xx alike); "fail" = answered but retryable (shed-503/429,
    # replica 5xx, superseded-generation 410) — remembered as `last`,
    # retried elsewhere;
    # "transport" = never answered, retried with nothing client-visible.

    @staticmethod
    def _classify(code: int) -> str:
        if code < 400:
            return "ok"
        if code in (410, 429, 503) or code >= 500:
            # 410 is the fencing refusal: a superseded-generation unit
            # (zombie healed from a partition) typed-rejected the
            # forward — answer is per-replica, so retry elsewhere.
            return "fail"
        return "ok"  # other 4xx: the client's request is bad everywhere

    def _account_live(self, view: _ReplicaView, code: int) -> None:
        """Breaker/retry bookkeeping for a LIVE (non-abandoned) answered
        attempt — abandoned hedge losers never reach this."""
        if code < 400:
            view.breaker.record_success()
        elif code == 410:
            # Superseded-generation refusal: placement identity, not
            # replica health — the unit is a fenced zombie doing
            # exactly its job. No breaker strike; the route loop
            # retries on the live generation, and reconcile() reaps
            # the zombie.
            _m_retries.inc(model=self.name, reason="generation")
            flight.record("retry", op="router.forward",
                          reason="generation", replica=view.rid,
                          model=self.name)
        elif code in (429, 503):
            # Shedding/draining: load, not failure. Don't strike the
            # breaker; the route loop tries a less-loaded replica.
            _m_retries.inc(model=self.name, reason="shed")
            flight.record("retry", op="router.forward", reason="shed",
                          replica=view.rid, model=self.name)
        elif code >= 500:
            view.breaker.record_failure()
            _m_retries.inc(model=self.name, reason="error")
            flight.record("retry", op="router.forward", reason="error",
                          replica=view.rid, model=self.name, status=code)

    def _account_transport(self, view: _ReplicaView, e: Exception) -> None:
        view.breaker.record_failure()
        _m_retries.inc(model=self.name, reason="connect")
        flight.record("retry", op="router.forward",
                      reason="connect", replica=view.rid,
                      model=self.name,
                      error=type(getattr(e, "reason", e)).__name__)

    def _attempt_sync(
        self, rep: Any, view: _ReplicaView, body: bytes,
        extra_headers: dict[str, str] | None, attempt: int,
    ) -> tuple[str, int, Any, dict[str, str]]:
        """One un-hedged forward attempt on the caller's thread."""
        _m_forwards.inc(model=self.name, replica=rep.rid)
        view.inflight_inc()
        fspan = tracing.child_span(
            "fleet.forward", replica=rep.rid, attempt=attempt,
            breaker=view.breaker.state,
        )
        t0 = time.perf_counter()
        try:
            with fspan:
                try:
                    # Chaos point. ANY armed error class models a
                    # transport failure on this hop (the catalog
                    # promises a retry, and the fault grammar defaults
                    # to RuntimeError) — only the real forward below
                    # narrows to transport exception types.
                    faultinject.fire("router.forward")
                except Exception as e:
                    raise urllib.error.URLError(e) from e
                code, payload, headers = self._forward(
                    self._rep_host(rep), rep.port, body,
                    self._stamp_generation(rep, extra_headers))
                fspan.annotate(status=code)
        except (OSError, urllib.error.URLError) as e:
            # Transport failure: the replica is gone or wedged —
            # breaker strike, retry elsewhere.
            self._account_transport(view, e)
            return "transport", 0, None, {}
        finally:
            view.inflight_dec()
        view.latency.observe(time.perf_counter() - t0)
        self._account_live(view, code)
        return self._classify(code), code, payload, headers

    def _attempt_hedged(
        self, rep: Any, view: _ReplicaView, body: bytes,
        extra_headers: dict[str, str] | None, attempt: int,
        tried: set[str], delay: float,
    ) -> tuple[str, int, Any, dict[str, str]]:
        """One possibly-hedged attempt: the primary forward runs on a
        worker thread; if it is still unanswered after ``delay`` and
        the hedge budget allows, a second forward races it at the
        next-best replica. First terminal response wins."""
        race = _HedgeRace()
        ctx = tracing.current_context()
        self._launch_attempt(race, rep, view, body, extra_headers,
                             attempt, ctx, role="primary")
        if race.wait(delay) is None and not race.settled():
            hedge_rep = self.pick(exclude=tried)
            if hedge_rep is not None:
                hview = self._view(hedge_rep.rid)
                if not hview.breaker.allow():
                    pass  # raced open; the primary stands alone
                elif self._hedge_take():
                    tried.add(hedge_rep.rid)
                    flight.record("hedge", model=self.name,
                                  replica=hedge_rep.rid, primary=rep.rid,
                                  delay_ms=round(delay * 1e3, 2))
                    self._launch_attempt(
                        race, hedge_rep, hview, body, extra_headers,
                        attempt, ctx, role="hedge")
                else:
                    _m_hedges.inc(model=self.name, outcome="denied")
        winner = race.wait(None)  # bounded by forward_timeout_s per leg
        if winner is not None:
            return winner
        fail = race.last_failure()
        if fail is not None:
            return fail
        return "transport", 0, None, {}

    def _launch_attempt(
        self, race: "_HedgeRace", rep: Any, view: _ReplicaView,
        body: bytes, extra_headers: dict[str, str] | None, attempt: int,
        ctx: Any, role: str,
    ) -> None:
        race.register_launch()
        _m_forwards.inc(model=self.name, replica=rep.rid)
        # Inflight counts from the COORDINATOR thread, before the
        # worker exists: the score must see the attempt immediately.
        view.inflight_inc()

        def run() -> None:
            err: Exception | None = None
            code, payload, headers = 0, None, {}
            t0 = time.perf_counter()
            try:
                with tracing.use_context(ctx):
                    fspan = tracing.child_span(
                        "fleet.forward", replica=rep.rid, attempt=attempt,
                        breaker=view.breaker.state, hedge=(role == "hedge"),
                    )
                    try:
                        with fspan:
                            try:
                                faultinject.fire("router.forward")
                            except Exception as e:
                                raise urllib.error.URLError(e) from e
                            code, payload, headers = self._forward(
                                self._rep_host(rep), rep.port, body,
                                self._stamp_generation(rep, extra_headers))
                            fspan.annotate(status=code)
                    except (OSError, urllib.error.URLError) as e:
                        err = e
            finally:
                view.inflight_dec()
            if err is None:
                # Abandoned losers observe too: an abandoned-slow
                # completion is exactly the gray-latency signal the
                # ejection detector feeds on.
                view.latency.observe(time.perf_counter() - t0)
                outcome = (self._classify(code), code, payload, headers)
            else:
                outcome = ("transport", 0, None, {})
            live = race.post(outcome)
            if live:
                # The race was undecided when this attempt landed: it
                # carries normal breaker/retry semantics.
                if err is not None:
                    self._account_transport(view, err)
                else:
                    self._account_live(view, code)
                if role == "hedge":
                    _m_hedges.inc(
                        model=self.name,
                        outcome="won" if outcome[0] == "ok" else "lost")
            else:
                # Abandoned loser: no breaker strike, no retry counter
                # — slow is not down, and the client was already
                # answered by the winner.
                if role == "hedge":
                    _m_hedges.inc(model=self.name, outcome="lost")

        self._attempt_executor(role).submit(run)

    def _attempt_executor(self, role: str):
        from concurrent.futures import ThreadPoolExecutor

        with self._hedge_lock:
            if role == "hedge":
                if self._hedge_pool is None:
                    # Sized by the budget: hedges are <= ~5% of
                    # traffic, so a quarter of the primary pool is
                    # already generous headroom.
                    self._hedge_pool = ThreadPoolExecutor(
                        max_workers=max(8, self.attempt_workers // 4),
                        thread_name_prefix=f"fleet-hedge-{self.name}",
                    )
                return self._hedge_pool
            if self._attempt_pool is None:
                self._attempt_pool = ThreadPoolExecutor(
                    max_workers=self.attempt_workers,
                    thread_name_prefix=f"fleet-attempt-{self.name}",
                )
            return self._attempt_pool

    def _stamp_generation(
        self, rep: Any, extra_headers: dict[str, str] | None,
    ) -> dict[str, str] | None:
        """Fencing stamp (docs/operations.md "Partition tolerance &
        fencing"): forwards to a PLACED replica carry its slot's
        CURRENT generation — deliberately the placement client's live
        counter, not the unit snapshot, so once reconcile() bumps the
        slot every forward that still reaches the old unit presents
        the newer token and the zombie typed-rejects it (410)."""
        unit = getattr(rep, "unit", None)
        placement = getattr(self.manager, "placement", None)
        if (unit is None or placement is None
                or getattr(unit, "slot", None) is None):
            return extra_headers
        gen = placement.current_generation(unit.slot)
        return {**(extra_headers or {}),
                "X-Hops-Generation": f"{unit.slot}:{gen}"}

    def _forward(
        self, host: str, port: int, body: bytes,
        extra_headers: dict[str, str] | None = None,
    ) -> tuple[int, bytes, dict[str, str]]:
        headers = {"Content-Type": "application/json", **(extra_headers or {})}
        # Propagate the trace across the process boundary: the active
        # span here is this hop's fleet.forward, so the replica's
        # serving.request parents to exactly the hop that reached it.
        tracing.inject_headers(headers)
        # Persistent-connection pool: no per-hop handshake, and 4xx/5xx
        # come back as data (the zero-copy relay treats status codes as
        # routing input, never exceptions). Bodies stay raw bytes.
        code, data, resp_headers = self.pool.request(
            "POST",
            f"http://{host}:{port}/v1/models/{self.name}:predict",
            body=body, headers=headers, timeout_s=self.forward_timeout_s,
        )
        if code >= 400 and not data:
            return (
                code,
                # Synthesized error body for an empty upstream error —
                # errors are spec'd JSON regardless of negotiation.
                json.dumps({"error": f"replica answered {code}"}).encode(),  # graftlint: disable=json-on-hot-wire
                _relay_headers(resp_headers),
            )
        return code, data, _relayed_with_ctype(resp_headers)

    def _merge_debug(
        self, payload: dict[str, Any] | bytes, tspan: Any
    ) -> dict[str, Any] | bytes:
        """Fold the router's own spans for this trace into the inline
        timeline a replica returned under ``X-Hops-Debug: timeline``
        (dedup by span id: with in-process replicas the shared ring
        already holds the replica's spans). The one relay path that
        parses the relayed bytes — the operator asked for the merged
        object. A non-JSON body relays untouched."""
        if isinstance(payload, bytes):
            raw = payload
            if wirecodec.is_packed(raw):
                # A packed frame carries no debug dict by design
                # (replicas answer timeline asks in JSON); relay the
                # frame untouched rather than mis-parse it.
                return raw
            try:
                # graftlint: disable=json-on-hot-wire — the one relay
                # path spec'd to parse: the operator asked for the
                # merged timeline object.
                parsed = json.loads(payload)
            except ValueError:
                return raw
            if not isinstance(parsed, dict):
                # Valid JSON but not an object (list/scalar): nothing
                # to merge into — relay the ORIGINAL bytes, not a
                # re-serialization of the parse.
                return raw
            payload = parsed
        if not isinstance(payload, dict):
            return payload
        dbg = payload.setdefault("debug", {})
        rows = {r["span_id"]: r for r in dbg.get("timeline", [])
                if isinstance(r, dict) and "span_id" in r}
        for r in tracing.timeline(tspan):
            rows.setdefault(r["span_id"], r)
        merged = sorted(rows.values(), key=lambda r: r.get("start", 0.0))
        if merged:
            dbg["timeline"] = merged
            dbg.setdefault("trace_id", merged[0].get("trace_id"))
        return payload

    # -- hedging --------------------------------------------------------------

    def _class_acquire(self, priority: str) -> float:
        bucket = self._class_buckets.get(priority)
        return bucket.acquire() if bucket is not None else 0.0

    def _hedge_accrue(self) -> None:
        with self._hedge_lock:
            self._hedge_tokens = min(
                self.hedge.budget_burst,
                self._hedge_tokens + self.hedge.budget_frac)

    def _hedge_take(self) -> bool:
        with self._hedge_lock:
            if self._hedge_tokens >= 1.0:
                self._hedge_tokens -= 1.0
                return True
            return False

    def _hedge_delay_s(self) -> float | None:
        """The adaptive hedge timer: the MEDIAN across replicas of each
        replica's recent-latency p95, clamped to the policy bounds. The
        median (not a merged-window p95) is what keeps one gray replica
        from inflating the very timer that defends against it. None
        until the fleet has ``min_samples`` observations — hedging from
        no data is a guess."""
        p95s: list[float] = []
        total = 0
        for rep in self.manager.replicas():
            if rep.state != "ready":
                continue
            view = self._view(rep.rid)
            n = view.latency.sample_count()
            if n >= 8:
                p = view.latency.p95_ms()
                if p is not None:
                    p95s.append(p)
                    total += n
        if not p95s or total < self.hedge.min_samples:
            return None
        delay = statistics.median(p95s) / 1e3
        return min(max(delay, self.hedge.delay_floor_s),
                   self.hedge.delay_cap_s)

    # -- gray-failure ejection / probation ------------------------------------

    def _healthy_median_ms(self) -> float | None:
        """Median latency EWMA across non-probation ready replicas —
        the reference a probe result is judged against."""
        vals: list[float] = []
        for rep in self.manager.replicas():
            if rep.state != "ready" or rep.port is None:
                continue
            view = self._view(rep.rid)
            if view.probation or view.latency.sample_count() < 4:
                continue
            e = view.latency.ewma_ms
            if e is not None:
                vals.append(e)
        return statistics.median(vals) if vals else None

    def _eject_tick(self) -> None:
        """One ejection pass (scrape-loop cadence): compare every ready
        replica's latency EWMA to the median of its PEERS (excluding
        itself — a 2-replica fleet must still see the gray one) and
        move outliers to probation, capped so the detector can never
        empty the fleet."""
        pol = self.ejection
        if not pol.enabled:
            return
        ready = [r for r in self.manager.replicas()
                 if r.state == "ready" and r.port is not None]
        views = [self._view(r.rid) for r in ready]
        in_probation = sum(1 for v in views if v.probation)
        candidates = []
        for v in views:
            if v.probation or v.latency.sample_count() < pol.min_samples:
                continue
            e = v.latency.ewma_ms
            if e is not None:
                candidates.append((v, e))
        if len(candidates) >= 2:
            max_ejected = min(
                len(views) - 1, int(len(views) * pol.max_ejected_frac))
            for view, ewma in sorted(candidates, key=lambda t: -t[1]):
                if in_probation >= max_ejected:
                    break
                peers = [e for v, e in candidates if v is not view]
                med = statistics.median(peers)
                if ewma > max(pol.factor * med, pol.floor_ms):
                    view.probation = True
                    view.probation_since = time.monotonic()
                    view.probe_oks = 0
                    view.last_probe_mono = 0.0
                    in_probation += 1
                    _m_ejections.inc(model=self.name)
                    flight.record("replica_ejected", model=self.name,
                                  replica=view.rid, ewma_ms=round(ewma, 1),
                                  peer_median_ms=round(med, 1))
                    log.warning(
                        "fleet %s: ejected %s into latency probation "
                        "(ewma %.1f ms vs peer median %.1f ms)",
                        self.name, view.rid, ewma, med)
        self._m_probation.set(
            sum(1 for v in views if v.probation))

    def _probe_body_pool(self) -> list[bytes]:
        """Synthetic probe bodies from the ``probe_workload`` artifact,
        materialized lazily on the first probation that needs one: up to
        32 captured requests, deterministically re-materialized
        (``materialize_payload`` seed 0 — the same bodies across router
        restarts). An unusable artifact logs once and leaves the pool
        empty; live-traffic probes keep working."""
        if self._probe_bodies is None:
            bodies: list[bytes] = []
            if self.probe_workload is not None:
                try:
                    from hops_tpu.telemetry.workload import (
                        load_artifact, materialize_payload)

                    art = load_artifact(self.probe_workload)
                    for rec in art["records"][:32]:
                        # Shadow probes are spec'd JSON: they exercise
                        # the replica's default (negotiation-free) path.
                        bodies.append(json.dumps(  # graftlint: disable=json-on-hot-wire
                            materialize_payload(rec, seed=0)
                        ).encode())
                except Exception:  # noqa: BLE001 — probes are optional
                    log.exception(
                        "fleet %s: probe_workload %s unusable — "
                        "synthetic probes disabled",
                        self.name, self.probe_workload)
            self._probe_bodies = bodies
        return self._probe_bodies

    def _synthetic_probe_tick(self) -> None:
        """Scrape-loop hook: probation replicas on a QUIET fleet get
        shadow probes with synthetic bodies from the captured-workload
        pool — without this, probes only piggyback on live requests and
        a zero-traffic probation is a life sentence. The per-view probe
        cadence inside :meth:`_maybe_shadow_probe` dedups against live
        traffic: a busy router's probation views are already inside
        their probe interval, so this tick fires nothing extra."""
        if not self.ejection.enabled or self.probe_workload is None:
            return
        with self._views_lock:
            if not any(v.probation for v in self._views.values()):
                return
        pool = self._probe_body_pool()
        if not pool:
            return
        body = pool[self._probe_body_idx % len(pool)]
        self._probe_body_idx += 1
        self._maybe_shadow_probe(body, None, synthetic=True)

    def _maybe_shadow_probe(
        self, body: bytes, extra_headers: dict[str, str] | None,
        synthetic: bool = False,
    ) -> None:
        """Probation replicas are re-judged with SHADOW traffic: a copy
        of a live (idempotent) request, fired after the real reply went
        out, response discarded. Probe cadence per replica is
        ``probe_interval_s``."""
        if not self.ejection.enabled:
            return
        now = time.monotonic()
        for rep in self.manager.replicas():
            if rep.state != "ready" or rep.port is None:
                continue
            view = self._view(rep.rid)
            if not view.probation:
                continue
            if now - view.last_probe_mono < self.ejection.probe_interval_s:
                continue
            view.last_probe_mono = now
            if synthetic:
                _m_synthetic_probes.inc(model=self.name)
            threading.Thread(
                target=self._shadow_probe, args=(rep, view, body,
                                                 extra_headers),
                daemon=True, name=f"fleet-probe-{self.name}-{rep.rid}",
            ).start()

    def _shadow_probe(
        self, rep: Any, view: _ReplicaView, body: bytes,
        extra_headers: dict[str, str] | None,
    ) -> None:
        headers = {"Content-Type": "application/json", **(extra_headers or {})}
        t0 = time.perf_counter()
        try:
            code, _, _ = self.pool.request(
                "POST",
                f"http://{self._rep_host(rep)}:{rep.port}"
                f"/v1/models/{self.name}:predict",
                body=body, headers=headers,
                timeout_s=self.ejection.probe_timeout_s,
            )
        except OSError:
            view.probe_oks = 0  # still unreachable — stay in probation
            return
        dt_ms = (time.perf_counter() - t0) * 1e3
        view.latency.observe(dt_ms / 1e3)
        ref = self._healthy_median_ms()
        limit = (
            self.ejection.readmit_factor * ref
            + self.ejection.readmit_slack_ms
            if ref is not None else None
        )
        if code < 500 and (limit is None or dt_ms <= limit):
            view.probe_oks += 1
        else:
            view.probe_oks = 0
        if view.probe_oks >= self.ejection.readmit_probes:
            view.probation = False
            view.probation_since = None
            view.probe_oks = 0
            # Forget the probation-era samples: the gray history must
            # not immediately re-eject a healed replica.
            view.latency.reset()
            _m_readmissions.inc(model=self.name)
            flight.record("replica_readmitted", model=self.name,
                          replica=rep.rid, probe_ms=round(dt_ms, 1))
            log.info("fleet %s: readmitted %s from probation "
                     "(probe %.1f ms)", self.name, rep.rid, dt_ms)

    # -- brownout / SLO signals -----------------------------------------------

    @property
    def brownout_level(self) -> int:
        return self._brownout.level if self._brownout is not None else 0

    def _brownout_tick(self) -> None:
        self._hist_snapshot_tick()
        if self._brownout is None:
            return
        p99 = self.histogram_p99_ms(priority=qos.PRIORITIES[0])
        if p99 is None:
            p99 = self.recent_p99_ms(priority=qos.PRIORITIES[0])
        prev = self._brownout.level
        level = self._brownout.observe(p99)
        if level != prev:
            flight.record(
                "brownout", model=self.name, level=level,
                p99_ms=None if p99 is None else round(p99, 1))
            log.warning(
                "fleet %s: brownout level %d -> %d (interactive p99 "
                "%s ms vs slo %.0f)", self.name, prev, level,
                "?" if p99 is None else f"{p99:.1f}",
                self._brownout.policy.slo_p99_ms)
        self._m_brownout.set(level)
        if level > 0:
            # Raise/refresh only, under THIS fleet's scope: a
            # co-hosted fleet's endpoints stay at full quality, and
            # level 0 arrives by TTL expiry so recovery never stomps
            # another controller's active brownout.
            qos.set_brownout(
                level, hold_s=max(1.0, 6 * self.scrape_interval_s),
                scope=self.name)

    def _hist_snapshot_tick(self) -> None:
        snap = {
            prio: _m_request_seconds.labels(
                model=self.name, priority=prio).snapshot()
            for prio in qos.PRIORITIES
        }
        with self._hist_lock:
            self._hist_ring.append((time.monotonic(), snap))

    def histogram_p99_ms(
        self, priority: str | None = None, window_s: float = 10.0,
        min_count: int = 20,
    ) -> float | None:
        """p99 estimated from the ``hops_tpu_fleet_latency_seconds``
        histogram's bucket deltas over the recent window — the SLO
        signal the autoscaler and the brownout controller read (None
        until enough observations land). Linear interpolation within
        the bucket; an overflow-bucket p99 reports the top bound (a
        lower bound on the truth, still a breach of any target below
        it)."""
        with self._hist_lock:
            ring = list(self._hist_ring)
        now = time.monotonic()
        base = None
        for t, snap in ring:
            if now - t <= window_s:
                base = snap  # oldest snapshot still inside the window
                break
        prios = [priority] if priority is not None else list(qos.PRIORITIES)
        bounds: tuple[float, ...] | None = None
        delta: list[int] | None = None
        total = 0
        for prio in prios:
            b, counts, n = _m_request_seconds.labels(
                model=self.name, priority=prio).snapshot()
            if base is not None and prio in base:
                base_counts, base_n = base[prio][1], base[prio][2]
            else:
                base_counts, base_n = [0] * len(counts), 0
            d = [c - bc for c, bc in zip(counts, base_counts)]
            bounds = b
            delta = d if delta is None else [x + y for x, y in zip(delta, d)]
            total += n - base_n
        if bounds is None or delta is None or total < min_count:
            return None
        target = 0.99 * total
        cum = 0
        lo = 0.0
        for i, c in enumerate(delta):
            hi = bounds[i] if i < len(bounds) else None
            cum += c
            if cum >= target:
                if hi is None:
                    return bounds[-1] * 1e3
                frac = (target - (cum - c)) / c if c else 1.0
                return (lo + frac * (hi - lo)) * 1e3
            if hi is not None:
                lo = hi
        return bounds[-1] * 1e3

    # -- surface --------------------------------------------------------------

    @property
    def port(self) -> int:
        return self._server.port

    @property
    def endpoint(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def breaker_state(self, rid: str) -> str:
        return self._view(rid).breaker.state

    def observe_latency(self, seconds: float,
                        priority: str | None = None) -> None:
        with self._lat_lock:
            self._latencies.append(seconds)
            if len(self._latencies) > 2048:
                del self._latencies[:1024]
            if priority is not None:
                lst = self._class_latencies.setdefault(priority, [])
                lst.append(seconds)
                if len(lst) > 2048:
                    del lst[:1024]

    def recent_p99_ms(self, priority: str | None = None) -> float | None:
        """p99 of the most recent window of router-observed latencies,
        optionally restricted to one QoS class (the autoscaler's
        fallback latency trigger; the primary signal is
        :meth:`histogram_p99_ms`)."""
        with self._lat_lock:
            src = (self._latencies if priority is None
                   else self._class_latencies.get(priority, []))
            window = list(src[-512:])
        if not window:
            return None
        window.sort()
        return window[min(len(window) - 1, int(len(window) * 0.99))] * 1e3

    def fleet_load(self) -> float | None:
        """Mean routing score per routable replica — the autoscaler's
        primary signal (None when nothing is routable)."""
        routable = self.routable()
        if not routable:
            return None
        return sum(self._view(r.rid).score() for r in routable) / len(routable)

    def describe(self) -> dict[str, Any]:
        reps = []
        now = time.monotonic()
        for rep in self.manager.replicas():
            view = self._view(rep.rid)
            ewma = view.latency.ewma_ms
            reps.append({
                "rid": rep.rid,
                "state": rep.state,
                "port": rep.port,
                "version": getattr(rep, "version", None),
                "score": round(view.score(), 3),
                "breaker": view.breaker.state,
                # Gray-failure state, DISTINCT from the breaker: a
                # probation replica answers 200s — it is slow, not
                # down — and heals by shadow probes, not half-open.
                "probation": view.probation,
                "latency_ewma_ms": (
                    round(ewma, 2) if ewma is not None else None),
                # How long the breaker has sat in that state, and how
                # stale the scraped load numbers are (None = never
                # scraped): without the ages a wedged replica whose
                # last scrape said "idle" is indistinguishable from a
                # healthy idle one.
                "breaker_state_age_s": round(view.breaker.state_age_s(), 3),
                "last_scrape_age_s": (
                    round(now - view.last_scrape_mono, 3)
                    if view.last_scrape_mono is not None else None
                ),
                # Scraped per-replica workload-capture status (for
                # in-process fleets every replica shares the router's
                # process-global recorder, so these agree).
                "capture": bool(view.capture_active),
            })
        with self._hedge_lock:
            hedge_tokens = self._hedge_tokens
        return {"model": self.name, "replicas": reps,
                "ready": sum(1 for r in reps if r["state"] == "ready"),
                "capture": workload.status(),
                "qos": {
                    "brownout_level": self.brownout_level,
                    "hedging": self.hedge.enabled,
                    "hedge_tokens": round(hedge_tokens, 3),
                    "ejection": self.ejection.enabled,
                    "probation": sum(
                        1 for r in reps if r.get("probation")),
                }}

    def stop(self) -> None:
        self._stop.set()
        self._server.stop()
        self._scraper.join(timeout=5)
        with self._hedge_lock:
            pools = [p for p in (self._attempt_pool, self._hedge_pool,
                                 self._scrape_pool)
                     if p is not None]
        for p in pools:
            # In-flight abandoned losers finish against the live pool;
            # waiting bounds teardown by the forward timeout instead of
            # racing socket close under a worker.
            p.shutdown(wait=True)
        self.pool.close()
