"""Model layer: versioned registry, serving, batch inference.

Reference surface (SURVEY.md §2.5): ``hops.model`` (export /
get_best_model with Metric.MAX/MIN) and ``hops.serving``
(create_or_update / start / stop / get_status / make_inference_request /
get_kafka_topic), plus Spark batch inference. TPU-native: models are
flax param trees + reconstructable module specs; serving is an
in-process XLA-backed HTTP server speaking the TF-Serving REST payload;
inference logging rides the pubsub layer.
"""

from hops_tpu.modelrepo import batch, registry, serving  # noqa: F401
from hops_tpu.modelrepo.lm_engine import LMEngine  # noqa: F401
from hops_tpu.modelrepo.paged import BlockPool, BlockPoolExhausted  # noqa: F401
from hops_tpu.modelrepo.registry import Metric, export, get_best_model, get_model  # noqa: F401
