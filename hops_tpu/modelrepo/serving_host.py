"""Standalone serving host / supervisor — ``python -m hops_tpu.modelrepo.serving_host``.

The reference's servings are platform-owned containers that outlive
whatever notebook created them (model_repo_and_serving.ipynb:370-374);
here the equivalent is this resident process:

- ``serving_host NAME`` — host one serving endpoint until terminated.
  ``serving.start(name, standalone=True)`` spawns exactly this in a
  detached session, so the endpoint survives its creator.
- ``serving_host --restore [--watch N]`` — the supervisor verb: revive
  every serving recorded Running whose server died with its process,
  stay resident hosting them, and (with ``--watch``) re-check liveness
  every N seconds, reviving again as needed.
- ``serving_host --fleet-worker DIR`` — one fleet replica: host the
  serving config at ``DIR/cfg.json`` (written by
  ``modelrepo.fleet.replicas.ReplicaManager``) WITHOUT touching the
  shared servings registry — N replicas of one endpoint each own a
  private port, announced via ``DIR/state.json``. The replica manager
  owns the lifecycle (drain via ``POST /admin/drain``, then SIGTERM).

Termination does NOT mark hosted servings Stopped: a record's Running
status is its owner's *intent*, which is what lets the next
``restore()`` bring the endpoint back after a crash or host restart.
A deliberate ``serving.stop(name)`` is the thing that flips the record.
"""

from __future__ import annotations

import argparse
import json
import os
import signal


def main(argv: list[str] | None = None) -> None:
    parser = argparse.ArgumentParser(
        prog="python -m hops_tpu.modelrepo.serving_host",
        description=__doc__.split("\n")[0],
    )
    parser.add_argument("name", nargs="?", help="serving to host standalone")
    parser.add_argument(
        "--restore", action="store_true",
        help="revive dead-Running servings and supervise them",
    )
    parser.add_argument(
        "--watch", type=float, default=0.0,
        help="with --restore: re-check liveness every N seconds",
    )
    parser.add_argument(
        "--fleet-worker", metavar="DIR", default=None,
        help="host one fleet replica from DIR/cfg.json (registry untouched; "
        "port announced in DIR/state.json)",
    )
    args = parser.parse_args(argv)
    if sum(map(bool, (args.name, args.restore, args.fleet_worker))) != 1:
        parser.error("provide a serving name, --restore, or --fleet-worker")

    from hops_tpu.modelrepo import serving

    # Block the termination signals BEFORE any server thread exists:
    # spawned threads inherit the mask, so the kernel can only deliver
    # them to this main thread's sigwait below. (A signal.signal handler
    # is NOT enough here — with server threads running, delivery can
    # land on a worker thread while the main thread sits in a C-level
    # wait, deferring the Python handler until that wait times out.)
    sigs = {signal.SIGTERM, signal.SIGINT}
    signal.pthread_sigmask(signal.SIG_BLOCK, sigs)

    if args.fleet_worker:
        from pathlib import Path

        rdir = Path(args.fleet_worker)
        cfg = json.loads((rdir / "cfg.json").read_text())
        running = serving._RunningServing(cfg)
        # Atomic announce: the replica manager polls for this file and
        # must never read a partial write.
        state = {"name": cfg["name"], "port": running.port, "pid": os.getpid(),
                 "version": cfg.get("model_version")}
        tmp = rdir / f".state.json.tmp{os.getpid()}"
        tmp.write_text(json.dumps(state))
        os.replace(tmp, rdir / "state.json")
        print(json.dumps(state), flush=True)
        signal.sigwait(sigs)
        os._exit(0)

    if args.restore:
        names = serving.restore()
        print(json.dumps({"restored": names, "pid": os.getpid()}), flush=True)
        if args.watch:
            while signal.sigtimedwait(sigs, args.watch) is None:
                serving.reconcile()  # honor stop()s issued elsewhere
                serving.restore()
        else:
            signal.sigwait(sigs)
    else:
        cfg = serving._host_here(args.name, dedicated=True)
        print(json.dumps({"name": args.name, "port": cfg["port"], "pid": os.getpid()}), flush=True)
        signal.sigwait(sigs)
    # Exit decisively: server/producer threads must not keep a
    # terminated host lingering (records stay Running by design — see
    # module docstring).
    os._exit(0)


if __name__ == "__main__":
    main()
