"""Model serving with the TF-Serving REST contract.

Reference (SURVEY.md §2.5, model_repo_and_serving.ipynb:370-375,523):
``serving.create_or_update(name, model_path, model_server=..., ...)``,
lifecycle ``start/stop/get_status/get_all``, inference via
``make_inference_request(name, {"signature_name", "instances": [...]})``
returning ``{"predictions": [...]}``, and every request/response tee'd
onto a per-serving Kafka topic (``serving.get_kafka_topic``).

TPU-native: each started serving is an HTTP server thread exposing
``POST /v1/models/<name>:predict`` (the TF-Serving path) backed by a
jitted flax apply — or by a user Python ``Predict`` class (the
reference's sklearn escape hatch, iris_flower_classifier.py:1-27).
Inference logging rides ``messaging.pubsub``.
"""

from __future__ import annotations

import importlib.util
import json
import os
import pickle
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.request
from pathlib import Path
from typing import Any

import numpy as np

from hops_tpu.messaging import pubsub
from hops_tpu.modelrepo import registry
from hops_tpu.runtime import faultinject, flight, fs, qos, wirecodec
from hops_tpu.runtime.httpserver import HTTPServer
from hops_tpu.runtime.logging import get_logger
from hops_tpu.runtime.resilience import (
    CircuitBreaker,
    DeadlineExceeded,
    with_deadline,
)
from hops_tpu.telemetry import export as telemetry_export
from hops_tpu.telemetry import tracing
from hops_tpu.telemetry import workload
from hops_tpu.telemetry.metrics import RATIO_BUCKETS, REGISTRY
from hops_tpu.telemetry.spans import span

log = get_logger(__name__)

FLAX = "FLAX"
PYTHON = "PYTHON"
LM = "LM"  # continuous-batching text generation (lm_engine.LMEngine)
# Accepted for reference parity; flax bundles are the native path.
TENSORFLOW_SERVING = FLAX

_servers: dict[str, "_RunningServing"] = {}  # guarded by: _lock
_lock = threading.Lock()
#: Names whose _RunningServing is mid-construction (single-flight):
#: the builder holds the name here — NOT _lock — while it loads the
#: model, so unrelated start()/stop()/status calls never queue behind
#: a model load. The Event is set when construction ends (either way).
_starting: dict[str, threading.Event] = {}  # guarded by: _lock


def _servings_file() -> Path:
    p = Path(fs.project_path("Serving"))
    p.mkdir(parents=True, exist_ok=True)
    return p / "servings.json"


import contextlib
import fcntl


@contextlib.contextmanager
def _registry_lock():
    """Cross-process lock for registry read-modify-write cycles.

    Atomic replace in _save_registry keeps READERS consistent, but two
    processes interleaving load-modify-save (a supervisor reviving A
    while a notebook stops B) would lose updates without this.
    """
    lockfile = _servings_file().with_suffix(".lock")
    with open(lockfile, "w") as f:
        fcntl.flock(f, fcntl.LOCK_EX)
        try:
            yield
        finally:
            fcntl.flock(f, fcntl.LOCK_UN)


def _load_registry() -> dict[str, dict[str, Any]]:
    f = _servings_file()
    return json.loads(f.read_text()) if f.exists() else {}


def _save_registry(reg: dict[str, dict[str, Any]]) -> None:
    # Atomic replace: standalone starts and supervisors poll this file
    # from other processes at 10 Hz (same rationale as jobs Execution.save).
    f = _servings_file()
    tmp = f.with_suffix(f".tmp{os.getpid()}")
    tmp.write_text(json.dumps(reg, indent=2, default=str))
    os.replace(tmp, f)


# -- predictors ---------------------------------------------------------------


class FlaxPredictor:
    """Serves a ``save_flax`` bundle with a jitted apply.

    Batch sizes are bucketed to the next power of two (padded with the
    first row, result sliced back): under jit every distinct shape is a
    separate compile, and a dynamic batcher produces many distinct
    sizes — bucketing caps the compile count at log2(max_batch).
    """

    def __init__(self, artifact_dir: Path):
        import jax
        import numpy as np

        bundle = pickle.loads((artifact_dir / "flax_model.pkl").read_bytes())
        module = bundle["module"]
        variables = {"params": bundle["params"], **bundle["extra_variables"]}
        self._np = np
        self._apply = jax.jit(lambda x: module.apply(variables, x, train=False))

    def predict(self, instances: list[Any]) -> list[Any]:
        np = self._np
        from hops_tpu.modelrepo.batch import ASSEMBLY_POOL

        n = len(instances)
        if n == 0:
            return []
        bucket = 1 << max(0, (n - 1)).bit_length()
        # Assemble straight into a pooled (bucket, ...) buffer: at
        # steady state every wave of a bucketed size reuses the same
        # allocation instead of np.asarray + a pad-concatenate copy
        # per wave. Row 0 converts first to learn the row shape (and
        # to fail on malformed input before a buffer is taken).
        row0 = np.asarray(instances[0], dtype=np.float32)
        x = ASSEMBLY_POOL.take((bucket, *row0.shape), np.float32)
        try:
            x[0] = row0
            if n > 1:
                x[1:n] = instances[1:]
            if bucket != n:
                x[n:] = row0  # pad rows: any valid row keeps shapes static
            preds = np.asarray(self._apply(x))[:n].tolist()
        finally:
            # jit copied the buffer host→device at dispatch, and
            # np.asarray above blocked on the result — safe to recycle
            # even when conversion/predict raised.
            ASSEMBLY_POOL.give(x)
        return preds


class PythonPredictor:
    """Loads a user script defining ``class Predict`` with
    ``__init__/predict`` (and optionally ``classify``/``regress``) —
    the reference's Python-model-server contract."""

    def __init__(self, script_path: Path):
        spec = importlib.util.spec_from_file_location("hops_tpu_predictor", script_path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        self._impl = mod.Predict()

    def predict(self, instances: list[Any]) -> list[Any]:
        return self._impl.predict(instances)


class LMEnginePredictor:
    """Continuous-batching text generation behind the serving contract.

    Loads a ``save_flax`` TransformerLM bundle, clones the module with
    ``ragged_decode=True`` (params are layout-identical), and drives an
    ``LMEngine`` from a single driver thread. Handler threads submit
    requests and sleep on a condition variable; every engine iteration
    serves ALL live requests in one decode dispatch, so concurrent
    ragged requests share the device instead of queueing behind each
    other — continuous batching at the HTTP surface.

    Instance format: ``{"prompt": [ids], "max_new_tokens": 32,
    "eos_id": null, "temperature": 0.0, "top_k": null, "top_p": null,
    "seed": 0}``
    (a bare token list is shorthand for just the prompt). Predictions
    are generated-token lists, prompt excluded.
    """

    def __init__(self, artifact_dir: Path, lm_config: dict[str, Any] | None = None):
        from hops_tpu.modelrepo.lm_engine import LMEngine  # defers jax

        cfg = lm_config or {}
        bundle = pickle.loads((artifact_dir / "flax_model.pkl").read_bytes())
        module = bundle["module"].clone(ragged_decode=True)
        if cfg.get("kv_cache_dtype"):
            # {"kv_cache_dtype": "int8"}: quantized-at-rest KV — on the
            # paged layout the pool stores int8 blocks + per-position
            # scale tables, ≈4x live tokens per cache byte (greedy
            # streams bit-identical to fp-layout scheduling peers at
            # the same dtype; see ops/attention int8 paths).
            module = module.clone(kv_cache_dtype=str(cfg["kv_cache_dtype"]))
        draft_module = draft_params = None
        if cfg.get("draft_model"):
            # Speculative serving: the draft is a second registry model
            # ({"draft_model": name, "draft_version": int?, "spec_k": k}).
            from hops_tpu.modelrepo import registry

            draft = registry.load_flax(
                cfg["draft_model"], cfg.get("draft_version")
            )
            draft_module = draft["module"].clone(ragged_decode=True)
            draft_params = draft["params"]
        self._engine = LMEngine(
            module,
            bundle["params"],
            slots=int(cfg.get("slots", 4)),
            prefill_buckets=(
                tuple(cfg["prefill_buckets"]) if "prefill_buckets" in cfg else None
            ),
            decode_horizon=int(cfg.get("decode_horizon", 1)),
            draft_model=draft_module,
            draft_params=draft_params,
            spec_k=int(cfg.get("spec_k", 4)),
            # Paged KV cache + chunked prefill: {"kv_page_size": 64,
            # "kv_pool_blocks": N?, "prefill_chunk": C?} — block-pool
            # memory bounded by live tokens, long prompts admitted in
            # chunks fused into the decode wave.
            kv_page_size=(
                int(cfg["kv_page_size"]) if cfg.get("kv_page_size") else None
            ),
            kv_pool_blocks=(
                int(cfg["kv_pool_blocks"]) if cfg.get("kv_pool_blocks") else None
            ),
            # Bounded admission: a full submit queue rejects with a
            # typed QueueFullError -> 503 reason="overload".
            max_queue=int(cfg.get("max_queue", 1024)),
            prefill_chunk=(
                int(cfg["prefill_chunk"]) if cfg.get("prefill_chunk") else None
            ),
        )
        # Shared prompt prefixes (system prompts): prefilled once at
        # startup; instances opt in with {"prefix_id": name}.
        for pname, ptokens in (cfg.get("prefixes") or {}).items():
            self._engine.register_prefix(pname, ptokens)
        # Brownout degrade: under SLO burn (qos.DEGRADE+) decode
        # budgets clamp to this — shorter answers beat shed answers.
        self._brownout_max_new = int(cfg.get("brownout_max_new_tokens", 16))
        self._cv = threading.Condition()
        self._stopping = False  # guarded by: self._cv
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        try:
            while True:
                with self._cv:
                    while not self._stopping and not self._engine.has_work:
                        self._cv.wait()
                    if self._stopping:
                        return
                    # The dispatch runs under the lock: admissions only
                    # land at iteration boundaries anyway, and waiters
                    # are woken the moment their ticket finishes — or
                    # fails (a dispatch error records per-ticket errors
                    # and returns no finishers).
                    if self._engine.step() or self._engine.has_failures:
                        self._cv.notify_all()
        except BaseException:  # noqa: BLE001
            # A dying driver thread must fail the waiters, not strand
            # them on cv.wait() forever with hung HTTP connections.
            with self._cv:
                self._stopping = True
                self._cv.notify_all()
            log.exception("LM engine driver thread died")
            raise

    def stats(self) -> dict[str, Any]:
        """Engine telemetry under the engine lock (the driver thread
        steps under the same condition variable)."""
        with self._cv:
            return self._engine.stats()

    @staticmethod
    def _parse(instance: Any) -> dict[str, Any]:
        if isinstance(instance, dict):
            return {
                "prompt": instance["prompt"],
                "max_new_tokens": int(instance.get("max_new_tokens", 32)),
                "eos_id": instance.get("eos_id"),
                "temperature": float(instance.get("temperature", 0.0)),
                "top_k": instance.get("top_k"),
                "top_p": instance.get("top_p"),
                "seed": int(instance.get("seed", 0)),
                "prefix_id": instance.get("prefix_id"),
            }
        return {"prompt": instance}

    def predict(self, instances: list[Any]) -> list[Any]:
        parsed = [self._parse(i) for i in instances]
        # QoS: the handler's class rides the contextvar into the
        # engine's priority admission; an active brownout shrinks
        # decode budgets (shorter answers beat shed answers).
        priority = qos.request_priority()
        if qos.brownout_level() >= qos.DEGRADE:
            for kw in parsed:
                # .get: a bare-prompt instance parses without the key
                # (submit() defaults it to 32) — brownout must shorten
                # its answer, never 500 it.
                kw["max_new_tokens"] = max(
                    1, min(kw.get("max_new_tokens", 32),
                           self._brownout_max_new))
        for kw in parsed:
            kw["priority"] = priority
        # The engine steps on ITS driver thread; attribute each
        # ticket's submit→finish window back to this request's trace
        # retroactively (with per-ticket TTFT, the queue/prefill vs
        # decode split) once the results are in.
        trace_ctx = tracing.current_context()
        t_submit = time.time()
        with self._cv:
            if self._stopping:
                raise RuntimeError("serving stopped")
            # All-or-nothing submission: a bad instance mid-batch must
            # not leave earlier ones burning slots with no reader. The
            # cancels are exact because the driver thread steps under
            # this same lock — nothing got admitted in between.
            tickets: list[int] = []
            try:
                for kw in parsed:
                    tickets.append(self._engine.submit(**kw))
            except Exception:
                for t in tickets:
                    self._engine.cancel(t)
                raise
            self._cv.notify_all()  # wake the driver thread
            while any(
                self._engine.result(t) is None
                and self._engine.error(t) is None
                for t in tickets
            ):
                if self._stopping:
                    # The driver thread is gone; nothing will ever
                    # finish these. Fail the request instead of hanging
                    # the handler (and its HTTP connection) forever.
                    for t in tickets:
                        self._engine.take_result(t)
                        self._engine.take_error(t)
                    raise RuntimeError("serving stopped")
                self._cv.wait()
            # take_result / take_error (consuming): one engine serves
            # the process lifetime — result() would leak every
            # request's tokens. A dispatch failure (lm_engine.dispatch
            # fault point, real backend error) failed only the affected
            # tickets; surface it as this request's 5xx while other
            # callers keep streaming.
            ttfts = {t: self._engine.ttft_s.get(t) for t in tickets}
            errors = [self._engine.take_error(t) for t in tickets]
            results = [self._engine.take_result(t) for t in tickets]
            if trace_ctx is not None:
                dur = time.time() - t_submit
                for t, res, err in zip(tickets, results, errors):
                    attrs: dict[str, Any] = {
                        "ticket": t,
                        "tokens": len(res) if res is not None else 0,
                    }
                    if ttfts.get(t) is not None:
                        attrs["ttft_ms"] = round(ttfts[t] * 1e3, 3)
                    if err is not None:
                        attrs["error"] = type(err).__name__
                    tracing.record_span(
                        "lm_engine.dispatch", trace_ctx, t_submit, dur,
                        **attrs)
            first = next((e for e in errors if e is not None), None)
            if first is not None:
                raise RuntimeError(
                    f"lm engine dispatch failed for this request: "
                    f"{type(first).__name__}: {first}"
                )
            return results

    def stop(self) -> None:
        with self._cv:
            self._stopping = True
            self._cv.notify_all()
        self._thread.join(timeout=5)


def _build_predictor(cfg: dict[str, Any]) -> Any:
    artifact_dir = Path(cfg["artifact_path"])
    if cfg["model_server"] == LM:
        return LMEnginePredictor(artifact_dir, cfg.get("lm_config"))
    if cfg["model_server"] == PYTHON:
        scripts = sorted(artifact_dir.rglob("*.py"))
        if not scripts:
            raise FileNotFoundError(f"no predictor script under {artifact_dir}")
        # The predictor is the script defining `class Predict` (the
        # reference's contract) — helper modules may sit alongside it.
        with_predict = [s for s in scripts if "class Predict" in s.read_text()]
        if not with_predict:
            raise FileNotFoundError(
                f"no script under {artifact_dir} defines `class Predict`"
            )
        return PythonPredictor(with_predict[0])
    return FlaxPredictor(artifact_dir)


# -- dynamic batching ---------------------------------------------------------


class DynamicBatcher:
    """Server-side request batching (TF-Serving's ``enable_batching``).

    Concurrent requests are coalesced: the batcher thread collects
    instances arriving within ``timeout_ms`` of the first, up to
    ``max_batch_size`` rows, runs ONE ``predict_fn`` over the
    concatenation, and splits the predictions back per request. On TPU
    this turns N concurrent batch-1 dispatches into one batch-N pass —
    the difference between matvec and matmul on the MXU. Exceptions
    from ``predict_fn`` propagate to every waiting request of that
    batch; later batches are unaffected.

    Requests never merge past ``max_batch_size`` (a request that would
    overflow the cap seeds the next batch instead); a SINGLE request
    larger than the cap runs alone, unsplit — the caller chose that
    batch shape explicitly.
    """

    def __init__(self, predict_fn, max_batch_size: int = 64,
                 timeout_ms: float = 5.0, model: str = "",
                 queue_bound: int = 1024, starvation_limit: int = 8):
        self._predict = predict_fn
        self.max_batch_size = max_batch_size
        self.timeout_s = timeout_ms / 1e3
        # Priority-aware and HARD-bounded (the unbounded-priority-queue
        # lint rule's contract): interactive requests coalesce ahead of
        # batch-class ones, FIFO within a class, batch never starves
        # (the queue's starvation guard), and a full queue sheds the
        # newest lowest-class item — its waiter gets qos.ShedError,
        # which the handler answers as a 503 shed.
        self._queue = qos.BoundedPriorityQueue(
            queue_bound, starvation_limit=starvation_limit)
        self._stop_lock = threading.Lock()
        self._stopped = False  # guarded by: self._stop_lock
        self.batches_run = 0
        self.rows_run = 0
        self._m_queue_depth = REGISTRY.gauge(
            "hops_tpu_serving_batch_queue_depth",
            "Requests waiting in the dynamic batcher",
            labels=("model",),
        ).labels(model=model)
        self._m_fill = REGISTRY.histogram(
            "hops_tpu_serving_batch_fill_ratio",
            "Rows per coalesced batch over max_batch_size",
            labels=("model",), buckets=RATIO_BUCKETS,
        ).labels(model=model)
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def predict(self, instances: list[Any]) -> list[Any]:
        from concurrent.futures import Future

        fut: Future = Future()
        # The handler thread's trace context rides along so the batcher
        # thread can attribute queue-wait and the shared batch-compute
        # time back to THIS request's trace (queue vs compute split).
        item = (list(instances), fut, tracing.current_context(),
                time.monotonic(), time.time())
        # Check-and-enqueue is atomic with stop()'s flag-and-sentinel:
        # every item the queue ever holds precedes the sentinel, so the
        # loop (or its stop-time drain) resolves every future — no
        # handler can block forever on a straggler enqueued after it.
        # (The sentinel rides the negative control lane, which get()
        # serves first — its short-circuit drain still answers every
        # queued item, whatever class order says.)
        with self._stop_lock:
            if self._stopped:
                raise RuntimeError("serving stopped")
            evicted = self._queue.put(
                item, rank=qos.rank(qos.request_priority()))
        if evicted is not None:
            # Shed-lowest-first under a full queue: the evicted waiter
            # is answered NOW (503 at the handler), not left to starve.
            evicted[1].set_exception(
                qos.ShedError("shed from the batch queue by "
                              "higher-priority work"))
        self._m_queue_depth.set(self._queue.qsize())
        return fut.result()

    def stop(self) -> None:
        with self._stop_lock:
            if self._stopped:
                return
            self._stopped = True
            self._queue.put(None, rank=-1)  # control lane: served first
        self._thread.join(timeout=30)
        # The enqueue lock means nothing lands after the sentinel: once
        # the loop thread exits, every queued future has been resolved.
        # _drain_and_fail is belt-and-braces for the timeout path only.
        if self._thread.is_alive():
            log.warning("dynamic batcher stop: drain still running after "
                        "30s; leaving it to finish")
            return
        self._drain_and_fail()

    def _loop(self) -> None:
        import queue
        import time as _time

        carry = None  # a request that didn't fit the previous batch
        while True:
            item = carry if carry is not None else self._queue.get()
            carry = None
            if item is None:
                self._run_remaining()
                return
            pending = [item]
            rows = len(item[0])
            deadline = _time.monotonic() + self.timeout_s
            while rows < self.max_batch_size:
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    break
                try:
                    nxt = self._queue.get(timeout=remaining)
                except queue.Empty:
                    break
                if nxt is None:
                    self._run(pending)
                    self._run_remaining()
                    return
                if rows + len(nxt[0]) > self.max_batch_size:
                    carry = nxt  # seed of the NEXT batch; cap respected
                    break
                pending.append(nxt)
                rows += len(nxt[0])
            self._run(pending)

    def _run_remaining(self) -> None:
        """Stop-time drain: work that was already QUEUED when the stop
        sentinel landed still gets its answer (replica drains complete
        queued requests before the predictor is torn down — the fleet
        rollout's zero-downtime contract); only stragglers that raced
        in after the drain are failed."""
        import queue

        pending: list = []
        rows = 0
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            if item is None:
                continue
            if pending and rows + len(item[0]) > self.max_batch_size:
                self._run(pending)
                pending, rows = [], 0
            pending.append(item)
            rows += len(item[0])
        if pending:
            self._run(pending)
        self._drain_and_fail()

    def _drain_and_fail(self) -> None:
        import queue

        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                return
            if item is not None:
                item[1].set_exception(RuntimeError("serving stopped"))

    def _run(self, pending) -> None:
        flat = [row for instances, *_ in pending for row in instances]
        self._m_queue_depth.set(self._queue.qsize())
        # An over-cap single request runs alone, unsplit — clamp so the
        # ratio histogram stays in [0, 1].
        self._m_fill.observe(min(len(flat) / self.max_batch_size, 1.0))
        # Trace attribution for the coalesced batch: the predict runs
        # ONCE for every queued request, under the first traced
        # request's context (its trace carries the real compute span
        # and any children the predictor emits, e.g. the feature
        # join); every other traced request gets the same compute
        # window recorded retroactively, all linked by `batch`, and
        # every traced request gets its own queue-wait span — the
        # queue-wait vs compute split, per request.
        carrier = next(
            (it[2] for it in pending if it[2] is not None and it[2].sampled),
            None,
        )
        t_run_mono, t_run_wall = time.monotonic(), time.time()
        error: Exception | None = None
        preds = None
        with tracing.use_context(carrier):
            cspan = tracing.child_span(
                "serving.batch.compute",
                rows=len(flat), requests=len(pending), shared=True,
            )
            try:
                with cspan:
                    preds = self._predict(flat)
            except Exception as e:  # noqa: BLE001 — fail THIS batch only
                error = e
        batch_id = cspan.span_id or None
        compute_s = time.monotonic() - t_run_mono
        for instances, fut, ctx, enq_mono, enq_wall in pending:
            if ctx is None:
                continue
            tracing.record_span(
                "serving.batch.queue_wait", ctx, enq_wall,
                max(0.0, t_run_mono - enq_mono), batch=batch_id,
            )
            if ctx is not carrier:
                attrs = {"batch": batch_id, "rows": len(flat),
                         "requests": len(pending), "shared": True}
                if error is not None:
                    attrs["error"] = f"{type(error).__name__}: {error}"
                tracing.record_span(
                    "serving.batch.compute", ctx, t_run_wall, compute_s,
                    **attrs,
                )
        if error is not None:
            for _, fut, *_rest in pending:
                fut.set_exception(error)
            return
        self.batches_run += 1
        self.rows_run += len(flat)
        start = 0
        for instances, fut, *_rest in pending:
            fut.set_result(preds[start:start + len(instances)])
            start += len(instances)


# -- the HTTP server ----------------------------------------------------------


class _InflightSlot:
    """One admitted unit of the ``max_inflight`` budget.

    The cap bounds concurrent PREDICTOR executions, not handler
    threads: when a deadline abandons a predict still running on its
    worker thread, the slot must stay held until that work actually
    finishes — releasing it at handler exit would admit new requests
    on top of zombie computations, the exact overload the shedder
    exists to prevent. Ownership: the handler releases by default
    (:meth:`release`); once :meth:`transfer` hands the slot to the
    predict worker, only the worker's ``release(from_worker=True)``
    frees it. Idempotent either way."""

    __slots__ = ("_running", "_lock", "_released", "_transferred")

    def __init__(self, running: "_RunningServing"):
        self._running = running
        self._lock = threading.Lock()
        self._released = False  # guarded by: self._lock
        self._transferred = False  # guarded by: self._lock

    def transfer(self) -> None:
        with self._lock:
            self._transferred = True

    def release(self, from_worker: bool = False) -> None:
        with self._lock:
            if self._released or (self._transferred and not from_worker):
                return
            self._released = True
        self._running._exit()


class _RunningServing:
    def __init__(self, cfg: dict[str, Any]):
        self.cfg = cfg
        self.predictor = _build_predictor(cfg)
        if cfg.get("feature_config"):
            # Serving-time feature joins: requests carry entity IDs
            # only; the wrapper multi-gets the configured feature
            # groups' online rows, assembles model-ready vectors, and
            # feeds the real predictor. Sits UNDER the DynamicBatcher,
            # so coalesced entity batches become one batched join.
            from hops_tpu.featurestore.online_serving import FeatureJoinPredictor

            self.predictor = FeatureJoinPredictor(
                self.predictor, cfg["feature_config"], model=cfg["name"]
            )
        self.producer = pubsub.Producer(cfg["topic"])
        name = cfg["name"]
        # Overload protection + failure gating (docs/operations.md
        # "Failure handling"): a queue-depth shedder (in-flight handler
        # threads over `max_inflight` get 503 + Retry-After instead of
        # queueing into a latency collapse), a per-request deadline,
        # and a circuit breaker that fails fast — and flips /healthz
        # unready — while the predictor is down rather than flaky.
        rcfg = cfg.get("resilience_config") or {}
        self.max_inflight = rcfg.get("max_inflight")
        self.deadline_s = rcfg.get("deadline_s")
        # Shed-lowest-class-first: batch traffic stops being admitted
        # once in-flight work crosses this fraction of max_inflight —
        # the headroom above it is reserved for interactive requests.
        self.batch_admit_frac = float(rcfg.get("batch_admit_frac", 0.75))
        self.breaker = CircuitBreaker(
            name=f"serving-{name}",
            failure_threshold=int(rcfg.get("breaker_failures", 5)),
            reset_timeout_s=float(rcfg.get("breaker_reset_s", 30.0)),
        )
        self._inflight_lock = threading.Lock()
        self._inflight = 0  # guarded by: self._inflight_lock
        self._draining = False  # guarded by: self._inflight_lock
        # The fleet router's least-loaded signal: live predictor
        # executions on THIS endpoint, scraped from /metrics.json.
        self._m_inflight = REGISTRY.gauge(
            "hops_tpu_serving_inflight",
            "Concurrent predictor executions in flight, per endpoint",
            labels=("model",),
        ).labels(model=name)
        self.batcher = None
        if cfg.get("batching_enabled"):
            bc = cfg.get("batching_config") or {}
            self.batcher = DynamicBatcher(
                self.predictor.predict,
                max_batch_size=int(bc.get("max_batch_size", 64)),
                timeout_ms=float(bc.get("timeout_ms", 5.0)),
                model=name,
                queue_bound=int(bc.get("queue_bound", 1024)),
                starvation_limit=int(bc.get("starvation_limit", 8)),
            )
        predictor = self.batcher or self.predictor
        raw_predictor = self.predictor
        producer = self.producer
        # Per-endpoint request telemetry (the reference's per-serving
        # Kafka metrics role): counters + the latency histogram the
        # `/metrics` route on THIS server's port exposes.
        m_requests = REGISTRY.counter(
            "hops_tpu_serving_requests_total",
            "Predict requests received, per serving endpoint",
            labels=("model",),
        ).labels(model=name)
        m_errors = REGISTRY.counter(
            "hops_tpu_serving_errors_total",
            "Predict requests that raised, per serving endpoint",
            labels=("model",),
        ).labels(model=name)
        m_logged = REGISTRY.counter(
            "hops_tpu_serving_inference_log_total",
            "Request/response pairs tee'd onto the serving's pubsub topic",
            labels=("model",),
        ).labels(model=name)
        m_shed = REGISTRY.counter(
            "hops_tpu_serving_shed_total",
            "Requests shed with 503, per serving endpoint and reason "
            "(overload | breaker | draining | qos — batch class shed "
            "first under load or evicted from the batch queue)",
            labels=("model", "reason"),
        )
        m_gen_rejected = REGISTRY.counter(
            "hops_tpu_fleet_generation_rejected_total",
            "Requests refused with a typed 410 because they stamped a "
            "generation newer than the unit's own — a superseded zombie "
            "fenced at the data plane, per unit kind",
            labels=("kind",),
        )
        # Placement identity (minted by the PlacementClient, carried in
        # cfg): this unit's own (slot, generation) token, compared
        # against the X-Hops-Generation stamp on every predict.
        unit_token = (f"{cfg['slot']}:{int(cfg.get('generation', 0))}"
                      if cfg.get("slot") else None)
        running = self
        breaker = self.breaker

        def _np_native(obj: Any):
            # A packed request hands the predictor an ndarray; a user
            # predictor may echo numpy scalars/arrays back into a JSON
            # (non-negotiated) response. Only invoked on non-native
            # objects, so the plain-JSON path pays nothing.
            if hasattr(obj, "tolist"):
                return obj.tolist()
            if hasattr(obj, "item"):
                return obj.item()
            raise TypeError(
                f"not JSON serializable: {type(obj).__name__}")

        def _json(code: int, body: dict[str, Any],
                  extra: dict[str, str] | None = None):
            h = {"Content-Type": "application/json"}
            if extra:
                h.update(extra)
            # JSON is the default wire format; errors, debug timelines,
            # and non-negotiated responses are spec'd to serialize here.
            return code, h, json.dumps(body, default=_np_native).encode()  # graftlint: disable=json-on-hot-wire

        def _maybe_debug(headers: Any, body: dict[str, Any],
                         tspan: Any) -> dict[str, Any]:
            """Attach the inline per-hop timing breakdown when the
            request asked for it (``X-Hops-Debug: timeline``) and this
            request is traced — the router merges its own hops into the
            same list on the way back out."""
            want = headers.get(tracing.DEBUG_HEADER, "")
            if want.strip().lower() == "timeline":
                rows = tracing.timeline(tspan)
                if rows:
                    body["debug"] = {
                        "trace_id": rows[0]["trace_id"],
                        "timeline": rows,
                    }
            return body

        def _do_get(path: str, headers: Any):
            # TF-Serving's model-status contract
            # (GET /v1/models/<name>), extended with live engine
            # telemetry when the predictor exposes stats() — the
            # LM engine's dispatches, occupancy, prefix hits, and
            # speculation acceptance.
            try:
                # Prometheus scrape rides the serving's own port
                # (GET /metrics, GET /metrics.json) — the whole
                # process's registry, not just this endpoint. The
                # debug surfaces (/debug/traces, /debug/flight)
                # ride the same port: this process's span ring and
                # flight recorder.
                resp = telemetry_export.metrics_response(path)
                if resp is None:
                    resp = telemetry_export.debug_response(path)
                if resp is not None:
                    return resp
                # Readiness: load balancers and supervisors poll
                # this; an open breaker = the predictor is down,
                # stop routing here until the half-open probe heals.
                # A DRAINING endpoint is also unready (503 +
                # Retry-After) and reports its in-flight count, so
                # a rollout can gate the reap on inflight == 0 off
                # the same probe the router stops routing on.
                if path.rstrip("/") == "/healthz":
                    bstate = breaker.state
                    if running.draining:
                        return _json(
                            503,
                            {"status": "draining", "breaker": bstate,
                             "inflight": running.inflight},
                            extra={"Retry-After": "1"},
                        )
                    if bstate == "open":
                        retry = max(1.0, breaker.retry_after_s())
                        return _json(
                            503,
                            {"status": "unready", "breaker": bstate},
                            extra={"Retry-After": f"{retry:.0f}"},
                        )
                    return _json(200, {"status": "ok", "breaker": bstate})
                # Exact TF-Serving routes only: /v1/models/<name>
                # and the versioned /v1/models/<name>/versions/<N>
                # form (a suffix match would accept arbitrary
                # prefixes like /junk/v1/models/<name>).
                p = path.rstrip("/")
                base = f"/v1/models/{name}"
                versioned = p.startswith(base + "/versions/")
                if versioned:
                    ver = p[len(base) + len("/versions/"):]
                    if ver != str(cfg.get("model_version", 1)):
                        return _json(404, {"error": f"unknown version {ver}"})
                elif p != base:
                    return _json(404, {"error": f"unknown path {path}"})
                body: dict[str, Any] = {
                    "model_version_status": [{
                        "version": str(cfg.get("model_version", 1)),
                        "state": "AVAILABLE",
                    }],
                }
                if hasattr(raw_predictor, "stats"):
                    body["engine"] = raw_predictor.stats()
                return _json(200, body)
            except Exception as e:  # noqa: BLE001 — server must stay up
                return _json(500, {"error": f"{type(e).__name__}: {e}"})

        def _predict_resp(headers: Any, payload: dict[str, Any],
                          instances: list[Any], slot: _InflightSlot,
                          tspan: Any):
            # Breaker check after shedding: an open breaker means
            # the predictor itself is failing — don't waste a
            # half-open probe on a request we'd shed anyway.
            if not breaker.allow():
                m_shed.inc(model=name, reason="breaker")
                tspan.annotate(shed="breaker")
                retry = max(1.0, breaker.retry_after_s())
                return _json(
                    503,
                    {"error": "circuit open; predictor failing"},
                    extra={"Retry-After": f"{retry:.0f}"},
                )
            try:
                # span() records into the request-latency histogram
                # even when predict raises — error latency is
                # latency; the error counter increments below.
                with span("hops_tpu_serving_request", model=name):
                    # Chaos point, keyed by this endpoint's port so
                    # a gray (slow-not-dead) fault can target ONE
                    # replica of an in-process fleet.
                    faultinject.fire("serving.handle", key=running.port)
                    if running.deadline_s:
                        # The worker owns the slot from here: a
                        # deadline overrun abandons the predict but
                        # its computation still occupies predictor
                        # capacity until it actually finishes.
                        slot.transfer()

                        def predict_holding_slot(rows):
                            try:
                                return predictor.predict(rows)
                            finally:
                                slot.release(from_worker=True)

                        preds = with_deadline(
                            predict_holding_slot, running.deadline_s,
                            instances, op="serving.handle")
                    else:
                        preds = predictor.predict(instances)
            except qos.ShedError as e:
                # Evicted from the batch queue by higher-priority
                # work (reason="qos") or refused at a full submit
                # queue (QueueFullError, reason="overload"): a
                # shed, not a failure — no breaker strike, same
                # 503 retry shape as every other shed.
                reason = (
                    "overload" if isinstance(e, qos.QueueFullError)
                    else "qos"
                )
                m_shed.inc(model=name, reason=reason)
                tspan.annotate(shed=reason)
                return _json(
                    503, _maybe_debug(
                        headers, {"error": f"{type(e).__name__}: {e}"}, tspan),
                    extra={"Retry-After": "1"},
                )
            except DeadlineExceeded as e:
                breaker.record_failure()
                m_errors.inc()
                return _json(504, _maybe_debug(
                    headers, {"error": f"{type(e).__name__}: {e}"}, tspan))
            except Exception as e:  # noqa: BLE001 — fail THIS request
                breaker.record_failure()
                m_errors.inc()
                return _json(500, _maybe_debug(
                    headers, {"error": f"{type(e).__name__}: {e}"}, tspan))
            breaker.record_success()
            response = {"predictions": preds}
            producer.send(
                {"request": payload, "response": response}, key=name
            )
            m_logged.inc()
            body = _maybe_debug(headers, response, tspan)
            if ("debug" not in body
                    and wirecodec.MEDIA_TYPE in (headers.get("Accept") or "")):
                # Accept-negotiated packed response. Debug timelines
                # always ride JSON (the router merges its hops into the
                # body); ragged/object predictions fall back to JSON
                # too — exactness over format.
                frame = wirecodec.try_encode_predictions(preds)
                if frame is not None:
                    return 200, {"Content-Type": wirecodec.MEDIA_TYPE}, frame
            return _json(200, body)

        def _do_post_inner(path: str, headers: Any, raw_body: bytes,
                           cap: dict[str, Any]):
            # Workload-capture control plane (arm / finalize the
            # process-global recorder; status rides GET
            # /debug/workload). Checked BEFORE the strict body parse
            # so a sloppy body degrades to {} — the same tolerant
            # contract as the router's route (a capture/stop must not
            # fail on replicas while succeeding on the front door).
            if path.split("?", 1)[0].rstrip("/").startswith(
                    "/admin/capture/"):
                try:
                    # Capture control plane, tolerant parse; not the
                    # data wire.
                    admin_payload = json.loads(raw_body)  # graftlint: disable=json-on-hot-wire
                except ValueError:
                    admin_payload = {}
                return _json(*workload.admin_action(path, admin_payload))
            # Fleet control plane: flip this endpoint into the
            # draining state (rollouts, scale-downs). Replies with
            # the in-flight count the caller will poll to zero on
            # /healthz before reaping. Checked before the body parse —
            # a drain must succeed whatever the body carries.
            if path.rstrip("/") == "/admin/drain":
                inflight = running.drain()
                return _json(200, {"status": "draining",
                                   "inflight": inflight})
            # Exact route, like GET: a suffix match would accept
            # /junk/v1/models/<name>:predict.
            if path.rstrip("/") != f"/v1/models/{name}:predict":
                return _json(404, {"error": f"unknown path {path}"})
            # Fencing gate (docs/operations.md "Partition tolerance &
            # fencing"): forwarders stamp the slot's CURRENT generation
            # on X-Hops-Generation; a mismatch means THIS unit has been
            # superseded (re-placed while it was partitioned) and must
            # refuse — typed 410, which the router retries on the live
            # generation without a breaker strike. Checked before
            # admission/parse: a zombie must not even shed or predict.
            stamped = headers.get("X-Hops-Generation")
            if stamped and unit_token and stamped != unit_token:
                m_gen_rejected.inc(kind="replica")
                flight.record("generation_rejected", unit_kind="replica",
                              model=name, slot=cfg.get("slot"),
                              have=unit_token, got=stamped)
                return _json(410, {"error": "superseded generation",
                                   "slot": cfg.get("slot"),
                                   "have": unit_token, "got": stamped})
            # Content-Type negotiation: the packed columnar frame
            # decodes zero-copy into the instance tensor; JSON stays
            # the default. A malformed frame fails closed with a 400
            # naming the offset — never a half-decoded batch.
            ctype = (headers.get("Content-Type") or "") \
                .split(";", 1)[0].strip().lower()
            if ctype == wirecodec.MEDIA_TYPE:
                wire_format = "packed"
                try:
                    instances = wirecodec.decode_instances(raw_body)
                except wirecodec.WireCodecError as e:
                    return _json(400, {"error": f"bad packed frame: {e}"})
                # The inference-log tee and capture tap need a
                # JSON-serializable request: a header-only shape
                # summary stands in for the tensor body.
                payload = {"format": "packed",
                           "summary": wirecodec.frame_summary(raw_body)}
            else:
                wire_format = "json"
                # The negotiated default path; packed bodies take the
                # branch above.
                payload = json.loads(raw_body)  # graftlint: disable=json-on-hot-wire
                instances = payload.get("instances")
                if instances is None:
                    return _json(400,
                                 {"error": "payload must carry 'instances'"})
            m_requests.inc()
            wirecodec.count_request(wire_format)
            if workload.capturing():
                # Arm the per-request capture tap: the route's single
                # exit records the request WITH its final status —
                # sheds, deadline 504s, and 500s included.
                cap["wire_format"] = wire_format
                if wire_format == "packed":
                    # Tensor bodies don't JSON-serialize; record the
                    # shape summary the replayer rebuilds from.
                    arr = instances
                    cap["payload"] = None
                    cap["instances"] = None
                    cap["summary"] = {
                        "bytes": len(raw_body),
                        "instances": int(arr.shape[0]) if arr.ndim else 1,
                        "instance": {"kind": "list",
                                     "shape": list(arr.shape[1:])},
                        "dtype": arr.dtype.str,
                    }
                else:
                    cap["payload"] = payload
                    cap["instances"] = instances
            # The trace enters (or starts) here: an incoming
            # `traceparent` — the fleet router injects one per
            # forward hop — makes this request span a child of
            # that hop; a bare request starts a fresh trace
            # under the tracer's sampling decision.
            # QoS: the fleet router stamps the RESOLVED class
            # on its forwards (clients of a bare endpoint may
            # also claim one); a relayed brownout level is
            # adopted with a TTL under THIS model's scope so the
            # replica degrades with its fleet — and only its
            # fleet, on a host serving several.
            priority = qos.parse_priority(headers.get(qos.PRIORITY_HEADER))
            qos.note_remote_brownout(headers.get(qos.BROWNOUT_HEADER),
                                     scope=name)
            want_debug = (
                headers.get(tracing.DEBUG_HEADER) or ""
            ).strip().lower() == "timeline"
            tspan = tracing.start_trace(
                "serving.request", headers=headers, model=name,
                force_sample=want_debug)
            if cap:
                cap["tspan"] = tspan
            with tspan, qos.priority_scope(priority), \
                    qos.brownout_scope(name):
                # Shedding BEFORE any model work — draining (stop
                # ADMITTING, keep finishing; the admission check is
                # atomic with the in-flight count inside _enter, so
                # /healthz can never report inflight==0 while a
                # checked-but-not-yet-admitted request sneaks in)
                # and overload (under a burst past max_inflight the
                # cheapest correct answer is an immediate 503 +
                # Retry-After — queueing collapses every request's
                # latency, not just the excess). One 503 shape for
                # both: clients and the fleet router share a single
                # retry path.
                slot, shed_reason = running._enter(priority)
                if slot is None:
                    m_shed.inc(model=name, reason=shed_reason)
                    tspan.annotate(shed=shed_reason)
                    if shed_reason == "draining":
                        msg = "draining; endpoint is going away"
                    elif shed_reason == "qos":
                        msg = ("batch traffic shed; interactive "
                               "headroom reserved")
                    else:
                        msg = "overloaded; retry later"
                    return _json(503, {"error": msg},
                                 extra={"Retry-After": "1"})
                try:
                    return _predict_resp(
                        headers, payload, instances, slot, tspan)
                finally:
                    slot.release()  # no-op once transferred

        def _do_post(path: str, headers: Any, body: bytes):
            # Workload capture stamps the ARRIVAL, not the predict
            # start — queueing ahead of the handler is part of the
            # workload being recorded.
            t_arr_mono, t_arr_wall = time.monotonic(), time.time()
            cap: dict[str, Any] = {}
            try:
                resp = _do_post_inner(path, headers, body or b"{}", cap)
            except Exception as e:  # noqa: BLE001 — server must stay up
                m_errors.inc()
                resp = _json(500, {"error": f"{type(e).__name__}: {e}"})
            if not cap:
                return resp
            # The workload tap: every predict branch replies exactly
            # once, so this is the one place the final status and
            # latency are both known. Runs as the route's `after`
            # callback — after the response is queued for write, so
            # capture never delays the reply.
            status = resp[0]
            tspan = cap.get("tspan")

            def after() -> None:
                workload.record_request(
                    surface="serving",
                    endpoint=name,
                    path=path,
                    tenant=headers.get("X-Tenant"),
                    payload=cap["payload"],
                    instances=cap["instances"],
                    lm_mode=cfg["model_server"] == LM,
                    status=status,
                    latency_ms=(time.monotonic() - t_arr_mono) * 1e3,
                    trace_id=(
                        tspan.trace_id
                        if getattr(tspan, "sampled", False) else None
                    ),
                    t_mono=t_arr_mono,
                    t_wall=t_arr_wall,
                    wire_format=cap.get("wire_format", "json"),
                    payload_summary=cap.get("summary"),
                )

            return resp[0], resp[1], resp[2], after

        def route(method: str, path: str, headers: Any, body: bytes):
            if method == "GET":
                return _do_get(path, headers)
            if method == "POST":
                return _do_post(path, headers, body)
            return _json(404, {"error": f"unknown path {path}"})

        self.server = HTTPServer(
            route, bind="127.0.0.1", port=0, name=f"serving-{name}",
            workers=int(rcfg.get("http_workers", 16)))

    def _enter(
        self, priority: str = "interactive"
    ) -> "tuple[_InflightSlot | None, str | None]":
        """Admit a request unless the endpoint is draining or
        ``max_inflight`` concurrent predictor executions are already in
        flight (None = no cap). The draining check lives HERE, under
        the same lock as the count, so ``drain()``'s returned inflight
        (and ``/healthz``'s) can never miss a request that had passed
        an earlier check but not yet been admitted. Batch-class
        requests stop being admitted at ``batch_admit_frac`` of the cap
        — the lowest class sheds first, the headroom above the fraction
        stays interactive-only. Returns ``(slot, None)`` when admitted
        (a one-shot slot the caller must release) or ``(None, reason)``
        — reason ``draining`` | ``qos`` | ``overload``."""
        with self._inflight_lock:
            if self._draining:
                return None, "draining"
            if self.max_inflight is not None:
                if self._inflight >= self.max_inflight:
                    return None, "overload"
                if (qos.rank(priority) > 0
                        and self._inflight >= max(
                            1, int(self.max_inflight
                                   * self.batch_admit_frac))):
                    return None, "qos"
            self._inflight += 1
            self._m_inflight.set(self._inflight)
        return _InflightSlot(self), None

    def _exit(self) -> None:
        with self._inflight_lock:
            self._inflight -= 1
            self._m_inflight.set(self._inflight)

    def drain(self) -> int:
        """Stop admitting new requests (they shed 503 ``draining`` with
        ``Retry-After``); in-flight work runs to completion. Returns the
        current in-flight count. ``/healthz`` reports ``draining`` from
        here on — the one readiness contract the fleet router and the
        rollout drain both key off. Idempotent."""
        with self._inflight_lock:
            already = self._draining
            self._draining = True
            inflight = self._inflight
        if not already:
            flight.record("drain", model=self.cfg["name"], inflight=inflight)
        return inflight

    @property
    def draining(self) -> bool:
        with self._inflight_lock:
            return self._draining

    @property
    def inflight(self) -> int:
        with self._inflight_lock:
            return self._inflight

    @property
    def port(self) -> int:
        return self.server.port

    def stop(self) -> None:
        self.server.stop()
        if self.batcher is not None:
            self.batcher.stop()
        if hasattr(self.predictor, "stop"):  # LMEnginePredictor's driver thread
            self.predictor.stop()


# -- public API (reference surface) ------------------------------------------


def create_or_update(
    name: str,
    model_path: str | None = None,
    model_version: int | None = None,
    model_name: str | None = None,
    model_server: str = FLAX,
    kfserving: bool = False,  # accepted for parity; single serving tool here
    instances: int = 1,
    batching_enabled: bool = False,
    batching_config: dict[str, Any] | None = None,
    lm_config: dict[str, Any] | None = None,
    resilience_config: dict[str, Any] | None = None,
    feature_config: dict[str, Any] | None = None,
) -> dict[str, Any]:
    """Create/update a serving endpoint definition (reference:
    ``serving.create_or_update``; ``batching_enabled`` mirrors the
    platform's server-side request batching). ``model_path`` may be a
    registry path or omitted in favor of ``model_name``+``model_version``.
    ``batching_config`` knobs: ``max_batch_size`` (default 64),
    ``timeout_ms`` (default 5). ``model_server="LM"`` serves a saved
    TransformerLM with continuous batching (``lm_config`` knobs:
    ``slots``, ``prefill_buckets``, ``decode_horizon`` — device-side
    steps per dispatch, amortizing host-dispatch latency —
    ``prefixes``, a ``{name: token_ids}`` dict of shared prompt
    prefixes prefilled once at startup,
    ``draft_model``/``draft_version``/``spec_k`` — a second registry
    model proposing tokens for greedy speculative serving — and
    ``kv_page_size``/``kv_pool_blocks``/``prefill_chunk``, which
    switch the engine to the paged KV cache: slot memory bounded by
    live tokens instead of slots x max_decode_len, prefix hits shared
    through page tables, and long prompts prefilled in chunks fused
    into the decode wave so they never freeze live generations); it
    does its own cross-request scheduling, so it composes with
    ``batching_enabled=False`` only.

    ``resilience_config`` knobs (docs/operations.md "Failure
    handling"): ``max_inflight`` — concurrent-request cap beyond which
    the endpoint sheds with 503 + ``Retry-After`` (default: uncapped);
    ``deadline_s`` — per-request budget, overruns answer 504;
    ``breaker_failures`` / ``breaker_reset_s`` — consecutive predictor
    failures that open the circuit, and how long it stays open before
    a half-open probe (defaults 5 / 30 s). ``GET /healthz`` reports
    readiness and flips 503 while the breaker is open.

    ``feature_config`` turns the endpoint into a feature-joining one
    (docs/featurestore.md "Online store & serving-time joins"):
    requests carry only entity-key dicts in ``instances``; the serving
    looks the entities up in the configured feature groups' sharded
    online stores, joins the rows into model-ready vectors (missing-key
    policy ``default`` | ``reject`` | ``passthrough``), and feeds the
    predictor those vectors — composing with ``batching_enabled``
    (coalesced entity batches become one batched multi-get join)."""
    if model_server.upper() == LM and batching_enabled:
        raise ValueError(
            "model_server='LM' schedules requests itself (continuous "
            "batching) — batching_enabled would double-batch; leave it off"
        )
    if feature_config:
        if model_server.upper() == LM:
            raise ValueError(
                "feature_config joins entity IDs into feature vectors — "
                "that is not a token stream; model_server='LM' cannot "
                "take it"
            )
        # Validate at definition time: a typo'd missing-key policy or a
        # group without a primary key must fail here, not at the first
        # request of a started endpoint.
        from hops_tpu.featurestore.online_serving import validate_feature_config

        feature_config = validate_feature_config(feature_config)
    if lm_config:
        # The registry round-trips through JSON with default=str: a
        # numpy/jnp array anywhere in lm_config would be silently
        # stringified and break start(). Normalize every array-valued
        # knob to plain int lists here, rejecting non-integral values
        # loudly instead of truncating them.
        def int_list(x: Any, what: str) -> list[int]:
            out = []
            for t in np.asarray(x).reshape(-1):
                # Loud rejection with the field's name for BOTH failure
                # shapes: non-integral numerics (int() succeeds but
                # changes the value) and non-numerics (int() raises).
                try:
                    i = int(t)
                except (TypeError, ValueError):
                    raise ValueError(f"{what} must be integers, got {t!r}") from None
                if i != t:
                    raise ValueError(f"{what} must be integers, got {t!r}")
                out.append(i)
            return out

        lm_config = dict(lm_config)
        if lm_config.get("prefill_buckets") is not None:
            lm_config["prefill_buckets"] = int_list(
                lm_config["prefill_buckets"], "lm_config prefill_buckets"
            )
        if lm_config.get("prefixes"):
            lm_config["prefixes"] = {
                pname: int_list(ptokens, f"prefix {pname!r} tokens")
                for pname, ptokens in lm_config["prefixes"].items()
            }
    reg = _load_registry()
    if model_path is None:
        meta = registry.get_model(model_name or name, model_version)
        artifact_path = meta["path"]
        model_version = meta["version"]
    else:
        p = Path(model_path)
        artifact_path = str(p if p.is_absolute() else fs.project_path(model_path))
        if model_version is None:
            model_version = int(p.name) if p.name.isdigit() else 1
    cfg = {
        "name": name,
        # The registry model backing this endpoint: version-pinned
        # consumers (the fleet's rollouts and heals) resolve artifacts
        # through this, NOT the endpoint name — they differ whenever
        # one model serves under several endpoint names.
        "model_name": model_name or name,
        "artifact_path": artifact_path,
        "model_version": model_version,
        "model_server": model_server.upper(),
        "kfserving": kfserving,
        "instances": instances,
        "batching_enabled": batching_enabled,
        "batching_config": batching_config or {},
        "lm_config": lm_config or {},
        "resilience_config": resilience_config or {},
        "feature_config": feature_config or {},
        "status": reg.get(name, {}).get("status", "Stopped"),
        "topic": f"serving-{name}-inference",
    }
    # Preserve runtime keys (e.g. "port") across updates of a running
    # serving; the new artifact is picked up on the next start().
    for key in ("port",):
        if key in reg.get(name, {}):
            cfg[key] = reg[name][key]
    reg[name] = cfg
    _save_registry(reg)
    pubsub.create_topic(cfg["topic"])
    return cfg


def get_all() -> list[dict[str, Any]]:
    return list(_load_registry().values())


def exists(name: str) -> bool:
    return name in _load_registry()


def _port_alive(port: int | None) -> bool:
    if not port:
        return False
    try:
        with socket.create_connection(("127.0.0.1", port), timeout=0.5):
            return True
    except OSError:
        return False


def get_status(name: str) -> str:
    """'Stopped' | 'Running' (reference statuses).

    Truthful, not trusting: a serving counts as Running if this process
    hosts it OR its recorded port answers (it may be hosted by another
    process sharing the workspace). A Running record whose server died
    with its process is healed to Stopped (use :func:`restore` to bring
    it back instead)."""
    reg = _load_registry()
    if name not in reg:
        raise KeyError(f"serving {name!r} not found")
    with _lock:
        if name in _servers:
            return "Running"
    cfg = reg[name]
    if cfg.get("status") == "Running":
        if _port_alive(cfg.get("port")):
            return "Running"
        if _host_process_alive(cfg):
            # The hosting process is alive but its port didn't answer —
            # a transient probe failure or a wedged host. Do NOT heal
            # (that would orphan the process and invite a duplicate from
            # restore()); report Stopped and leave the record intact so
            # stop() can still reach the pid.
            return "Stopped"
        # Host process is dead: heal against a FRESH snapshot under the
        # lock — the port probe above can take 0.5 s, during which
        # another thread may have updated other servings. "Failed"
        # (reported as Stopped) preserves the owner's running-intent so
        # restore() still revives it — healing must not erase what it heals.
        with _lock, _registry_lock():
            reg = _load_registry()
            if name in reg and reg[name].get("status") == "Running":
                reg[name]["status"] = "Failed"
                reg[name].pop("port", None)
                reg[name].pop("pid", None)
                _save_registry(reg)
    return "Stopped"


def restore(standalone: bool = False) -> list[str]:
    """Re-start endpoints recorded Running whose server died with its
    process — the restart-survival story (reference: platform servings
    outlive the notebook that created them, model_repo_and_serving.ipynb
    cells 15-21). Returns restarted names.

    Deliberate entry points that call this: the supervisor verb
    ``python -m hops_tpu.modelrepo.serving_host --restore [--watch N]``
    (resident, revives in-process) and ``standalone=True`` (spawns a
    detached host per serving)."""
    restarted = []
    for name, cfg in _load_registry().items():
        with _lock:
            hosted = name in _servers
        # "Failed" = a dead-Running record already healed by get_status;
        # the owner's intent is still Running.
        if cfg.get("status") in ("Running", "Failed") and not hosted and not _port_alive(cfg.get("port")):
            if _host_process_alive(cfg):
                log.warning(
                    "serving %s: host pid %s alive but port unresponsive — "
                    "not spawning a duplicate; stop() it first", name, cfg.get("pid"))
                continue
            try:
                start(name, standalone=standalone)
            except Exception as exc:  # one broken artifact must not block the rest
                log.warning("restore of serving %s failed: %s", name, exc)
                continue
            restarted.append(name)
    return restarted


def reconcile() -> list[str]:
    """Shut down in-process servers whose record no longer says Running —
    the other half of supervision: restore() revives, reconcile() honors
    deliberate stop()s issued from other processes (which can only flip
    the record of a server they don't host). Returns stopped names."""
    stopped = []
    reg = _load_registry()
    with _lock:
        hosted = list(_servers)
    for name in hosted:
        if reg.get(name, {}).get("status") == "Running":
            continue
        with _lock:
            running = _servers.pop(name, None)
        if running is not None:
            running.stop()
            stopped.append(name)
    return stopped


def start(name: str, standalone: bool = False, timeout_s: float = 60.0) -> dict[str, Any]:
    """Start a serving endpoint.

    ``standalone=True`` hosts it in a detached process
    (``python -m hops_tpu.modelrepo.serving_host <name>``) that outlives
    the caller — the stand-in for the reference's platform-owned serving
    containers (model_repo_and_serving.ipynb:370-374). Default hosts it
    as a thread of this process, as before.
    """
    if standalone:
        return _start_standalone(name, timeout_s)
    return _host_here(name)


def _host_here(name: str, dedicated: bool = False) -> dict[str, Any]:
    reg = _load_registry()
    if name not in reg:
        raise KeyError(f"serving {name!r} not found")
    while True:
        with _lock:
            if name in _servers:
                return reg[name]
            ev = _starting.get(name)
            if ev is None:
                ev = _starting[name] = threading.Event()
                break
        # Another thread is building this serving: wait for it OUTSIDE
        # the module lock, then re-check (its construction may have
        # failed, in which case this thread takes over the build).
        ev.wait()
        reg = _load_registry()
    try:
        # The slow part — registry model load, feature-store open, HTTP
        # bind — runs with _lock RELEASED (graftlint: blocking-under-
        # lock). Construction used to hold the module-wide lock, so any
        # start/stop/status of ANY serving stalled for a full model load.
        faultinject.fire("serving.start", key=name)  # chaos: slow load
        running = _RunningServing(reg[name])
    except BaseException:
        with _lock:
            _starting.pop(name, None)
        ev.set()
        raise
    with _lock:
        _servers[name] = running
        _starting.pop(name, None)
    try:
        with _registry_lock():
            reg = _load_registry()
            reg[name]["status"] = "Running"
            reg[name]["port"] = running.port
            reg[name]["pid"] = os.getpid()
            # Only a DEDICATED host process (serving_host <name>) may be
            # killed by stop() — never a notebook or a shared supervisor
            # whose pid happens to be on the record.
            if dedicated:
                reg[name]["host"] = "standalone"
            else:
                reg[name].pop("host", None)
            _save_registry(reg)
    finally:
        # Wake waiters only after the registry says Running: start()
        # peers must return a published record, and a stop() issued
        # mid-construction must sequence its "Stopped" write AFTER this
        # one, not interleave with it.
        ev.set()
    log.info("serving %s listening on 127.0.0.1:%d", name, running.port)
    return reg[name]


def _host_log(name: str) -> Path:
    return _servings_file().parent / f"{name}.host.log"


def _start_standalone(name: str, timeout_s: float) -> dict[str, Any]:
    if name not in _load_registry():
        raise KeyError(f"serving {name!r} not found")
    if get_status(name) == "Running":
        return _load_registry()[name]
    from hops_tpu.jobs.api import _child_pythonpath

    env = dict(os.environ)
    env["HOPS_TPU_WORKSPACE"] = str(fs.workspace_root())
    env["HOPS_TPU_PROJECT"] = fs.project_name()
    env["PYTHONPATH"] = _child_pythonpath(env.get("PYTHONPATH"))
    with open(_host_log(name), "a") as logfile:
        # start_new_session detaches the host from our process group: our
        # death (even SIGKILL) leaves the endpoint serving. The child owns
        # its copy of the log fd from here.
        proc = subprocess.Popen(
            [sys.executable, "-m", "hops_tpu.modelrepo.serving_host", name],
            stdout=logfile,
            stderr=subprocess.STDOUT,
            env=env,
            start_new_session=True,
        )
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        cfg = _load_registry().get(name, {})
        if cfg.get("pid") == proc.pid and _port_alive(cfg.get("port")):
            return cfg
        if proc.poll() is not None:
            break
        time.sleep(0.1)
    tail = _host_log(name).read_text()[-2000:] if _host_log(name).exists() else ""
    if proc.poll() is None:
        # The host blocks SIGTERM during startup (serving_host's sigwait
        # routing), so a wedged predictor load must be SIGKILLed.
        proc.terminate()
        try:
            proc.wait(timeout=3)
        except subprocess.TimeoutExpired:
            proc.kill()
    raise RuntimeError(
        f"standalone serving {name!r} failed to come up within {timeout_s}s; "
        f"host log tail:\n{tail}"
    )


def _host_process_alive(cfg: dict[str, Any]) -> bool:
    """Is the record's hosting process still alive — with the pid-reuse
    guard for dedicated hosts (a recycled pid must actually be a
    serving_host to count, or healing/restore would block forever)."""
    pid = cfg.get("pid")
    if not _pid_alive(pid):
        return False
    if cfg.get("host") == "standalone":
        return _is_serving_host(pid)
    return True


def _is_serving_host(pid: int) -> bool:
    """Guard against pid reuse: only signal a process that actually is a
    serving host (best-effort; non-Linux says yes)."""
    try:
        cmdline = Path(f"/proc/{pid}/cmdline").read_bytes()
    except OSError:
        return True
    return b"serving_host" in cmdline


def _pid_alive(pid: int | None) -> bool:
    if not pid or pid == os.getpid():
        return False
    try:
        os.kill(pid, 0)
        return True
    except (ProcessLookupError, PermissionError):
        return False


def stop(name: str) -> None:
    with _lock:
        ev = _starting.get(name)
    if ev is not None:
        # A start() is mid-construction: let it publish (outside the
        # module lock), then stop what it built — the behavior callers
        # had when construction itself held _lock.
        ev.wait()
    with _lock:
        running = _servers.pop(name, None)
    if running is not None:
        running.stop()
    reg = _load_registry()
    if name in reg:
        # A DEDICATED standalone host (another process) owns the server:
        # terminate it, then record the deliberate stop. In-process hosts
        # (notebooks, shared supervisors) are never signaled — their pid
        # on the record is informational.
        pid = reg[name].get("pid")
        if (running is None and reg[name].get("host") == "standalone"
                and _pid_alive(pid) and _is_serving_host(pid)):
            try:
                os.kill(pid, signal.SIGTERM)
                deadline = time.monotonic() + 10.0
                while time.monotonic() < deadline and _pid_alive(pid):
                    time.sleep(0.1)
                if _pid_alive(pid):
                    os.kill(pid, signal.SIGKILL)
                    deadline = time.monotonic() + 5.0
                    while time.monotonic() < deadline and _pid_alive(pid):
                        time.sleep(0.05)
            except (ProcessLookupError, PermissionError):
                pass
        with _registry_lock():
            reg = _load_registry()
            reg[name]["status"] = "Stopped"
            reg[name].pop("port", None)
            reg[name].pop("pid", None)
            _save_registry(reg)


def delete(name: str) -> None:
    stop(name)
    reg = _load_registry()
    reg.pop(name, None)
    _save_registry(reg)


def get_kafka_topic(name: str) -> str:
    """Per-serving inference-log topic (reference:
    ``serving.get_kafka_topic``)."""
    reg = _load_registry()
    if name not in reg:
        raise KeyError(f"serving {name!r} not found")
    return reg[name]["topic"]


def make_inference_request(
    name: str, data: dict[str, Any], verb: str = ":predict"
) -> dict[str, Any]:
    """POST the TF-Serving payload to the endpoint (reference:
    ``serving.make_inference_request(name, {"signature_name",
    "instances": [...]})``)."""
    req = urllib.request.Request(
        f"{_endpoint(name)}/v1/models/{name}{verb}",
        # Convenience client for the TF-Serving-shaped verbs; JSON is
        # that surface's contract.
        data=json.dumps(data).encode(),  # graftlint: disable=json-on-hot-wire
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        return json.loads(resp.read())


def get_model_status(name: str) -> dict[str, Any]:
    """``GET /v1/models/<name>`` — TF-Serving's model-status contract,
    extended with live ``engine`` telemetry (dispatch counts, slot
    occupancy, prefix hits, speculation acceptance) for
    ``model_server="LM"`` endpoints."""
    with urllib.request.urlopen(
        f"{_endpoint(name)}/v1/models/{name}", timeout=30
    ) as resp:
        return json.loads(resp.read())


def _endpoint(name: str) -> str:
    """Base URL of a RUNNING serving, or raise (the one definition of
    the registry/port/status preamble)."""
    reg = _load_registry()
    if name not in reg:
        raise KeyError(f"serving {name!r} not found")
    port = reg[name].get("port")
    if port is None or get_status(name) != "Running":
        raise RuntimeError(f"serving {name!r} is not running")
    return f"http://127.0.0.1:{port}"
