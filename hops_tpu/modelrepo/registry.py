"""Versioned model registry.

Reference: ``hops.model.export(path, name, metrics={})`` registering a
SavedModel/artifact dir under ``Models/<name>/<version>``, and
``model.get_best_model(name, metric, Metric.MAX)`` returning
``{'name','version','metrics'}`` (model_repo_and_serving.ipynb:241,
314-320; SURVEY.md §2.5).

A model here is whatever the user exports: a flax module+params bundle
(via :func:`save_flax`), a directory of artifacts, or any single file.
Every version carries ``model.json`` metadata.
"""

from __future__ import annotations

import json
import pickle
import shutil
import time
from pathlib import Path
from typing import Any

from hops_tpu.runtime import fs


class Metric:
    MAX = "max"
    MIN = "min"


def _models_root() -> Path:
    p = Path(fs.project_path("Models"))
    p.mkdir(parents=True, exist_ok=True)
    return p


def _next_version(name: str) -> int:
    d = _models_root() / name
    if not d.exists():
        return 1
    versions = [int(v.name) for v in d.iterdir() if v.name.isdigit()]
    return max(versions, default=0) + 1


def export(
    path: str | Path,
    name: str,
    metrics: dict[str, Any] | None = None,
    description: str = "",
) -> dict[str, Any]:
    """Register a local artifact file/dir as a new model version
    (reference: ``model.export``)."""
    src = Path(path)
    if not src.exists():
        raise FileNotFoundError(f"model artifact {src} does not exist")
    version = _next_version(name)
    dst = _models_root() / name / str(version)
    dst.mkdir(parents=True, exist_ok=True)
    if src.is_dir():
        shutil.copytree(src, dst, dirs_exist_ok=True)
    else:
        shutil.copy2(src, dst / src.name)
    meta = {
        "name": name,
        "version": version,
        "metrics": {k: _num(v) for k, v in (metrics or {}).items()},
        "description": description,
        "created": time.time(),
        "path": str(dst),
    }
    (dst / "model.json").write_text(json.dumps(meta, indent=2, default=str))
    return meta


def save_flax(
    model: Any,
    params: Any,
    name: str,
    metrics: dict[str, Any] | None = None,
    extra_variables: dict[str, Any] | None = None,
    description: str = "",
) -> dict[str, Any]:
    """Export a flax module + trained variables as a servable bundle.

    The module (a dataclass) and param pytree are pickled together with
    any extra collections (e.g. ``batch_stats``); ``serving`` knows how
    to load and apply the bundle.
    """
    import tempfile

    with tempfile.TemporaryDirectory() as tmp:
        bundle = {
            "format": "flax-pickle-v1",
            "module": model,
            "params": params,
            "extra_variables": extra_variables or {},
        }
        p = Path(tmp) / "flax_model.pkl"
        p.write_bytes(pickle.dumps(bundle))
        return export(Path(tmp), name, metrics=metrics, description=description)


def load_flax(name: str, version: int | None = None) -> dict[str, Any]:
    meta = get_model(name, version)
    bundle_path = Path(meta["path"]) / "flax_model.pkl"
    return pickle.loads(bundle_path.read_bytes())


def list_models(name: str | None = None) -> list[dict[str, Any]]:
    out = []
    for model_dir in sorted(_models_root().iterdir() if name is None else [_models_root() / name]):
        if not model_dir.is_dir():
            continue
        for vdir in sorted(model_dir.iterdir(), key=lambda v: int(v.name) if v.name.isdigit() else 0):
            meta_file = vdir / "model.json"
            if meta_file.exists():
                out.append(json.loads(meta_file.read_text()))
    return out


def get_model(name: str, version: int | None = None) -> dict[str, Any]:
    versions = list_models(name)
    if not versions:
        raise KeyError(f"model {name!r} not found")
    if version is None:
        return versions[-1]
    for m in versions:
        if m["version"] == version:
            return m
    raise KeyError(f"model {name!r} version {version} not found")


def get_best_model(name: str, metric: str, direction: str = Metric.MAX) -> dict[str, Any]:
    """Best version by a metric (reference: ``model.get_best_model(name,
    'accuracy', Metric.MAX)``)."""
    candidates = [
        m for m in list_models(name)
        if isinstance(m.get("metrics", {}).get(metric), (int, float))
    ]
    if not candidates:
        raise KeyError(f"no versions of {name!r} carry numeric metric {metric!r}")
    key = lambda m: m["metrics"][metric]  # noqa: E731
    return max(candidates, key=key) if direction == Metric.MAX else min(candidates, key=key)


def _num(v: Any) -> Any:
    try:
        return float(v)
    except (TypeError, ValueError):
        return str(v)
