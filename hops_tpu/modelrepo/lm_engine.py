"""Continuous batching for LM serving — slot-based decode scheduling.

Beyond-reference capability (the reference's serving is one-shot
classifier REST calls — SURVEY.md §2.5): requests of different prompt
lengths and generation budgets share one fixed set of decode *slots*.
Each engine iteration runs ONE decode dispatch for every live slot;
a request that finishes frees its slot immediately and the next queued
request takes it — no head-of-line blocking on the longest generation,
which is where static-batch serving loses its throughput.

TPU-shaped throughout:

- The per-layer KV caches are ONE ``(slots, heads, capacity, d)``
  buffer per layer, alive across requests. The cache index is a
  ``(slots,)`` vector (``TransformerLM(ragged_decode=True)``), so every
  slot advances independently and ``decode_attention`` masks/clamps
  each row's DMA by its own length (``ops/attention.py`` ragged path).
- A handful of compiled programs, all static-shape: *batched prefill*
  (one per prompt-length bucket, full-slot batch with per-row ragged
  true lengths — every request entering a free slot in the same
  iteration shares ONE dispatch), *insert-batch* (one vectorized
  masked merge into the persistent cache), the per-request *append*
  (prefix-cache admissions), and *step* (one token for all slots).
  Admission and completion are host-side bookkeeping — no recompiles
  at any request mix.
- Free slots stay in the batch: the step program clamps their cache
  index to 0 (an ``active`` mask), so a free row writes one position,
  attends one block, and its token is discarded host-side — noise,
  regardless of how long the slot's previous occupant was.

Greedy decoding (temperature 0) — the contract is that interleaved
continuous batching emits EXACTLY what per-request ``generate(...,
temperature=0)`` would (tests/test_lm_engine.py parity).
"""

from __future__ import annotations

import collections
import dataclasses
import functools
import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from hops_tpu.models.generation import top_p_mask
from hops_tpu.modelrepo.paged import BlockPool
from hops_tpu.runtime import faultinject, flight, qos
from hops_tpu.runtime.logging import get_logger
from hops_tpu.telemetry.metrics import REGISTRY

log = get_logger(__name__)


def _map_cache(cache: Any, fn_kv, fn_idx, *rest: Any, fn_pages=None) -> Any:
    """Apply ``fn_kv`` to k/v/scale leaves, ``fn_idx`` to the 'idx'
    leaves, and ``fn_pages`` (default: ``fn_kv``) to the 'pages' leaves
    of a transformer KV-cache pytree (the same layout contract as
    generation._rewind; 'pages' exists only on paged caches). Extra
    trees in ``rest`` (same treedef) are zipped leaf-for-leaf into the
    callbacks — the single definition of "walk a cache by leaf role"
    in this module."""
    import jax.tree_util as jtu

    hits = 0

    def fix(path, leaf, *others):
        nonlocal hits
        name = str(path[-1].key) if hasattr(path[-1], "key") else ""
        if name == "idx":
            hits += 1
            return fn_idx(leaf, *others)
        if name == "pages" and fn_pages is not None:
            return fn_pages(leaf, *others)
        return fn_kv(leaf, *others)

    out = jtu.tree_map_with_path(fix, cache, *rest)
    if not hits:
        raise ValueError(
            "cache has no 'idx' leaves — LMEngine requires the "
            "transformer KV-cache layout (transformer.py _decode_attend)"
        )
    return out


def _clamp_idx(cache: Any, active: Any) -> Any:
    """Clamp inactive rows' cache index to 0 (the free-slot
    convention): a free row writes one position, attends one block,
    and its output is discarded host-side. On a PAGED cache the row's
    page table is zeroed too, so that one write lands in the reserved
    scratch block — a dead row pointing at its old pages would scribble
    garbage into physical blocks that may already be shared or
    reallocated."""
    return _map_cache(
        cache, lambda leaf: leaf, lambda idx: jnp.where(active, idx, 0),
        fn_pages=lambda pg: jnp.where(active[:, None], pg, 0),
    )


def _rewind_idx(cache: Any, new_idx: Any) -> Any:
    """Set every layer's cache index to ``new_idx`` (per-row)."""
    return _map_cache(
        cache, lambda leaf: leaf,
        lambda idx: jnp.asarray(new_idx, idx.dtype),
    )


def _get_idx(cache: Any) -> Any:
    """The cache-index vector: every layer's idx leaf carries the same
    value (transformer.py advances them in lockstep); return the
    first."""
    for path, leaf in jax.tree_util.tree_leaves_with_path(cache):
        name = str(path[-1].key) if hasattr(path[-1], "key") else ""
        if name == "idx":
            return leaf
    raise ValueError("cache has no 'idx' leaves")


def _filter_rows(logits, temps, topks, topps, use_top_p=False):
    """The per-row sampling filter: temperature-scale, top-k-mask, and
    (``use_top_p``, static) nucleus-mask (rows, vocab) logits.
    ``temps[i] <= 0`` rows divide by 1e-6 (a near-one-hot after
    softmax); paths with an exactness contract for greedy rows — the
    speculative rejection sampler, `_sample_rows`'s output — override
    those rows with exact argmax/one-hots rather than rely on it."""
    v = logits.shape[-1]
    logits = logits.astype(jnp.float32)
    srt = jnp.sort(logits, axis=-1)  # ascending
    k_eff = jnp.clip(jnp.where(topks > 0, topks, v), 1, v)
    kth = jnp.take_along_axis(srt, (v - k_eff)[:, None], axis=-1)
    masked = jnp.where(logits < kth, -jnp.inf, logits)
    scaled = masked / jnp.maximum(temps, 1e-6)[:, None]
    if use_top_p:
        # Reuse the ascending top-k sort: value-mask (ties kept, same
        # multiset as `masked`) and temperature-scale it descending —
        # top_p_mask then skips its own full-vocab sort.
        srt_desc = srt[:, ::-1]
        srt_desc = jnp.where(srt_desc >= kth, srt_desc, -jnp.inf)
        srt_desc = srt_desc / jnp.maximum(temps, 1e-6)[:, None]
        scaled = top_p_mask(scaled, topps, sorted_desc=srt_desc)
    return scaled


def _sample_rows(logits, temps, topks, topps, seeds, ns, use_top_p=False):
    """Per-row sampling over (rows, vocab) logits: ``temps[i] <= 0`` is
    greedy; ``topks[i] > 0`` keeps the top-k logits; ``0 < topps[i] <
    1`` applies the nucleus filter on top. Keys derive in-graph from
    (request seed, token index) — a pure function, so a request's
    output is independent of slot placement and of what else shares
    the batch, and the host never touches the backend to build keys.
    Vectorized so greedy and sampled requests share one dispatch.
    ``use_top_p`` is static: the nucleus filter costs a second
    full-vocab sort + softmax + cumsum, so workloads with no top_p
    request never pay it."""
    keys = jax.vmap(
        lambda sd, n: jax.random.fold_in(jax.random.PRNGKey(sd), n)
    )(seeds, ns)
    greedy = jnp.argmax(logits.astype(jnp.float32), axis=-1).astype(jnp.int32)
    scaled = _filter_rows(logits, temps, topks, topps, use_top_p)
    sampled = jax.vmap(jax.random.categorical)(keys, scaled).astype(jnp.int32)
    return jnp.where(temps <= 0.0, greedy, sampled)


@dataclasses.dataclass
class _Request:
    ticket: int
    prompt: np.ndarray  # (L,) int32
    max_new_tokens: int
    eos_id: int | None
    temperature: float = 0.0
    top_k: int = 0  # 0 = no top-k truncation
    top_p: float = 0.0  # 0 = no nucleus truncation
    seed: int = 0
    # Snapshot taken at submit time: re-registering the name later must
    # not invalidate this request's capacity validation or swap its
    # prefix mid-queue. Dense engine: (target_cache,
    # draft_cache_or_None, length); paged engine: a _PagedPrefix.
    prefix: Any = None
    # The prefix_id this request was submitted under (None = no
    # prefix): the admission-ordering key that groups same-prefix
    # requests into one wave so they share cached pages/caches.
    prefix_key: str | None = None
    # monotonic submit time — the TTFT histogram's start mark.
    submitted_at: float = 0.0
    # QoS class (interactive | batch): admission serves interactive
    # first under the engine's starvation guard.
    priority: str = "interactive"
    # Preemption restarts a request from scratch (deterministic
    # sampling makes the replayed stream identical); its TTFT was
    # already observed the first time around.
    ttft_observed: bool = False


@dataclasses.dataclass
class _PagedPrefix:
    """A registered prefix on the PAGED engine: tokens at registration,
    and — once the first request that names it finishes its prefill —
    the physical blocks holding the prefix's COMPLETE pages, each
    carrying one registry reference. Later admissions point their page
    tables at these blocks (pool.ref per reader) and re-compute only
    from the first incomplete block: page-table sharing with
    copy-on-write at the divergence boundary."""

    name: str
    tokens: np.ndarray  # (L,) int32
    blocks: list[int] | None = None  # full pages only: L // page blocks


@dataclasses.dataclass
class _SlotState:
    ticket: int
    emitted: list[int]
    remaining: int
    eos_id: int | None
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 0.0
    seed: int = 0
    n_sampled: int = 1  # tokens drawn so far (prefill's counts as #0)
    # --- paged-engine scheduling state (None/0 on the dense engine) ---
    req: Any = None  # the _Request, for preemption requeue
    pending: np.ndarray | None = None  # un-prefilled prompt tail
    base_len: int = 0  # true tokens written so far (device idx mirror)
    prompt_total: int = 0  # prefix + prompt length
    worst_len: int = 0  # deepest position this request can ever write
    blocks: list[int] | None = None  # physical blocks, logical order
    shared_hit: bool = False  # admission reused prefix pages
    seq: int = 0  # admission order — preemption picks the newest


class LMEngine:
    """Continuous-batching scheduler over ``slots`` concurrent decodes.

    ``model`` must be built with ``ragged_decode=True`` and its
    ``max_decode_len`` must cover every request's prompt + generation.
    ``submit()`` enqueues and returns a ticket; ``step()`` runs one
    engine iteration (admit into free slots, then one decode dispatch);
    ``run()`` drains everything and returns ``{ticket: tokens}``.

    ``decode_horizon`` scans that many decode steps on-device per
    dispatch, amortizing host-dispatch latency (the measured serving
    bottleneck — BENCHMARKS.md round-4 hardware notes) at the cost of
    admitting new requests only at horizon boundaries and of wasted
    steps for rows that retire mid-horizon. Output tokens are
    IDENTICAL for any horizon (an in-graph live mask retires rows at
    their budget/eos exactly as the host loop would).

    ``mesh`` serves a model too big for one chip: every program runs
    tensor-parallel over ``tp_axis`` (Megatron head/hidden sharding,
    ``parallel/tp_inference.py`` — the dense checkpoint is sliced in
    place, the KV caches live head-sharded, and output is identical to
    the unsharded engine for the full knob surface).

    The three levers COMPOSE: ``draft_model`` + ``decode_horizon`` runs
    the whole draft/score/accept loop ``horizon`` times per dispatch
    (up to ``horizon * spec_k`` tokens per host round-trip — the
    configuration that matters when per-dispatch latency, not chip
    time, bounds serving throughput), and either or both run
    tensor-parallel under ``mesh``.

    ``kv_page_size`` switches the MEMORY core to the paged layout:
    per-layer caches become one shared block pool of
    ``kv_pool_blocks`` pages plus per-slot page tables
    (``transformer.paged_decode`` + ``ops.paged_decode_attention``), so
    persistent HBM is bounded by LIVE tokens rather than
    ``slots x max_decode_len`` — more concurrent slots at equal memory.
    Blocks allocate on demand as decode advances and free on
    completion; a dry pool queues admissions and, for live decode
    growth, preempts the newest request (replayed deterministically).
    Prefix-cache hits become page-table sharing with copy-on-write at
    the first incomplete block. Prompts prefill in ``prefill_chunk``-
    token chunks FUSED into the decode dispatch (chunked prefill), so
    a long prompt's admission no longer freezes tokens-out for every
    live slot. Token streams are bit-identical to the dense engine
    (tests/test_lm_engine.py paged parity), and the paged layout
    composes with speculation (draft pool pages ride the same table)
    and with ``mesh`` (pools shard on their head axis,
    ``tp_inference.tp_cache_specs``).
    """

    def __init__(
        self,
        model: Any,
        params: Any,
        slots: int = 4,
        prefill_buckets: tuple[int, ...] | None = None,
        decode_horizon: int = 1,
        mesh: Any = None,
        tp_axis: str = "model",
        draft_model: Any = None,
        draft_params: Any = None,
        spec_k: int = 4,
        kv_page_size: int | None = None,
        kv_pool_blocks: int | None = None,
        prefill_chunk: int | None = None,
        max_queue: int = 1024,
    ):
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        #: Admission bound on :meth:`submit`: beyond this many queued
        #: requests submit raises :class:`~hops_tpu.runtime.qos.QueueFullError`
        #: (a ShedError) — backpressure surfaces at the door as a typed
        #: 503 instead of an unbounded deque eating the host.
        self.max_queue = int(max_queue)
        if not getattr(model, "ragged_decode", False):
            raise ValueError(
                "LMEngine requires TransformerLM(ragged_decode=True) — "
                "the (slots,) cache index is what lets rows advance "
                "independently"
            )
        # --- paged KV cache + chunked prefill (the serving memory core)
        # ``kv_page_size`` switches the engine to the paged layout:
        # per-layer caches become a shared block pool plus per-slot page
        # tables (transformer.paged_decode), slot memory is bounded by
        # LIVE tokens instead of slots x max_decode_len, prefix-cache
        # hits become page-table sharing, and long prompts prefill in
        # ``prefill_chunk``-token chunks fused into the same dispatch as
        # the decode step (no admission freeze for live slots).
        self._paged = kv_page_size is not None
        if self._paged:
            if kv_page_size < 1:
                raise ValueError(f"kv_page_size must be >= 1, got {kv_page_size}")
            if getattr(model, "kv_cache_dtype", None) not in (None, "int8"):
                raise ValueError(
                    "paged engine supports kv_cache_dtype None or 'int8' "
                    f"(got {model.kv_cache_dtype!r})"
                )
            cap0 = model.max_decode_len
            max_blocks = -(-cap0 // kv_page_size)
            if kv_pool_blocks is None:
                # Parity default: same token capacity as the dense
                # reservation (+ the reserved scratch block). Shrink it
                # to actually SAVE memory; the scheduler queues/preempts
                # when it runs dry.
                kv_pool_blocks = 1 + slots * max_blocks
            if kv_pool_blocks < 2:
                raise ValueError(
                    f"kv_pool_blocks must be >= 2, got {kv_pool_blocks}"
                )
            self._page_size = int(kv_page_size)
            self._max_blocks = max_blocks
            self.prefill_chunk = int(prefill_chunk or min(64, cap0))
            if not 1 <= self.prefill_chunk <= cap0:
                raise ValueError(
                    f"prefill_chunk must be in [1, {cap0}], got "
                    f"{self.prefill_chunk}"
                )
            model = model.clone(
                paged_decode=True, kv_page_size=self._page_size,
                kv_pool_blocks=int(kv_pool_blocks),
            )
            if draft_model is not None:
                if draft_model.max_decode_len != cap0:
                    raise ValueError(
                        "paged speculative engine needs "
                        "draft.max_decode_len == model.max_decode_len "
                        f"({draft_model.max_decode_len} != {cap0}) — the "
                        "two pools share one page table"
                    )
                draft_model = draft_model.clone(
                    paged_decode=True, kv_page_size=self._page_size,
                    kv_pool_blocks=int(kv_pool_blocks),
                )
            self._pool = BlockPool(int(kv_pool_blocks))
            self._pages_np = np.zeros((slots, max_blocks), np.int32)
            self._pages_dirty = True
            # True when some LIVE row rode a dispatch inert (its device
            # idx scratch-clamped in-graph): the next decode dispatch
            # must re-graft the host mirror.
            self._idx_stale = False
        elif prefill_chunk is not None:
            raise ValueError(
                "prefill_chunk requires the paged cache (kv_page_size=): "
                "chunked prefill writes in place through page tables"
            )
        else:
            self._pool = None
            self.prefill_chunk = None
        self.model = model
        self.params = params
        self.slots = slots
        if decode_horizon < 1:
            raise ValueError(f"decode_horizon must be >= 1, got {decode_horizon}")
        self.decode_horizon = decode_horizon
        # Speculative decoding (greedy): the draft proposes spec_k - 1
        # tokens per dispatch and the target scores the chunk in one
        # ragged warm append. Unlike generate_speculative's scalar-min
        # acceptance, each SLOT accepts its own a_r tokens — the ragged
        # (slots,) cache index is what makes per-row acceptance free.
        self.draft_model = draft_model
        self.draft_params = draft_params
        self.spec_k = spec_k if draft_model is not None else 0
        if draft_model is not None:
            if spec_k < 2:
                raise ValueError(f"spec_k must be >= 2, got {spec_k}")
            if not getattr(draft_model, "ragged_decode", False):
                raise ValueError("draft_model needs ragged_decode=True too")
            # Speculation composes with BOTH other levers (round-4
            # review item #3): decode_horizon runs the whole
            # draft/score/accept loop ``horizon`` times inside one
            # dispatch (the high-RTT configuration the dispatch-floor
            # analysis asks for), and mesh= runs every spec program
            # tensor-parallel like the non-spec engine.
        # Tensor parallelism: every engine program runs inside a
        # shard_map over ``tp_axis`` — params and KV caches shard on
        # their head axes (parallel/tp_inference.py layout), scalars
        # and token vectors replicate, and the per-block psums are the
        # only cross-device traffic. Output is identical to the
        # unsharded engine.
        self.mesh = mesh
        local_model = model
        local_draft = draft_model
        param_specs = cache_specs = None
        draft_param_specs = draft_cache_specs = None
        if mesh is not None:
            from jax.sharding import NamedSharding

            from hops_tpu.parallel.tp_inference import tp_param_specs

            local_model = model.clone(
                tp_axis=tp_axis, tp_shards=mesh.shape[tp_axis]
            )
            param_specs = tp_param_specs(params, tp_axis)
            # Shard the checkpoint NOW: the whole point of mesh= is a
            # model too big for one chip, so the weights must live in
            # the Megatron layout rather than be re-laid-out from a
            # single-device resident on every dispatch.
            params = jax.tree.map(
                lambda leaf, spec: jax.device_put(
                    leaf, NamedSharding(mesh, spec)
                ),
                params, param_specs,
            )
            self.params = params
            if draft_model is not None:
                # The draft shards the same Megatron way: its heads must
                # divide the tp degree just like the target's.
                shards = mesh.shape[tp_axis]
                dh = getattr(draft_model, "num_kv_heads", None) or draft_model.num_heads
                if draft_model.num_heads % shards or dh % shards:
                    raise ValueError(
                        f"draft heads {draft_model.num_heads}/{dh} not "
                        f"divisible by tp degree {shards}"
                    )
                local_draft = draft_model.clone(tp_axis=tp_axis, tp_shards=shards)
                draft_param_specs = tp_param_specs(draft_params, tp_axis)
                draft_params = jax.tree.map(
                    lambda leaf, spec: jax.device_put(
                        leaf, NamedSharding(mesh, spec)
                    ),
                    draft_params, draft_param_specs,
                )
                self.draft_params = draft_params
        cap = model.max_decode_len
        if prefill_buckets is None:
            prefill_buckets = tuple(
                b for b in (16, 32, 64, 128, 256, 512, 1024, 2048, 4096) if b < cap
            ) or (cap,)
        self.prefill_buckets = tuple(sorted(prefill_buckets))

        # The persistent cache: init with a (slots, 1) dummy step, then
        # zero every leaf — idx zeros mark all slots free.
        dummy = jnp.zeros((slots, 1), jnp.int32)
        _, variables = model.apply(
            {"params": params}, dummy, decode=True, mutable=["cache"]
        )
        self._cache = _map_cache(
            variables["cache"], jnp.zeros_like, jnp.zeros_like
        )
        self._draft_cache = None
        if draft_model is not None:
            _, dvariables = draft_model.apply(
                {"params": draft_params}, dummy, decode=True, mutable=["cache"]
            )
            self._draft_cache = _map_cache(
                dvariables["cache"], jnp.zeros_like, jnp.zeros_like
            )
        if mesh is not None:
            # Dense: (slots, heads, ...) k/v/scale leaves shard on the
            # head dim. Paged: (kv_heads, blocks, page, d) pools shard
            # on their leading head dim; the replicated page table
            # indexes the same logical blocks on every shard. One
            # definition for both layouts: tp_inference.tp_cache_specs.
            from hops_tpu.parallel.tp_inference import tp_cache_specs

            cache_specs = tp_cache_specs(
                self._cache, tp_axis, paged=self._paged
            )
            self._cache = jax.tree.map(
                lambda leaf, spec: jax.device_put(
                    leaf, NamedSharding(mesh, spec)
                ),
                self._cache, cache_specs,
            )
            if self._draft_cache is not None:
                draft_cache_specs = tp_cache_specs(
                    self._draft_cache, tp_axis, paged=self._paged
                )
                self._draft_cache = jax.tree.map(
                    lambda leaf, spec: jax.device_put(
                        leaf, NamedSharding(mesh, spec)
                    ),
                    self._draft_cache, draft_cache_specs,
                )

        def sharded(body, in_specs, out_specs):
            if mesh is None:
                return body
            from jax.experimental.shard_map import shard_map

            return shard_map(
                body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                check_rep=False,
            )

        # Rebuild templates for dispatch-failure recovery: a wave that
        # raised AFTER donation consumed the old cache buffers, and the
        # failed requests' state is discarded anyway — _fail_inflight
        # re-materializes fresh all-free caches from these specs so the
        # scheduler really does keep serving (not just for errors that
        # fired before dispatch).
        def cache_tmpl(cache):
            return jax.tree.map(
                lambda leaf: jax.ShapeDtypeStruct(
                    leaf.shape, leaf.dtype, sharding=leaf.sharding
                ),
                cache,
            )

        self._cache_tmpl = cache_tmpl(self._cache)
        self._draft_cache_tmpl = (
            cache_tmpl(self._draft_cache)
            if self._draft_cache is not None else None
        )

        self._queue: collections.deque[_Request] = collections.deque()
        self._slot_state: list[_SlotState | None] = [None] * slots
        self._results: dict[int, list[int]] = {}
        self._next_ticket = 0
        # Priority admission: interactive requests claim free slots
        # first, with the guard forcing a batch admission after at most
        # `starvation_limit` consecutive interactive ones — batch makes
        # progress under ANY sustained interactive load.
        self._admission_guard = qos.StarvationGuard(limit=8)

        # --- the compiled programs (see module docstring) ---------------
        def _admit_tail(logits, variables, true_len, end_len, temp, topk,
                        topp, seed, sampled, nucleus):
            """Shared tail of both admission programs: pick the last
            true row's logits, draw/argmax the first token, rewind the
            cache index to the true end (pad garbage past it stays
            masked forever — kernel invariant:
            test_decode_attention_ignores_garbage_past_valid_len)."""
            last = jax.lax.dynamic_index_in_dim(
                logits[0], true_len - 1, axis=0, keepdims=False
            )
            if sampled:
                first_tok = _sample_rows(
                    last[None], temp[None], topk[None], topp[None],
                    seed[None], jnp.zeros((1,), jnp.int32),
                    use_top_p=nucleus,
                )[0]
            else:
                first_tok = jnp.argmax(last, axis=-1).astype(jnp.int32)
            cache = _map_cache(
                variables["cache"],
                lambda leaf: leaf,
                lambda idx: jnp.full_like(idx, end_len),
            )
            return first_tok, cache

        @functools.partial(jax.jit, static_argnames=("sampled", "nucleus"))
        def prefill(params, padded_prompt, true_len, temp, topk, topp, seed,
                    sampled=False, nucleus=False):
            def body(params, padded_prompt, true_len, temp, topk, topp, seed):
                # b=1 fresh cache.
                logits, variables = local_model.apply(
                    {"params": params}, padded_prompt, decode=True,
                    mutable=["cache"],
                )
                return _admit_tail(
                    logits, variables, true_len, true_len, temp, topk, topp,
                    seed, sampled, nucleus,
                )

            body = sharded(
                body, (param_specs,) + (P(),) * 6, (P(), cache_specs)
            )
            return body(params, padded_prompt, true_len, temp, topk, topp, seed)

        @functools.partial(jax.jit, static_argnames=("sampled", "nucleus"))
        def append(params, cache, padded_suffix, base_len, true_len, temp,
                   topk, topp, seed, sampled=False, nucleus=False):
            def body(params, cache, padded_suffix, base_len, true_len, temp,
                     topk, topp, seed):
                # Warm-cache chunk append onto a COPY of a registered
                # prefix cache (not donated — the stored prefix is
                # reused by every request that names it). The apply
                # writes the whole padded bucket at offset base_len;
                # garbage rows past true_len are causally invisible to
                # true rows during the append.
                logits, variables = local_model.apply(
                    {"params": params, "cache": cache},
                    padded_suffix,
                    decode=True,
                    mutable=["cache"],
                )
                return _admit_tail(
                    logits, variables, true_len, base_len + true_len,
                    temp, topk, topp, seed, sampled, nucleus,
                )

            body = sharded(
                body, (param_specs, cache_specs) + (P(),) * 7,
                (P(), cache_specs),
            )
            return body(params, cache, padded_suffix, base_len, true_len,
                        temp, topk, topp, seed)

        @functools.partial(jax.jit, static_argnames=("sampled", "nucleus"))
        def spec_append(params, dparams, t_cache, d_cache, padded_suffix,
                        base_len, true_len, temp, topk, topp, seed,
                        sampled=False, nucleus=False):
            # Prefix-cache admission on a speculative engine: the
            # suffix appends onto COPIES of BOTH stored prefix caches
            # (not donated — the prefixes are reused), and both indices
            # rewind to base_len + true_len so target and draft enter
            # the first speculative dispatch at the same position.
            def body(params, dparams, t_cache, d_cache, padded_suffix,
                     base_len, true_len, temp, topk, topp, seed):
                logits, t_vars = local_model.apply(
                    {"params": params, "cache": t_cache}, padded_suffix,
                    decode=True, mutable=["cache"],
                )
                _, d_vars = local_draft.apply(
                    {"params": dparams, "cache": d_cache}, padded_suffix,
                    decode=True, mutable=["cache"],
                )
                first_tok, t_cache2 = _admit_tail(
                    logits, t_vars, true_len, base_len + true_len,
                    temp, topk, topp, seed, sampled, nucleus,
                )
                d_cache2 = _map_cache(
                    d_vars["cache"], lambda leaf: leaf,
                    lambda idx: jnp.full_like(idx, base_len + true_len),
                )
                return first_tok, t_cache2, d_cache2

            body = sharded(
                body,
                (param_specs, draft_param_specs, cache_specs,
                 draft_cache_specs) + (P(),) * 7,
                (P(), cache_specs, draft_cache_specs),
            )
            return body(params, dparams, t_cache, d_cache, padded_suffix,
                        base_len, true_len, temp, topk, topp, seed)

        def insert(big, one, row, true_len):
            # The b=1 tree shares the big tree's treedef — only the
            # leading dims differ — so _map_cache zips them.
            return _map_cache(
                big,
                lambda big_leaf, one_leaf: jax.lax.dynamic_update_slice(
                    big_leaf, one_leaf, (row,) + (0,) * (big_leaf.ndim - 1)
                ),
                lambda big_idx, _one: jax.lax.dynamic_update_slice(
                    big_idx, jnp.asarray([true_len], big_idx.dtype), (row,)
                ),
                one,
            )

        # -- batched admission --------------------------------------------
        # Admission used to cost TWO dispatches PER REQUEST (b=1 prefill
        # + row insert). On a dispatch-latency-bound link that tax
        # dominates ragged workloads (measured: 84 ms/dispatch on the
        # relay, HW step=decode_continuous — 24 of the 68+ dispatches
        # were admissions). Now every request entering a free slot in
        # the same engine iteration shares ONE full-slot-batch prefill
        # (per-row ragged true lengths; un-admitted rows are zero
        # prompts whose cache index rewinds to 0 = the free-slot
        # convention) and ONE vectorized merge into the big cache.
        # Compiles are keyed by (bucket, sampled, nucleus) only — batch
        # is always `slots` — so the program count matches the old
        # per-request path's.
        @functools.partial(jax.jit, static_argnames=("sampled", "nucleus"))
        def prefill_batch(params, padded, true_lens, temps, topks, topps,
                          seeds, sampled=False, nucleus=False):
            def body(params, padded, true_lens, temps, topks, topps, seeds):
                logits, variables = local_model.apply(
                    {"params": params}, padded, decode=True, mutable=["cache"]
                )
                # Pad garbage past each row's true length stays masked
                # forever once idx rewinds (kernel invariant) — same as
                # the per-request path.
                return _batched_admit_tail(
                    logits, variables, true_lens, temps, topks, topps,
                    seeds, sampled, nucleus,
                )

            body = sharded(
                body, (param_specs,) + (P(),) * 6, (P(), cache_specs)
            )
            return body(params, padded, true_lens, temps, topks, topps, seeds)

        @functools.partial(jax.jit, static_argnames=("sampled", "nucleus"))
        def spec_prefill_batch(params, dparams, padded, true_lens, temps,
                               topks, topps, seeds, sampled=False,
                               nucleus=False):
            def body(params, dparams, padded, true_lens, temps, topks,
                     topps, seeds):
                logits, t_vars = local_model.apply(
                    {"params": params}, padded, decode=True, mutable=["cache"]
                )
                _, d_vars = local_draft.apply(
                    {"params": dparams}, padded, decode=True, mutable=["cache"]
                )
                toks, t_cache = _batched_admit_tail(
                    logits, t_vars, true_lens, temps, topks, topps, seeds,
                    sampled, nucleus,
                )
                d_cache = _map_cache(
                    d_vars["cache"], lambda leaf: leaf,
                    lambda idx: jnp.asarray(true_lens, idx.dtype),
                )
                return toks, t_cache, d_cache

            body = sharded(
                body, (param_specs, draft_param_specs) + (P(),) * 6,
                (P(), cache_specs, draft_cache_specs),
            )
            return body(params, dparams, padded, true_lens, temps, topks,
                        topps, seeds)

        def insert_batch(big, rows_cache, admit, true_lens):
            # One vectorized merge: the batched prefill's cache shares
            # the big cache's full (slots, ...) shape, so admission is
            # a masked where per leaf — no per-row dispatches.
            def merge_kv(b, r):
                m = admit.reshape((slots,) + (1,) * (b.ndim - 1))
                return jnp.where(m, r, b)

            def merge_idx(b_idx, r_idx):
                return jnp.where(admit, jnp.asarray(true_lens, b_idx.dtype), b_idx)

            return _map_cache(big, merge_kv, merge_idx, rows_cache)

        def _step_logits(params, cache, tokens, active):
            # Clamp free rows' cache index to 0 BEFORE the apply: the
            # decode write advances every row's idx, so without this a
            # freed slot would keep its final length (streaming its
            # whole stale cache each dispatch) and then grow without
            # bound. Clamped, a free row writes one position at offset
            # 0 and attends one block — actually "noise".
            cache = _clamp_idx(cache, active)
            logits, variables = local_model.apply(
                {"params": params, "cache": cache},
                tokens[:, None],
                decode=True,
                mutable=["cache"],
            )
            return logits[:, -1], variables["cache"]

        # Two step programs: the all-greedy dispatch (the default
        # workload) pays one argmax, not a full-vocab sort + discarded
        # Gumbel draw; the sampled program serves mixed batches (its
        # greedy rows selected inside _sample_rows).
        def step_greedy(params, cache, tokens, active):
            def body(params, cache, tokens, active):
                last, cache2 = _step_logits(params, cache, tokens, active)
                return jnp.argmax(last, axis=-1).astype(jnp.int32), cache2

            body = sharded(
                body, (param_specs, cache_specs, P(), P()),
                (P(), cache_specs),
            )
            return body(params, cache, tokens, active)

        def step_sampled(params, cache, tokens, active, temps, topks, topps,
                         seeds, ns, nucleus=False):
            def body(params, cache, tokens, active, temps, topks, topps,
                     seeds, ns):
                last, cache2 = _step_logits(params, cache, tokens, active)
                return _sample_rows(
                    last, temps, topks, topps, seeds, ns, use_top_p=nucleus
                ), cache2

            body = sharded(
                body, (param_specs, cache_specs) + (P(),) * 7,
                (P(), cache_specs),
            )
            return body(params, cache, tokens, active, temps, topks, topps,
                        seeds, ns)

        def _decode_scan(params, cache, tok0, live0, n0, rem0, eos_ids,
                         temps, topks, topps, seeds, *, horizon, sampled,
                         nucleus):
            """``horizon`` decode steps under one lax.scan with in-graph
            retirement — THE single definition of the live-mask
            semantics (budget decrement, emit-then-finish eos,
            live-going-in output convention) that step_horizon,
            offline_wave, and the host-side account() all rely on
            staying bit-identical. Returns ((horizon, slots) tokens,
            live-going-in mask, final cache)."""

            def body(carry, _):
                cache, tok, live, n, rem = carry
                last, cache = _step_logits(params, cache, tok, live)
                if sampled:
                    nxt = _sample_rows(
                        last, temps, topks, topps, seeds, n, use_top_p=nucleus
                    )
                else:
                    nxt = jnp.argmax(last, axis=-1).astype(jnp.int32)
                n2 = n + live.astype(jnp.int32)
                rem2 = rem - live.astype(jnp.int32)
                live2 = live & (rem2 > 0) & (nxt != eos_ids)
                return (cache, nxt, live2, n2, rem2), (nxt, live)

            (cache2, _, _, _, _), (toks, lives) = jax.lax.scan(
                body, (cache, tok0, live0, n0, rem0), None, length=horizon
            )
            return toks, lives, cache2

        def _batched_admit_tail(logits, variables, true_lens, temps, topks,
                                topps, seeds, sampled, nucleus):
            """Shared tail of every batched admission program: per-row
            last-true-logit select, first-token draw (n=0 keys), and
            cache-index rewind to each row's true length."""
            last = jnp.take_along_axis(
                logits, jnp.maximum(true_lens - 1, 0)[:, None, None], axis=1
            )[:, 0]
            if sampled:
                tok0 = _sample_rows(
                    last, temps, topks, topps, seeds,
                    jnp.zeros((slots,), jnp.int32), use_top_p=nucleus,
                )
            else:
                tok0 = jnp.argmax(last, axis=-1).astype(jnp.int32)
            cache = _map_cache(
                variables["cache"], lambda leaf: leaf,
                lambda idx: jnp.asarray(true_lens, idx.dtype),
            )
            return tok0, cache

        # Horizon program: ``horizon`` decode steps in ONE dispatch via
        # the shared _decode_scan — the host-dispatch-latency
        # amortization (measured on the relay: per-token dispatch cost
        # ~84 ms RTT dominated engine throughput, BENCHMARKS.md "decode
        # knobs, hardware"). A dead row's cache index clamps to 0 (the
        # free-slot convention), so caches can never overrun
        # max_decode_len mid-horizon.
        def step_horizon(params, cache, tokens, live0, rems, eos_ids,
                         temps, topks, topps, seeds, ns, *, horizon, sampled,
                         nucleus=False):
            def run(params, cache, tokens, live0, rems, eos_ids, temps,
                    topks, topps, seeds, ns):
                return _decode_scan(
                    params, cache, tokens, live0, ns, rems, eos_ids,
                    temps, topks, topps, seeds,
                    horizon=horizon, sampled=sampled, nucleus=nucleus,
                )

            run = sharded(
                run, (param_specs, cache_specs) + (P(),) * 9,
                (P(), P(), cache_specs),
            )
            return run(params, cache, tokens, live0, rems, eos_ids, temps,
                       topks, topps, seeds, ns)

        # Offline wave: the whole lifetime of `slots` requests — ragged
        # prefill, first token, and the full decode scan with in-graph
        # retirement — FUSED into one compiled program, one dispatch.
        # This is the TPU-shaped answer to dispatch-latency-bound batch
        # inference: the host contributes nothing between a wave's
        # admission and its last token, so a W-wave workload costs W
        # dispatches total (vs 2 admissions + ceil(budget/horizon)
        # dispatches per wave online). Compiles key on
        # (bucket, horizon, sampled, nucleus); run_offline buckets the
        # horizon to powers of two so sorted workloads reuse programs.
        def offline_wave(params, padded, true_lens, rems, eos_ids, temps,
                         topks, topps, seeds, *, horizon, sampled,
                         nucleus=False):
            def run(params, padded, true_lens, rems, eos_ids, temps, topks,
                    topps, seeds):
                logits, variables = local_model.apply(
                    {"params": params}, padded, decode=True, mutable=["cache"]
                )
                tok0, cache = _batched_admit_tail(
                    logits, variables, true_lens, temps, topks, topps,
                    seeds, sampled, nucleus,
                )
                admit = true_lens > 0  # zero-length rows pad the wave
                rem0 = rems - admit.astype(jnp.int32)
                live0 = admit & (rem0 > 0) & (tok0 != eos_ids)
                toks, lives, _ = _decode_scan(
                    params, cache, tok0, live0,
                    jnp.ones((slots,), jnp.int32), rem0, eos_ids,
                    temps, topks, topps, seeds,
                    horizon=horizon, sampled=sampled, nucleus=nucleus,
                )
                return tok0, toks, lives

            run = sharded(
                run, (param_specs,) + (P(),) * 8, (P(), P(), P())
            )
            return run(params, padded, true_lens, rems, eos_ids, temps,
                       topks, topps, seeds)

        @functools.partial(jax.jit, static_argnames=("sampled", "nucleus"))
        def spec_prefill(params, dparams, padded_prompt, true_len, temp,
                         topk, topp, seed, sampled=False, nucleus=False):
            # Admission for a speculative engine: prefill BOTH caches
            # on the prompt; the target's last true row gives the
            # first token (drawn per the request's sampling knobs),
            # both indices rewind to the true end.
            def body(params, dparams, padded_prompt, true_len, temp, topk,
                     topp, seed):
                logits, t_vars = local_model.apply(
                    {"params": params}, padded_prompt, decode=True,
                    mutable=["cache"],
                )
                _, d_vars = local_draft.apply(
                    {"params": dparams}, padded_prompt, decode=True,
                    mutable=["cache"],
                )
                first_tok, t_cache = _admit_tail(
                    logits, t_vars, true_len, true_len, temp, topk, topp,
                    seed, sampled, nucleus,
                )
                d_cache = _map_cache(
                    d_vars["cache"], lambda leaf: leaf,
                    lambda idx: jnp.full_like(idx, true_len),
                )
                return first_tok, t_cache, d_cache

            body = sharded(
                body, (param_specs, draft_param_specs) + (P(),) * 6,
                (P(), cache_specs, draft_cache_specs),
            )
            return body(params, dparams, padded_prompt, true_len, temp,
                        topk, topp, seed)

        def _spec_core(params, dparams, t_cache, d_cache, tokens, active):
            # One speculative dispatch: the draft proposes spec_k - 1
            # greedy tokens per slot, the target scores each slot's
            # [token, proposals] chunk in ONE ragged warm append, and
            # every row keeps its own longest matching prefix a_r plus
            # the target prediction after it (bonus) — per-row
            # acceptance, which generate_speculative's scalar cache
            # index cannot do. Cache invariant: idx = written tokens
            # (the newest emitted token is unwritten); the dispatch
            # writes the current token plus the proposals, so both
            # indices rewind to idx0 + 1 + a_r per row. A shard-mappable
            # CORE: the single-dispatch jit, the tp wrapper, and the
            # horizon scan all call this same body.
            t_cache, d_cache = _clamp_idx(t_cache, active), _clamp_idx(d_cache, active)
            idx0 = _get_idx(t_cache)

            def dstep(carry, _):
                dc, tok = carry
                logits, dv = local_draft.apply(
                    {"params": dparams, "cache": dc}, tok[:, None],
                    decode=True, mutable=["cache"],
                )
                nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
                return (dv["cache"], nxt), nxt

            # spec_k steps, spec_k - 1 proposals: the last step's
            # proposal is discarded but its cache WRITE is load-bearing
            # — on full acceptance the rewind keeps position
            # idx0 + spec_k - 1, which only that step writes (same
            # invariant as generate_speculative's draft scan).
            (d_cache, _), drafts_t = jax.lax.scan(
                dstep, (d_cache, tokens), None, length=spec_k
            )
            drafts = jnp.moveaxis(drafts_t, 0, 1)[:, : spec_k - 1]
            chunk = jnp.concatenate([tokens[:, None], drafts], axis=1)
            logits, t_vars = local_model.apply(
                {"params": params, "cache": t_cache}, chunk, decode=True,
                mutable=["cache"],
            )
            t_cache = t_vars["cache"]
            preds = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            match = drafts == preds[:, : spec_k - 1]
            a_rows = jnp.argmin(
                jnp.concatenate([match, jnp.zeros((slots, 1), bool)], axis=1),
                axis=1,
            ).astype(jnp.int32)
            bonus = jnp.take_along_axis(preds, a_rows[:, None], axis=1)[:, 0]
            new_idx = jnp.where(active, idx0 + 1 + a_rows, 0)
            return (drafts, a_rows, bonus,
                    _rewind_idx(t_cache, new_idx), _rewind_idx(d_cache, new_idx))

        def _spec_core_sampled(params, dparams, t_cache, d_cache, tokens,
                               active, temps, topks, topps, seeds, ns,
                               *, nucleus):
            # Rejection-sampling speculation, PER ROW (the engine's
            # advantage over generate_speculative's batch-min): draft
            # samples proposals from its filtered q, target accepts
            # with prob min(1, p/q) (division-free u*q < p), and each
            # row's first rejected slot resamples from the residual
            # norm(max(p - q, 0)) — q zero-padded at the all-accepted
            # bonus slot, so that case reduces to sampling from p.
            # Greedy rows flow through the SAME math: temp <= 0 rows'
            # filtered distributions are exact one-hots, making
            # acceptance "argmax match" and the residual "target
            # argmax" — bit-identical to the greedy engine. Keys fold
            # (purpose, request seed, generated-token index); indices
            # of discarded proposals are reused next dispatch, which is
            # sound because discarded draws never influenced output.
            t_cache, d_cache = _clamp_idx(t_cache, active), _clamp_idx(d_cache, active)
            idx0 = _get_idx(t_cache)

            def keys_for(purpose, n_idx):
                return jax.vmap(
                    lambda sd, n: jax.random.fold_in(
                        jax.random.fold_in(jax.random.PRNGKey(sd), 7 + purpose),
                        n,
                    )
                )(seeds, n_idx)

            def dstep(carry, _):
                dc, tok, n_idx = carry
                logits, dv = local_draft.apply(
                    {"params": dparams, "cache": dc}, tok[:, None],
                    decode=True, mutable=["cache"],
                )
                last = logits[:, -1].astype(jnp.float32)
                scaled = _filter_rows(last, temps, topks, topps, nucleus)
                # Greedy rows get EXACT one-hots, not softmax(x/1e-6):
                # with near-tied logits the quasi-one-hot could accept
                # a mismatched draft token (or split an exact tie),
                # breaking the bit-identical-to-generate contract.
                onehot = jax.nn.one_hot(
                    jnp.argmax(last, axis=-1), last.shape[-1]
                )
                q = jnp.where(
                    (temps <= 0.0)[:, None],
                    onehot,
                    jax.nn.softmax(scaled, axis=-1),
                )
                drawn = jax.vmap(
                    lambda kk, sc: jax.random.categorical(kk, sc)
                )(keys_for(0, n_idx), scaled).astype(jnp.int32)
                nxt = jnp.where(
                    temps <= 0.0,
                    jnp.argmax(last, axis=-1).astype(jnp.int32),
                    drawn,
                )
                return (dv["cache"], nxt, n_idx + 1), (nxt, q)

            # spec_k steps, spec_k - 1 proposals: the last step's cache
            # write is load-bearing on full acceptance (see spec_step).
            (d_cache, _, _), (drafts_t, q_t) = jax.lax.scan(
                dstep, (d_cache, tokens, ns), None, length=spec_k
            )
            drafts = jnp.moveaxis(drafts_t, 0, 1)[:, : spec_k - 1]
            q_probs = jnp.moveaxis(q_t, 0, 1)[:, : spec_k - 1]
            chunk = jnp.concatenate([tokens[:, None], drafts], axis=1)
            logits, t_vars = local_model.apply(
                {"params": params, "cache": t_cache}, chunk, decode=True,
                mutable=["cache"],
            )
            t_cache = t_vars["cache"]
            v = logits.shape[-1]
            rep = lambda x: jnp.repeat(x, spec_k)
            p_probs = jax.nn.softmax(
                _filter_rows(
                    logits.reshape(slots * spec_k, v), rep(temps),
                    rep(topks), rep(topps), nucleus,
                ).reshape(slots, spec_k, v),
                axis=-1,
            )
            # Greedy rows: exact one-hot targets (see dstep comment) —
            # acceptance degenerates to exact argmax match and the
            # residual to the target argmax, bit-identical to the
            # greedy program.
            p_onehot = jax.nn.one_hot(
                jnp.argmax(logits.astype(jnp.float32), axis=-1), v
            )
            p_probs = jnp.where(
                (temps <= 0.0)[:, None, None], p_onehot, p_probs
            )
            tok_idx = drafts[..., None]
            px = jnp.take_along_axis(p_probs[:, : spec_k - 1], tok_idx, -1)[..., 0]
            qx = jnp.take_along_axis(q_probs, tok_idx, -1)[..., 0]
            us = jnp.stack(
                [
                    jax.vmap(jax.random.uniform)(keys_for(1, ns + i))
                    for i in range(spec_k - 1)
                ],
                axis=1,
            )
            accepts = us * qx < px
            acc_pad = jnp.concatenate(
                [accepts, jnp.zeros((slots, 1), bool)], axis=1
            )
            a_rows = jnp.argmin(acc_pad, axis=1).astype(jnp.int32)
            # Per-row residual at each row's OWN first-rejected slot
            # (acc_pad[r, a_r] is False by construction, so the bonus
            # is always a residual/bonus-slot draw — never a re-emit).
            gather = lambda x: jnp.take_along_axis(
                x, a_rows[:, None, None], axis=1
            )[:, 0]
            p_a = gather(p_probs)
            q_a = gather(
                jnp.concatenate([q_probs, jnp.zeros((slots, 1, v))], axis=1)
            )
            res = jnp.maximum(p_a - q_a, 0.0)
            ssum = jnp.sum(res, axis=-1, keepdims=True)
            res = jnp.where(ssum > 0, res / jnp.where(ssum > 0, ssum, 1.0), p_a)
            drawn_bonus = jax.vmap(
                lambda kk, rr: jax.random.categorical(kk, jnp.log(rr))
            )(keys_for(2, ns + a_rows), res).astype(jnp.int32)
            # Greedy rows' residual is an exact one-hot: take its
            # argmax outright rather than a categorical over log(0)s.
            bonus = jnp.where(
                temps <= 0.0,
                jnp.argmax(res, axis=-1).astype(jnp.int32),
                drawn_bonus,
            )
            new_idx = jnp.where(active, idx0 + 1 + a_rows, 0)
            return (drafts, a_rows, bonus,
                    _rewind_idx(t_cache, new_idx), _rewind_idx(d_cache, new_idx))

        def spec_step(params, dparams, t_cache, d_cache, tokens, active):
            body = sharded(
                _spec_core,
                (param_specs, draft_param_specs, cache_specs,
                 draft_cache_specs, P(), P()),
                (P(), P(), P(), cache_specs, draft_cache_specs),
            )
            return body(params, dparams, t_cache, d_cache, tokens, active)

        def spec_step_sampled(params, dparams, t_cache, d_cache, tokens,
                              active, temps, topks, topps, seeds, ns,
                              *, nucleus):
            body = sharded(
                functools.partial(_spec_core_sampled, nucleus=nucleus),
                (param_specs, draft_param_specs, cache_specs,
                 draft_cache_specs) + (P(),) * 7,
                (P(), P(), P(), cache_specs, draft_cache_specs),
            )
            return body(params, dparams, t_cache, d_cache, tokens, active,
                        temps, topks, topps, seeds, ns)

        # Speculation x horizon: the whole draft/score/accept loop runs
        # ``horizon`` times inside ONE dispatch — the configuration the
        # dispatch-floor analysis asks for on high-RTT hosts (round-4
        # review item #3: one ~84 ms dispatch then buys up to
        # horizon * spec_k tokens). In-graph retirement mirrors
        # account() exactly: a row emits its accepted prefix plus the
        # bonus, truncated by its budget and its first eos, then goes
        # dead (cache index clamps to 0 — the free-slot convention).
        # Returns per-iteration (emitted-token matrix, emit mask,
        # accepted counts, live-going-in) so the host replays the same
        # bookkeeping the single-dispatch path does token by token.
        def spec_horizon(params, dparams, t_cache, d_cache, tokens, live0,
                         rems, eos_ids, temps, topks, topps, seeds, ns,
                         *, horizon, sampled, nucleus=False):
            def run(params, dparams, t_cache, d_cache, tokens, live0, rems,
                    eos_ids, temps, topks, topps, seeds, ns):
                cols = jnp.arange(spec_k)[None, :]

                def body(carry, _):
                    t_c, d_c, tok, live, n, rem = carry
                    if sampled:
                        drafts, a_rows, bonus, t_c, d_c = _spec_core_sampled(
                            params, dparams, t_c, d_c, tok, live,
                            temps, topks, topps, seeds, n, nucleus=nucleus,
                        )
                    else:
                        drafts, a_rows, bonus, t_c, d_c = _spec_core(
                            params, dparams, t_c, d_c, tok, live
                        )
                    # Emitted-token matrix: accepted drafts in columns
                    # 0..a_r-1, the bonus at column a_r.
                    toks_e = jnp.concatenate(
                        [drafts, jnp.zeros((slots, 1), jnp.int32)], axis=1
                    )
                    toks_e = jnp.where(
                        cols == a_rows[:, None], bonus[:, None], toks_e
                    )
                    emit = (
                        (cols <= a_rows[:, None])
                        & (cols < rem[:, None])
                        & live[:, None]
                    )
                    is_eos = (toks_e == eos_ids[:, None]) & emit
                    # The first eos is emitted (account() emits then
                    # finishes); everything after it is not.
                    after = (jnp.cumsum(is_eos, axis=1) - is_eos) > 0
                    emit &= ~after
                    cnt = emit.sum(axis=1).astype(jnp.int32)
                    rem2 = rem - cnt
                    live2 = live & (rem2 > 0) & ~(is_eos & emit).any(axis=1)
                    # Live rows always emit their full chunk, so the
                    # last emitted token — next dispatch's input — is
                    # the bonus; dead rows' carry token is a don't-care.
                    return (t_c, d_c, bonus, live2, n + cnt, rem2), (
                        toks_e, emit, a_rows, live,
                    )

                (t_c, d_c, _, _, _, _), (toks, emits, accs, lives) = jax.lax.scan(
                    body, (t_cache, d_cache, tokens, live0, ns, rems), None,
                    length=horizon,
                )
                return toks, emits, accs, lives, t_c, d_c

            run = sharded(
                run,
                (param_specs, draft_param_specs, cache_specs,
                 draft_cache_specs) + (P(),) * 9,
                (P(), P(), P(), P(), cache_specs, draft_cache_specs),
            )
            return run(params, dparams, t_cache, d_cache, tokens, live0,
                       rems, eos_ids, temps, topks, topps, seeds, ns)

        # --- paged programs -------------------------------------------
        # One fused dispatch serves BOTH roles every iteration: rows
        # mid-prefill write their next prompt chunk, decode rows write
        # their single next token (padded to the chunk width — pad
        # writes land past idx or in the scratch block, unreachable
        # either way), and each row's last-true logit yields its next
        # token. This is chunked prefill: admitting a long prompt costs
        # ceil(L/chunk) of these dispatches WITH decode riding along,
        # instead of one monolithic prefill that freezes tokens-out for
        # every live slot.
        def paged_mixed(params, cache, tokens, base_lens, true_lens, temps,
                        topks, topps, seeds, ns, *, sampled=False,
                        nucleus=False):
            def run(params, cache, tokens, base_lens, true_lens, temps,
                    topks, topps, seeds, ns):
                active = true_lens > 0
                cache2 = _clamp_idx(_rewind_idx(cache, base_lens), active)
                logits, variables = local_model.apply(
                    {"params": params, "cache": cache2}, tokens,
                    decode=True, mutable=["cache"],
                )
                last = jnp.take_along_axis(
                    logits, jnp.maximum(true_lens - 1, 0)[:, None, None],
                    axis=1,
                )[:, 0]
                if sampled:
                    toks = _sample_rows(
                        last, temps, topks, topps, seeds, ns,
                        use_top_p=nucleus,
                    )
                else:
                    toks = jnp.argmax(last, axis=-1).astype(jnp.int32)
                # Rewind every row to ITS true end — pad garbage past it
                # stays masked forever (kernel invariant), exactly the
                # dense batched-admission convention.
                cache3 = _map_cache(
                    variables["cache"], lambda leaf: leaf,
                    lambda idx: jnp.asarray(base_lens + true_lens, idx.dtype),
                )
                return toks, cache3

            run = sharded(
                run, (param_specs, cache_specs) + (P(),) * 8,
                (P(), cache_specs),
            )
            return run(params, cache, tokens, base_lens, true_lens, temps,
                       topks, topps, seeds, ns)

        # Speculative twin: the chunk appends into BOTH pools (the
        # draft's pages ride alongside the target's — one page table,
        # two pools) so target and draft enter the next speculative
        # dispatch at the same position. Decode rows pass through inert
        # (true_len 0: clamped to the scratch block, no emit) — their
        # tokens come from the spec decode dispatch that follows.
        def spec_paged_chunk(params, dparams, t_cache, d_cache, tokens,
                             base_lens, true_lens, temps, topks, topps,
                             seeds, ns, *, sampled=False, nucleus=False):
            def run(params, dparams, t_cache, d_cache, tokens, base_lens,
                    true_lens, temps, topks, topps, seeds, ns):
                active = true_lens > 0
                t2 = _clamp_idx(_rewind_idx(t_cache, base_lens), active)
                d2 = _clamp_idx(_rewind_idx(d_cache, base_lens), active)
                logits, t_vars = local_model.apply(
                    {"params": params, "cache": t2}, tokens, decode=True,
                    mutable=["cache"],
                )
                _, d_vars = local_draft.apply(
                    {"params": dparams, "cache": d2}, tokens, decode=True,
                    mutable=["cache"],
                )
                last = jnp.take_along_axis(
                    logits, jnp.maximum(true_lens - 1, 0)[:, None, None],
                    axis=1,
                )[:, 0]
                if sampled:
                    toks = _sample_rows(
                        last, temps, topks, topps, seeds, ns,
                        use_top_p=nucleus,
                    )
                else:
                    toks = jnp.argmax(last, axis=-1).astype(jnp.int32)
                end = base_lens + true_lens
                t3 = _rewind_idx(t_vars["cache"], end)
                d3 = _rewind_idx(d_vars["cache"], end)
                return toks, t3, d3

            run = sharded(
                run,
                (param_specs, draft_param_specs, cache_specs,
                 draft_cache_specs) + (P(),) * 8,
                (P(), cache_specs, draft_cache_specs),
            )
            return run(params, dparams, t_cache, d_cache, tokens, base_lens,
                       true_lens, temps, topks, topps, seeds, ns)

        self._paged_mixed = (
            jax.jit(
                paged_mixed, donate_argnums=(1,),
                static_argnames=("sampled", "nucleus"),
            )
            if self._paged else None
        )
        self._spec_paged_chunk = (
            jax.jit(
                spec_paged_chunk, donate_argnums=(2, 3),
                static_argnames=("sampled", "nucleus"),
            )
            if self._paged and draft_model is not None else None
        )

        self._prefill = prefill
        self._append = append
        self._prefill_batch = prefill_batch
        self._spec_prefill_batch = (
            spec_prefill_batch if draft_model is not None else None
        )
        self._insert_batch = jax.jit(insert_batch, donate_argnums=(0,))
        self._offline_wave = jax.jit(
            offline_wave, static_argnames=("horizon", "sampled", "nucleus")
        )
        self._spec_prefill = (
            spec_prefill if draft_model is not None else None
        )
        self._spec_append = (
            spec_append if draft_model is not None else None
        )
        self._spec_step = (
            jax.jit(spec_step, donate_argnums=(2, 3))
            if draft_model is not None else None
        )
        self._spec_step_sampled = (
            jax.jit(
                spec_step_sampled, donate_argnums=(2, 3),
                static_argnames=("nucleus",),
            )
            if draft_model is not None else None
        )
        self._spec_horizon = (
            jax.jit(
                spec_horizon, donate_argnums=(2, 3),
                static_argnames=("horizon", "sampled", "nucleus"),
            )
            if draft_model is not None else None
        )
        self._insert = jax.jit(insert, donate_argnums=(0,))
        # Dense: (target cache, draft cache or None, length) per prefix
        # name. Paged: a _PagedPrefix (tokens + shared block ids).
        self._prefixes: dict[str, Any] = {}
        # The effective cache capacity: a speculative engine is bounded
        # by the SMALLER of the two caches — the single definition every
        # capacity check uses.
        self._cap = model.max_decode_len
        if draft_model is not None:
            self._cap = min(self._cap, draft_model.max_decode_len)
        self._step_greedy = jax.jit(step_greedy, donate_argnums=(1,))
        self._step_sampled = jax.jit(
            step_sampled, donate_argnums=(1,), static_argnames=("nucleus",)
        )
        self._step_horizon = jax.jit(
            step_horizon, donate_argnums=(1,),
            static_argnames=("horizon", "sampled", "nucleus"),
        )
        # Telemetry: dispatches vs tokens emitted say how well slots
        # stayed occupied (the continuous-batching win); prefix_hits
        # counts admissions that skipped a shared-prefix recompute.
        self.dispatches = 0
        self.tokens_emitted = 0
        self.prefix_hits = 0
        # Batched-admission telemetry: requests admitted / waves is the
        # dispatch amortization factor (1.0 = no batching benefit).
        self.admission_waves = 0
        # Speculation telemetry: accepted proposals / proposal slots
        # offered is the acceptance rate (how good the draft is).
        self.spec_accepted = 0
        self.spec_offered = 0
        # Registry metrics (hops_tpu.telemetry): process-wide, shared
        # by every engine in the process — scrape-side rate() over the
        # token counter is tokens/sec, occupancy is sampled at dispatch
        # cadence in _mark_dispatch.
        self._m_dispatches = REGISTRY.counter(
            "hops_tpu_lm_dispatches_total", "LM engine device dispatches"
        ).labels()
        self._m_tokens = REGISTRY.counter(
            "hops_tpu_lm_tokens_total", "Tokens emitted by the LM engine"
        ).labels()
        self._m_ttft = REGISTRY.histogram(
            "hops_tpu_lm_ttft_seconds",
            "Time from submit to a request's first emitted token",
        ).labels()
        self._m_occupancy = REGISTRY.gauge(
            "hops_tpu_lm_slot_occupancy",
            "Busy decode slots / total slots, sampled at dispatch time",
        ).labels()
        self._m_prefix_cache = REGISTRY.counter(
            "hops_tpu_lm_prefix_cache_total",
            "Admissions by prefix-cache outcome",
            labels=("result",),
        )
        self._m_prefix_batched = REGISTRY.counter(
            "hops_tpu_lm_prefix_batched_total",
            "Requests admitted in a wave with another request sharing "
            "their prefix (prefix-aware admission ordering)",
        ).labels()
        # Paged-engine telemetry (registered unconditionally so the
        # metric catalog is one list; the dense engine simply never
        # moves them).
        self._m_pool_util = REGISTRY.gauge(
            "hops_tpu_lm_block_pool_utilization",
            "Live KV blocks / allocatable pool blocks, sampled at "
            "dispatch time",
        ).labels()
        self._m_prefill_chunks = REGISTRY.counter(
            "hops_tpu_lm_prefill_chunks_total",
            "Prompt chunks prefilled by the paged engine",
        ).labels()
        self._m_preemptions = REGISTRY.counter(
            "hops_tpu_lm_preemptions_total",
            "Requests preempted (blocks freed, requeued for replay) "
            "because the block pool ran dry",
        ).labels()
        self._m_dispatch_failures = REGISTRY.counter(
            "hops_tpu_lm_dispatch_failures_total",
            "Engine dispatch waves that raised; their in-flight "
            "requests were failed and the scheduler continued",
        ).labels()
        # Host scheduling state shared by both layouts.
        self.preemptions = 0
        self.prefill_chunks = 0
        self._occ_sum = 0.0  # sum of per-dispatch occupancy samples
        self._admit_seq = 0
        self._admitting: list[_Request] = []  # popped, not yet slotted
        # Per-ticket TTFT (seconds) and failure records; both consumed
        # by take_result / take_error so a long-lived server stays flat.
        self.ttft_s: dict[int, float] = {}
        self._errors: dict[int, BaseException] = {}

    # --- public API -----------------------------------------------------

    def register_prefix(self, name: str, tokens: Any) -> str:
        """Prefill a shared prompt prefix ONCE (a system prompt, a
        few-shot header) and cache its KV state; requests that
        ``submit(..., prefix_id=name)`` start from it and only compute
        their own suffix — the standard prefix-caching serving
        optimization. On a speculative engine the DRAFT's prefix cache
        is prefilled and stored alongside the target's (the draft must
        enter every dispatch at the same position). Re-registering a
        name replaces it.

        On the PAGED engine the prefix is not prefilled here at all:
        the first request that names it prefills normally, and the
        physical blocks holding the prefix's complete pages are then
        captured (one registry reference each). Every later admission
        points its page table at those shared blocks and re-computes
        only from the first incomplete block — page-table sharing with
        copy-on-write at the divergence boundary, no stored cache
        copy."""
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        if tokens.size == 0:
            raise ValueError("empty prefix")
        cap = self._cap
        if tokens.size >= cap:
            raise ValueError(
                f"prefix {tokens.size} leaves no room in "
                f"max_decode_len {cap}"
            )
        if self._paged:
            old = self._prefixes.get(name)
            if isinstance(old, _PagedPrefix) and old.blocks:
                # Drop the registry's references; blocks still shared
                # by live requests survive until those finish.
                self._pool.unref_all(old.blocks)
                old.blocks = None
            self._prefixes[name] = _PagedPrefix(name=name, tokens=tokens)
            return name
        L = tokens.size
        bucket = min(self._bucket(L), cap)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :L] = tokens
        zero_knobs = (jnp.float32(0.0), jnp.int32(0), jnp.float32(0.0),
                      jnp.int32(0))
        if self.spec_k:
            _, cache, d_cache = self._spec_prefill(
                self.params, self.draft_params, jnp.asarray(padded),
                jnp.int32(L), *zero_knobs, sampled=False,
            )
        else:
            _, cache = self._prefill(
                self.params, jnp.asarray(padded), jnp.int32(L),
                *zero_knobs, sampled=False,
            )
            d_cache = None
        self._prefixes[name] = (cache, d_cache, L)
        return name

    def submit(
        self,
        prompt: Any,
        max_new_tokens: int = 32,
        eos_id: int | None = None,
        temperature: float = 0.0,
        top_k: int | None = None,
        top_p: float | None = None,
        seed: int = 0,
        prefix_id: str | None = None,
        priority: str = "interactive",
    ) -> int:
        """Enqueue a request. ``temperature=0`` is greedy; otherwise
        tokens draw from the (optionally top-k- and/or top-p-truncated)
        scaled distribution, with a key chain that depends only on ``seed``
        and token index — reproducible regardless of slot placement or
        batch company. With ``prefix_id``, ``prompt`` is the SUFFIX
        after a prefix registered via :meth:`register_prefix`.
        ``priority`` (``interactive`` | ``batch``): admission serves
        interactive first, starvation-guarded (per-ticket token streams
        are placement-independent, so priority reordering never changes
        any request's output)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        prefix = None
        prefix_len = 0
        if prefix_id is not None:
            if prefix_id not in self._prefixes:
                raise ValueError(
                    f"unknown prefix_id {prefix_id!r} — register_prefix first"
                )
            # Snapshot: re-registering the name later must not swap the
            # prefix (or invalidate this validation) for queued work.
            prefix = self._prefixes[prefix_id]
            prefix_len = (
                prefix.tokens.size if self._paged else prefix[2]
            )
        total = prefix_len + prompt.size + max_new_tokens
        if total > self.model.max_decode_len:
            raise ValueError(
                f"prefix {prefix_len} + prompt {prompt.size} + "
                f"{max_new_tokens} new tokens "
                f"exceeds max_decode_len {self.model.max_decode_len}"
            )
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if temperature < 0:
            raise ValueError("temperature must be >= 0")
        if top_p is not None and not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        if self.spec_k:
            cap2 = self._cap
            # Deepest write: the final dispatch enters with at most
            # total - 2 written tokens (one emitted-but-unwritten, one
            # of the budget still to come) and writes spec_k positions.
            if total + self.spec_k - 2 > cap2:
                raise ValueError(
                    f"prefix {prefix_len} + prompt {prompt.size} + "
                    f"{max_new_tokens} new tokens "
                    f"(+{self.spec_k - 2} speculation slack) exceeds "
                    f"max_decode_len {cap2}"
                )
        if self._paged:
            # The deepest position this request can EVER write must fit
            # the pool even when it is the only live request — the
            # preemption policy can evict everyone else, never itself.
            worst = total + (max(0, self.spec_k - 2) if self.spec_k else 0)
            need = -(-worst // self._page_size)
            if need > self._pool.total:
                raise ValueError(
                    f"request needs {need} KV blocks at its deepest "
                    f"write; the pool has {self._pool.total} "
                    f"(kv_pool_blocks={self._pool.num_blocks}, "
                    f"page={self._page_size})"
                )
        # Admission bound LAST: malformed requests above stay 400-shaped
        # (ValueError); only a well-formed request at a full queue is a
        # shed the client should retry.
        if len(self._queue) >= self.max_queue:
            raise qos.QueueFullError(
                f"submit queue full ({len(self._queue)}/{self.max_queue} "
                f"queued); retry later"
            )
        seed = int(seed) & 0x7FFFFFFF  # fold into int32 before it hits jit
        ticket = self._next_ticket
        self._next_ticket += 1
        self._queue.append(
            _Request(
                ticket, prompt, max_new_tokens, eos_id,
                temperature=float(temperature), top_k=int(top_k or 0),
                top_p=float(top_p or 0.0), seed=int(seed), prefix=prefix,
                prefix_key=prefix_id, submitted_at=time.monotonic(),
                priority=priority if priority in qos.PRIORITIES
                else "batch",
            )
        )
        return ticket

    def step(self) -> list[int]:
        """One engine iteration: admit queued requests into free slots,
        then one decode dispatch wave for all slots (``decode_horizon``
        device-side steps — admission happens only at horizon
        boundaries, the standard latency/throughput trade; on the paged
        engine the wave also advances every in-progress chunked
        prefill). Returns tickets that finished this iteration.

        Failure isolation: a dispatch error — injected through the
        ``lm_engine.dispatch`` fault point or a real backend failure —
        fails ONLY the in-flight requests. Their slots (and, paged,
        their blocks) are freed, the error is retrievable per ticket
        via :meth:`take_error` (serving turns it into a 5xx), and the
        scheduler keeps draining the queue on the next iteration.
        """
        try:
            faultinject.fire("lm_engine.dispatch")
            self._order_queue_for_prefix_waves()
            if self._paged:
                out = self._step_paged()
            else:
                out = self._step_dense()
            self._count_prefix_batched()
            return out
        except Exception as e:  # noqa: BLE001 — isolate to in-flight work
            return self._fail_inflight(e)
        finally:
            self._admitting.clear()

    def _promote_next_admission(self) -> None:
        """Move the priority-admission winner to the queue head, so the
        existing head-FIFO admission paths (dense wave build, paged
        pool-pressure gate) stay untouched. FIFO within a class; the
        starvation guard bounds how long batch work can be passed
        over. No-op when one class is queued — bit-identical to plain
        FIFO for single-class workloads.

        Interaction with prefix-wave ordering (which ran just before):
        promotion picks FIFO *within* the chosen class, so a same-class
        prefix group stays adjacent across consecutive promotions and
        still admits as one wave; only a guard-forced cross-class pick
        (at most 1 in `starvation_limit` admissions) can split a wave —
        the bounded price of batch never starving."""
        if len(self._queue) <= 1:
            return
        ranks = [qos.rank(r.priority) for r in self._queue]
        if len(set(ranks)) <= 1:
            return
        want = self._admission_guard.pick_rank(ranks)
        idx = next(i for i, r in enumerate(ranks) if r == want)
        if idx:
            req = self._queue[idx]
            del self._queue[idx]
            self._queue.appendleft(req)

    def _order_queue_for_prefix_waves(self) -> None:
        """Prefix-aware admission ordering: stable-group the queue so
        requests submitted under the same ``prefix_id`` sit adjacent
        and land in the same admission wave — the wave that can share
        the cached prefix (paged: page-table refs on the published
        blocks; dense: copies of one stored cache) instead of straddling
        waves and re-admitting cold. Groups anchor at their oldest
        still-queued member and pull forward at most ``slots`` members
        (one admission wave's worth); later same-prefix arrivals anchor
        a NEW wave at their own position, so a hot prefix under
        sustained load can overtake an older request by at most one
        wave — never starve it. The sort is stable, so relative order
        inside a wave — and for prefix-less requests — never changes;
        per-ticket token streams are placement- and company-independent
        ((seed, n)-keyed sampling), so outputs stay bit-identical to
        FIFO admission."""
        if len(self._queue) < 2 or not any(
            r.prefix_key is not None for r in self._queue
        ):
            return
        q = list(self._queue)  # deque random access is O(n) per element
        wave_rank: dict[str, int] = {}
        wave_fill: dict[str, int] = {}
        ranks = []
        for pos, req in enumerate(q):
            key = req.prefix_key
            if key is None:
                ranks.append(pos)  # singleton group at its own position
                continue
            if wave_fill.get(key, self.slots) >= self.slots:
                wave_rank[key] = pos  # start a new wave here
                wave_fill[key] = 0
            ranks.append(wave_rank[key])
            wave_fill[key] += 1
        if all(a <= b for a, b in zip(ranks, ranks[1:])):
            return  # already wave-grouped — skip the rebuild
        order = sorted(range(len(ranks)), key=ranks.__getitem__)
        self._queue = collections.deque(q[i] for i in order)

    def _count_prefix_batched(self) -> None:
        """Tally requests whose admission wave contained another request
        sharing their prefix — the prefix-aware ordering's win."""
        keys: dict[str, int] = {}
        for req in self._admitting:
            if req.prefix_key is not None:
                keys[req.prefix_key] = keys.get(req.prefix_key, 0) + 1
        batched = sum(c for c in keys.values() if c >= 2)
        if batched:
            self._m_prefix_batched.inc(batched)

    def _step_dense(self) -> list[int]:
        """One iteration of the dense-cache engine (the seed layout:
        per-slot max-length cache reservations, monolithic bucketed
        prefill at admission)."""
        finished = []
        wave: list[tuple[int, _Request]] = []
        for row in range(self.slots):
            if self._slot_state[row] is None and self._queue:
                self._promote_next_admission()
                req = self._queue.popleft()
                self._admitting.append(req)
                if req.prefix is not None:
                    # Prefix-append admissions keep the per-request
                    # path: each starts from a different stored cache.
                    done = self._admit(req, row)
                    if done is not None:
                        finished.append(done)
                else:
                    wave.append((row, req))
        if wave:
            finished.extend(self._admit_wave(wave))
        if not any(st is not None for st in self._slot_state):
            return finished

        tokens = jnp.asarray(
            [st.emitted[-1] if st else 0 for st in self._slot_state], jnp.int32
        )
        active = jnp.asarray(
            [st is not None for st in self._slot_state], jnp.bool_
        )
        sampled = any(
            st is not None and st.temperature > 0 for st in self._slot_state
        )
        # A greedy request's top_p is inert (argmax path): gating the
        # static flag on temperature too avoids compiling a second,
        # graph-identical program variant for it.
        nucleus = any(
            st is not None and st.temperature > 0 and 0.0 < st.top_p < 1.0
            for st in self._slot_state
        )
        # _admit finishes exhausted/eos'd requests on the spot, so
        # every slot that reaches a dispatch has work left.
        assert all(
            st is None or st.remaining >= 1 for st in self._slot_state
        )

        def sampling_vectors():
            return (
                jnp.asarray(
                    [st.temperature if st else 0.0 for st in self._slot_state],
                    jnp.float32,
                ),
                jnp.asarray(
                    [st.top_k if st else 0 for st in self._slot_state], jnp.int32
                ),
                jnp.asarray(
                    [st.top_p if st else 0.0 for st in self._slot_state],
                    jnp.float32,
                ),
                jnp.asarray(
                    [st.seed if st else 0 for st in self._slot_state], jnp.int32
                ),
                jnp.asarray(
                    [st.n_sampled if st else 0 for st in self._slot_state],
                    jnp.int32,
                ),
            )

        def account(row: int, tok: int) -> None:
            self._account(row, tok, finished)

        if self.spec_k and self.decode_horizon > 1:
            rems = jnp.asarray(
                [st.remaining if st else 0 for st in self._slot_state],
                jnp.int32,
            )
            eos_ids = jnp.asarray(
                [st.eos_id if st and st.eos_id is not None else -1
                 for st in self._slot_state],
                jnp.int32,
            )
            toks, emits, accs, lives, self._cache, self._draft_cache = (
                self._spec_horizon(
                    self.params, self.draft_params, self._cache,
                    self._draft_cache, tokens, active, rems, eos_ids,
                    *sampling_vectors(),
                    horizon=self.decode_horizon, sampled=sampled,
                    nucleus=nucleus,
                )
            )
            self._mark_dispatch()
            toks, emits = np.asarray(toks), np.asarray(emits)
            accs, lives = np.asarray(accs), np.asarray(lives)
            for i in range(self.decode_horizon):
                for row in range(self.slots):
                    if self._slot_state[row] is None or not lives[i, row]:
                        continue
                    self.spec_offered += self.spec_k - 1
                    self.spec_accepted += int(accs[i, row])
                    for j in range(self.spec_k):
                        if emits[i, row, j] and self._slot_state[row] is not None:
                            account(row, int(toks[i, row, j]))
            return finished

        if self.spec_k:
            if sampled:
                drafts, a_rows, bonus, self._cache, self._draft_cache = (
                    self._spec_step_sampled(
                        self.params, self.draft_params, self._cache,
                        self._draft_cache, tokens, active,
                        *sampling_vectors(), nucleus=nucleus,
                    )
                )
            else:
                drafts, a_rows, bonus, self._cache, self._draft_cache = (
                    self._spec_step(
                        self.params, self.draft_params, self._cache,
                        self._draft_cache, tokens, active,
                    )
                )
            self._mark_dispatch()
            drafts = np.asarray(drafts)
            a_rows, bonus = np.asarray(a_rows), np.asarray(bonus)
            for row in range(self.slots):
                if self._slot_state[row] is None:
                    continue
                self.spec_offered += self.spec_k - 1
                self.spec_accepted += int(a_rows[row])
                # Emit the accepted proposals then the bonus; account()
                # may finish the slot mid-stream (budget or eos), after
                # which the rest of this row's tokens are discarded —
                # the over-advanced cache rows are garbage a future
                # insert overwrites.
                for tok in [int(t) for t in drafts[row, : a_rows[row]]] + [
                    int(bonus[row])
                ]:
                    if self._slot_state[row] is None:
                        break
                    account(row, tok)
            return finished

        if self.decode_horizon > 1:
            rems = jnp.asarray(
                [st.remaining if st else 0 for st in self._slot_state],
                jnp.int32,
            )
            eos_ids = jnp.asarray(
                [st.eos_id if st and st.eos_id is not None else -1
                 for st in self._slot_state],
                jnp.int32,
            )
            toks, lives, self._cache = self._step_horizon(
                self.params, self._cache, tokens, active, rems, eos_ids,
                *sampling_vectors(),
                horizon=self.decode_horizon, sampled=sampled,
                nucleus=nucleus,
            )
            self._mark_dispatch()
            toks, lives = np.asarray(toks), np.asarray(lives)
            for i in range(self.decode_horizon):
                for row in range(self.slots):
                    if self._slot_state[row] is not None and lives[i, row]:
                        account(row, int(toks[i, row]))
            return finished

        if sampled:
            nxt, self._cache = self._step_sampled(
                self.params, self._cache, tokens, active,
                *sampling_vectors(), nucleus=nucleus,
            )
        else:
            nxt, self._cache = self._step_greedy(
                self.params, self._cache, tokens, active
            )
        self._mark_dispatch()
        nxt = np.asarray(nxt)
        for row in range(self.slots):
            if self._slot_state[row] is not None:
                account(row, int(nxt[row]))
        return finished

    def run(self) -> dict[int, list[int]]:
        """Drain the queue and all live slots; returns every result
        collected so far (including earlier iterations')."""
        while self._queue or any(st is not None for st in self._slot_state):
            self.step()
        return dict(self._results)

    def run_offline(self) -> dict[int, list[int]]:
        """Drain every queued request in budget-sorted slot-waves, ONE
        fused prefill+decode dispatch per wave.

        The batch-inference shape (all requests known upfront — the
        reference's batch-inference role, SURVEY §2.5) doesn't need the
        online scheduler's admit/decode cadence: each wave's whole
        lifetime runs device-side, so a W-wave workload costs W
        dispatches total — on a dispatch-latency-bound link this is the
        difference between losing and winning against monolithic static
        batching, while still doing strictly less padded compute
        (budget-sorted waves pad to the WAVE's max budget, not the
        global max; finished rows idle only to their wave's end).
        Output is identical to :meth:`run` / per-request ``generate``
        (sampled rows are placement-independent, so re-grouping by
        budget changes nothing). Transient memory: one fresh full-slot
        cache per wave (the persistent cache is untouched), same ~2×
        peak as a multi-request admission wave.

        Speculative engines, queued prefix requests, and drains started
        mid-decode fall back to :meth:`run` (the online scheduler).
        """
        if (
            self.spec_k
            or self._paged
            or any(r.prefix is not None for r in self._queue)
            or any(st is not None for st in self._slot_state)
        ):
            # (Paged engines use the online scheduler: the fused wave
            # program assumes the dense transient-cache layout.)
            return self.run()
        # Budget-major sort: uniform budgets per wave minimize the scan
        # steps finished rows idle through; bucket-minor keeps prompt
        # padding tight. The sorted requests go BACK into the queue and
        # pop per wave, so an exception mid-drain (OOM on a new shape,
        # interrupt on a slow link) leaves every unprocessed request
        # queued and retryable — same contract as run().
        self._queue = collections.deque(sorted(
            self._queue,
            key=lambda r: (r.max_new_tokens, self._bucket(r.prompt.size)),
            reverse=True,
        ))
        while self._queue:
            wave = [
                self._queue.popleft()
                for _ in range(min(self.slots, len(self._queue)))
            ]
            try:
                self._run_offline_wave(wave)
            except BaseException:
                self._queue.extendleft(reversed(wave))
                raise
        return dict(self._results)

    def _run_offline_wave(self, wave: list["_Request"]) -> None:
        """One fused offline dispatch for ``wave`` + host bookkeeping."""
        bucket = max(
            min(self._bucket(r.prompt.size), self.model.max_decode_len)
            for r in wave
        )
        padded = np.zeros((self.slots, bucket), np.int32)
        true_lens = np.zeros((self.slots,), np.int32)
        rems = np.zeros((self.slots,), np.int32)
        eos_ids = np.full((self.slots,), -1, np.int32)
        temps = np.zeros((self.slots,), np.float32)
        topks = np.zeros((self.slots,), np.int32)
        topps = np.zeros((self.slots,), np.float32)
        seeds = np.zeros((self.slots,), np.int32)
        for row, r in enumerate(wave):
            L = r.prompt.size
            padded[row, :L] = r.prompt
            true_lens[row] = L
            rems[row] = r.max_new_tokens
            if r.eos_id is not None:
                eos_ids[row] = r.eos_id
            temps[row] = r.temperature
            topks[row] = r.top_k
            topps[row] = r.top_p
            seeds[row] = r.seed
        maxrem = max(r.max_new_tokens for r in wave) - 1
        # Power-of-two horizons bound the compile count; extra scan
        # steps past the wave's last live row are all-dead idles.
        horizon = 1 << (maxrem - 1).bit_length() if maxrem > 0 else 0
        sampled = any(r.temperature > 0 for r in wave)
        nucleus = any(
            r.temperature > 0 and 0.0 < r.top_p < 1.0 for r in wave
        )
        tok0, toks, lives = self._offline_wave(
            self.params, jnp.asarray(padded), jnp.asarray(true_lens),
            jnp.asarray(rems), jnp.asarray(eos_ids), jnp.asarray(temps),
            jnp.asarray(topks), jnp.asarray(topps), jnp.asarray(seeds),
            horizon=horizon, sampled=sampled, nucleus=nucleus,
        )
        self._mark_dispatch()
        self.admission_waves += 1
        tok0 = np.asarray(tok0)
        toks, lives = np.asarray(toks), np.asarray(lives)
        for row, r in enumerate(wave):
            # live-going-in is a monotone true->false prefix per row, so
            # the real tokens are exactly the first sum(lives) scan
            # outputs — no per-token host loop.
            cnt = int(lives[:, row].sum()) if horizon else 0
            out = [int(tok0[row])] + toks[:cnt, row].astype(int).tolist()
            self.tokens_emitted += len(out)
            self._m_tokens.inc(len(out))
            # Offline waves never carry prefixes (run_offline falls
            # back to run() for those) — every admission is a miss.
            self._m_prefix_cache.inc(result="miss")
            self._observe_ttft(r)
            self._results[r.ticket] = out

    def result(self, ticket: int) -> list[int] | None:
        """Generated tokens (prompt excluded) or None if not finished."""
        return self._results.get(ticket)

    def take_result(self, ticket: int) -> list[int] | None:
        """Like :meth:`result` but consuming — long-lived servers must
        use this or ``_results`` grows without bound. Also drops the
        ticket's TTFT record."""
        self.ttft_s.pop(ticket, None)
        return self._results.pop(ticket, None)

    def error(self, ticket: int) -> BaseException | None:
        """The dispatch failure that killed this ticket, if any (set
        when a decode wave raised while the request was in flight)."""
        return self._errors.get(ticket)

    def take_error(self, ticket: int) -> BaseException | None:
        """Consuming :meth:`error` — serving surfaces call this to turn
        the failure into a 5xx without leaking the record."""
        return self._errors.pop(ticket, None)

    def cancel(self, ticket: int) -> bool:
        """Remove a still-QUEUED request (admitted requests run to
        completion). Returns whether anything was removed. Callers that
        share the engine across threads hold their lock around
        submit/cancel, which makes cancel-on-partial-failure exact:
        nothing can have been admitted in between."""
        for req in self._queue:
            if req.ticket == ticket:
                self._queue.remove(req)
                return True
        return False

    def stats(self) -> dict[str, Any]:
        """Serving-telemetry snapshot: dispatch counts, occupancy,
        prefix-cache hits, and speculation acceptance — surfaced over
        HTTP by ``GET /v1/models/<name>`` (serving.py)."""
        out = {
            "dispatches": self.dispatches,
            "tokens_emitted": self.tokens_emitted,
            "tokens_per_dispatch": round(
                self.tokens_emitted / max(self.dispatches, 1), 3
            ),
            "prefix_hits": self.prefix_hits,
            "admission_waves": self.admission_waves,
            "queued": len(self._queue),
            "slots_busy": sum(st is not None for st in self._slot_state),
            "slots": self.slots,
            "decode_horizon": self.decode_horizon,
            "mean_occupancy": round(
                self._occ_sum / max(self.dispatches, 1), 4
            ),
            "cache_layout": "paged" if self._paged else "dense",
        }
        if self._paged:
            out.update(self._pool.stats())
            out.update(
                page_size=self._page_size,
                prefill_chunk=self.prefill_chunk,
                prefill_chunks=self.prefill_chunks,
                preemptions=self.preemptions,
            )
        if self.spec_k:
            out["spec_k"] = self.spec_k
            out["spec_acceptance"] = round(
                self.spec_accepted / max(self.spec_offered, 1), 3
            )
        return out

    @property
    def has_failures(self) -> bool:
        """Unconsumed per-ticket dispatch failures exist (the serving
        driver uses this to wake waiters whose tickets just failed)."""
        return bool(self._errors)

    @property
    def has_work(self) -> bool:
        """Anything queued or decoding? (The serving driver thread
        sleeps on this.) The engine itself is NOT thread-safe — callers
        that share it across threads serialize on their own lock
        (serving.LMEnginePredictor)."""
        return bool(self._queue) or any(
            st is not None for st in self._slot_state
        )

    # --- internals ------------------------------------------------------

    def _mark_dispatch(self) -> None:
        """The one dispatch-accounting path: the legacy ``dispatches``
        counter plus the registry metrics; batch-slot occupancy (and,
        paged, block-pool utilization) is sampled here because dispatch
        cadence IS the engine's clock."""
        self.dispatches += 1
        self._m_dispatches.inc()
        occ = sum(st is not None for st in self._slot_state) / self.slots
        self._occ_sum += occ
        self._m_occupancy.set(occ)
        if self._paged:
            self._m_pool_util.set(self._pool.stats()["utilization"])

    def _observe_ttft(self, req: "_Request") -> None:
        """First-token latency, once per request — a preempted request
        replays its stream but keeps its original TTFT."""
        if req.submitted_at and not req.ttft_observed:
            dt = time.monotonic() - req.submitted_at
            self._m_ttft.observe(dt)
            self.ttft_s[req.ticket] = dt
            req.ttft_observed = True

    def _account(self, row: int, tok: int, finished: list[int]) -> None:
        """The one emit-and-finish bookkeeping path, shared by the
        single-step and horizon loops of BOTH cache layouts (must
        mirror the in-graph live-mask retirement exactly)."""
        st = self._slot_state[row]
        st.emitted.append(tok)
        st.remaining -= 1
        st.n_sampled += 1
        self.tokens_emitted += 1
        self._m_tokens.inc()
        if st.remaining == 0 or (st.eos_id is not None and tok == st.eos_id):
            finished.append(self._finish(row))

    def _fail_inflight(self, exc: BaseException) -> list[int]:
        """Dispatch-failure isolation: every in-flight request fails
        with ``exc`` (ticket -> :meth:`take_error`), slots and blocks
        free, and the scheduler stays serviceable for the queue."""
        self._m_dispatch_failures.inc()
        failed: list[int] = []
        for row in range(self.slots):
            st = self._slot_state[row]
            if st is None:
                continue
            self._errors[st.ticket] = exc
            failed.append(st.ticket)
            self._slot_state[row] = None
            if self._paged and st.blocks is not None:
                self._release_blocks(row, st.blocks)
        for req in self._admitting:
            # Popped from the queue but not yet slotted when the wave
            # died (dense batched admission): fail those too rather
            # than lose them silently. A paged admission that was
            # PREEMPTED back to the queue within this same dispatch is
            # still live — it replays next iteration, so failing it
            # here would hand the client an error AND a later result.
            if any(r is req for r in self._queue):  # identity: _Request
                continue  # holds ndarrays, == would be ambiguous
            if req.ticket not in self._errors and req.ticket not in self._results:
                self._errors[req.ticket] = exc
                failed.append(req.ticket)
        self._admitting.clear()
        # Re-materialize fresh all-free caches: a program that raised
        # AFTER buffer donation consumed the old ones, and every slot's
        # state was just discarded anyway — without this, the next
        # dispatch would trip over deleted buffers and wedge the
        # engine for good.
        def fresh(tmpl):
            return jax.tree.map(
                lambda s: jax.device_put(
                    jnp.zeros(s.shape, s.dtype), s.sharding
                ),
                tmpl,
            )

        self._cache = fresh(self._cache_tmpl)
        if self._draft_cache_tmpl is not None:
            self._draft_cache = fresh(self._draft_cache_tmpl)
        if self._paged:
            self._pages_dirty = True
        log.warning(
            "lm_engine dispatch failed; %d in-flight request(s) failed "
            "(%s: %s)", len(failed), type(exc).__name__, exc,
        )
        flight.record("dispatch_failure", failed=len(failed),
                      error=f"{type(exc).__name__}: {exc}")
        return []

    def _bucket(self, n: int) -> int:
        for b in self.prefill_buckets:
            if n <= b:
                return b
        return self.model.max_decode_len

    # --- paged scheduler ------------------------------------------------
    # Host bookkeeping for the paged layout: which physical blocks each
    # slot owns (BlockPool refcounts), how much of each prompt is still
    # un-prefilled, and when to preempt. Admission costs NO dispatch —
    # the prompt enters the cache through prefill_chunk-token chunks
    # fused into the regular decode waves.

    def _graft_cache_leaf(self, leaf_name: str, host_value: np.ndarray) -> None:
        """Overwrite every layer's ``leaf_name`` cache leaf (in both
        caches) with ``host_value`` — the single host->device graft
        walker. Each leaf gets a FRESH buffer: the programs donate the
        cache pytree, and donation rejects one buffer aliased across
        leaves (f(donate(a), donate(a)))."""
        import jax.tree_util as jtu

        def set_leaf(path, leaf):
            name = str(path[-1].key) if hasattr(path[-1], "key") else ""
            return jnp.array(host_value) if name == leaf_name else leaf

        self._cache = jtu.tree_map_with_path(set_leaf, self._cache)
        if self._draft_cache is not None:
            self._draft_cache = jtu.tree_map_with_path(
                set_leaf, self._draft_cache
            )

    def _sync_pages(self) -> None:
        """Push the host page table into every layer's 'pages' cache
        leaf if it changed since the last dispatch. Must run before ANY
        dispatch that follows an admission, free, preemption, or
        in-graph scratch-clamp."""
        if not self._pages_dirty:
            return
        self._graft_cache_leaf("pages", self._pages_np)
        self._pages_dirty = False

    def _graft_idx(self, idx_np: np.ndarray) -> None:
        """Overwrite every layer's cache-index leaf with the host's
        authoritative per-row lengths. The decode programs that do not
        take an explicit base (spec_step / spec_horizon / step_horizon)
        trust the device idx — but a live row that rode a previous
        dispatch INERT (mid-prefill during a spec decode wave) had its
        idx scratch-clamped to 0 in-graph. The host mirror is exact at
        every iteration boundary, so re-grafting it is always sound;
        callers gate on ``_idx_stale`` to keep it off the steady-state
        hot path."""
        self._graft_cache_leaf("idx", idx_np)

    def _release_blocks(self, row: int, blocks: list[int]) -> None:
        self._pool.unref_all(blocks)
        self._pages_np[row, :] = 0
        self._pages_dirty = True

    def _admit_paged(self, row: int) -> bool:
        """Try to admit the queue head into free slot ``row``:
        bookkeeping only (page-table row + block refs + slot state).
        False = the pool can't cover the prompt right now — the request
        QUEUES (admission control) rather than OOMing or corrupting
        live slots."""
        req = self._queue[0]
        entry = req.prefix
        if entry is not None:
            full = np.concatenate([entry.tokens, req.prompt])
        else:
            full = req.prompt
        ps = self._page_size
        shared: list[int] = list(entry.blocks) if (
            entry is not None and entry.blocks
        ) else []
        shared_len = len(shared) * ps
        n_new = -(-full.size // ps) - len(shared)
        while n_new > self._pool.available:
            # Idle prefix registrations must not starve admissions
            # forever: with no live slot to ever free blocks, the
            # registry's references would deadlock a queued request
            # that submit-time validation promised fits. Evict those
            # (cheap — re-computed on the next prefix hit; this
            # request's own snapshot is kept, its shared list is
            # already built on it); never preempt live work to admit.
            if not self._evict_idle_prefix(keep=entry):
                return False
        new_blocks = self._pool.alloc(n_new)
        for blk in shared:
            self._pool.ref(blk)
        blocks = shared + new_blocks
        self._queue.popleft()
        # Wave membership for the prefix-batching tally (slot failures
        # surface through _slot_state, so _fail_inflight skips these).
        self._admitting.append(req)
        self._pages_np[row, :] = 0
        self._pages_np[row, : len(blocks)] = blocks
        self._pages_dirty = True
        worst = full.size + req.max_new_tokens + (
            max(0, self.spec_k - 2) if self.spec_k else 0
        )
        self._slot_state[row] = _SlotState(
            ticket=req.ticket, emitted=[], remaining=req.max_new_tokens,
            eos_id=req.eos_id, temperature=req.temperature,
            top_k=req.top_k, top_p=req.top_p, seed=req.seed, n_sampled=0,
            req=req, pending=full[shared_len:], base_len=shared_len,
            prompt_total=int(full.size), worst_len=worst, blocks=blocks,
            shared_hit=bool(shared), seq=self._admit_seq,
        )
        self._admit_seq += 1
        return True

    def _capture_prefix_blocks(self, st: "_SlotState") -> None:
        """Prefill just crossed the prefix boundary: publish the
        prefix's COMPLETE pages for sharing (one registry reference
        each). Only the first finisher publishes, and only while its
        snapshot is still the registered entry."""
        entry = st.req.prefix
        if not isinstance(entry, _PagedPrefix) or entry.blocks is not None:
            return
        if self._prefixes.get(entry.name) is not entry:
            return  # re-registered since this request was submitted
        nfull = entry.tokens.size // self._page_size
        if nfull == 0:
            return
        entry.blocks = list(st.blocks[:nfull])
        for blk in entry.blocks:
            self._pool.ref(blk)

    def _ensure_blocks(self, row: int, st: "_SlotState", cover_len: int) -> None:
        """Grow ``row``'s page table to cover positions < cover_len —
        the on-demand allocation as decode advances. A dry pool first
        evicts idle prefix registrations, then preempts the
        newest-admitted OTHER slot (its blocks free, its request
        replays from the queue front — deterministic sampling makes the
        replayed stream identical)."""
        ps = self._page_size
        need = -(-cover_len // ps)
        while need > len(st.blocks):
            want = need - len(st.blocks)
            if self._pool.available >= want:
                newb = self._pool.alloc(want)
                self._pages_np[
                    row, len(st.blocks): len(st.blocks) + want
                ] = newb
                st.blocks.extend(newb)
                self._pages_dirty = True
                return
            if not self._reclaim(row):
                raise RuntimeError(
                    "block pool wedged: no free blocks, no evictable "
                    "prefix, no preemptible slot — submit-time "
                    "validation should have made this impossible"
                )

    def _evict_idle_prefix(self, keep: Any = None) -> bool:
        """Drop ONE prefix registration's block references (no lost
        work — the next hit re-computes them). ``keep`` protects a
        specific entry (the admission in progress already points at
        its blocks). False = nothing evictable."""
        for entry in self._prefixes.values():
            if entry is keep:
                continue
            if isinstance(entry, _PagedPrefix) and entry.blocks:
                self._pool.unref_all(entry.blocks)
                entry.blocks = None
                return True
        return False

    def _reclaim(self, needy_row: int) -> bool:
        """Free capacity for ``needy_row``: drop an idle prefix
        registration's references first (no lost work), else preempt
        the newest-admitted other slot. False = nothing left to take."""
        if self._evict_idle_prefix():
            return True
        victims = [
            (st.seq, r)
            for r, st in enumerate(self._slot_state)
            if st is not None and r != needy_row
        ]
        if not victims:
            return False
        self._preempt(max(victims)[1])
        return True

    def _preempt(self, row: int) -> None:
        st = self._slot_state[row]
        self._slot_state[row] = None
        self._release_blocks(row, st.blocks)
        # Queue FRONT: the victim re-admits as soon as space frees, and
        # replays to an identical token stream (greedy is
        # deterministic; sampled keys fold (seed, token index) only).
        self._queue.appendleft(st.req)
        self.preemptions += 1
        self._m_preemptions.inc()

    def _first_token(self, row: int, st: "_SlotState", tok: int) -> int | None:
        """Prefill completed this chunk: the row's first emitted token.
        The paged twin of :meth:`_register`'s bookkeeping tail."""
        self.tokens_emitted += 1
        self._m_tokens.inc()
        self._m_prefix_cache.inc(result="hit" if st.shared_hit else "miss")
        self._observe_ttft(st.req)
        st.emitted = [tok]
        st.remaining = st.req.max_new_tokens - 1
        st.n_sampled = 1
        if st.remaining == 0 or (st.eos_id is not None and tok == st.eos_id):
            return self._finish(row)
        return None

    def _step_paged(self) -> list[int]:
        """One iteration of the paged engine: admit (bookkeeping only),
        grow decode rows' page tables on demand (preempting if dry),
        then ONE fused chunk+decode dispatch — or, on speculative
        engines, a chunk dispatch followed by the spec decode dispatch.
        Decode-only iterations use the horizon/speculative programs
        unchanged (they operate on the cache pytree, whatever its
        layout)."""
        finished: list[int] = []
        for row in range(self.slots):
            if self._queue and self._slot_state[row] is None:
                self._promote_next_admission()
                if not self._admit_paged(row):
                    break  # FIFO: pool pressure queues, never reorders
        live = [
            (r, st) for r, st in enumerate(self._slot_state) if st is not None
        ]
        if not live:
            return finished
        prefilling = [(r, st) for r, st in live if st.pending is not None]
        # Worst-case decode advance of this wave, for block coverage.
        horizon = 1 if prefilling else self.decode_horizon
        adv = (self.spec_k or 1) * horizon
        for r, st in live:
            if self._slot_state[r] is not st or st.pending is not None:
                continue  # preempted meanwhile, or still prefilling
            mirror = st.prompt_total + len(st.emitted) - 1
            self._ensure_blocks(r, st, min(mirror + adv, st.worst_len))
        # _ensure_blocks may have preempted: rebuild the worklists.
        live = [
            (r, st) for r, st in enumerate(self._slot_state) if st is not None
        ]
        if not live:
            return finished
        prefilling = [(r, st) for r, st in live if st.pending is not None]
        decoding = [(r, st) for r, st in live if st.pending is None]
        sampled = any(st.temperature > 0 for _, st in live)
        nucleus = any(
            st.temperature > 0 and 0.0 < st.top_p < 1.0 for _, st in live
        )
        temps = jnp.asarray(
            [st.temperature if st else 0.0 for st in self._slot_state],
            jnp.float32,
        )
        topks = jnp.asarray(
            [st.top_k if st else 0 for st in self._slot_state], jnp.int32
        )
        topps = jnp.asarray(
            [st.top_p if st else 0.0 for st in self._slot_state], jnp.float32
        )
        seeds = jnp.asarray(
            [st.seed if st else 0 for st in self._slot_state], jnp.int32
        )

        if prefilling:
            W = self.prefill_chunk
            tokens = np.zeros((self.slots, W), np.int32)
            base = np.zeros((self.slots,), np.int32)
            tl = np.zeros((self.slots,), np.int32)
            ns = np.zeros((self.slots,), np.int32)
            for r, st in prefilling:
                n = min(W, int(st.pending.size))
                tokens[r, :n] = st.pending[:n]
                base[r] = st.base_len
                tl[r] = n
            fused_decode = not self.spec_k
            for r, st in decoding:
                base[r] = st.prompt_total + len(st.emitted) - 1
                if fused_decode:
                    tokens[r, 0] = st.emitted[-1]
                    tl[r] = 1
                    ns[r] = st.n_sampled
            self._sync_pages()
            if self.spec_k:
                toks, self._cache, self._draft_cache = self._spec_paged_chunk(
                    self.params, self.draft_params, self._cache,
                    self._draft_cache, jnp.asarray(tokens),
                    jnp.asarray(base), jnp.asarray(tl), temps, topks,
                    topps, seeds, jnp.asarray(ns),
                    sampled=sampled, nucleus=nucleus,
                )
                # Inert decode rows were scratch-clamped in-graph; the
                # next _sync_pages restores their real pages.
                self._pages_dirty = True
            else:
                toks, self._cache = self._paged_mixed(
                    self.params, self._cache, jnp.asarray(tokens),
                    jnp.asarray(base), jnp.asarray(tl), temps, topks,
                    topps, seeds, jnp.asarray(ns),
                    sampled=sampled, nucleus=nucleus,
                )
            self._mark_dispatch()
            toks = np.asarray(toks)
            for r, st in prefilling:
                n = int(tl[r])
                self.prefill_chunks += 1
                self._m_prefill_chunks.inc()
                st.base_len += n
                st.pending = st.pending[n:]
                if st.pending.size == 0:
                    st.pending = None
                    self._capture_prefix_blocks(st)
                    done = self._first_token(r, st, int(toks[r]))
                    if done is not None:
                        finished.append(done)
            if fused_decode:
                for r, st in decoding:
                    if self._slot_state[r] is st:
                        self._account(r, int(toks[r]), finished)
                return finished
            if not decoding:
                return finished

        # --- decode dispatch --------------------------------------------
        # Decode set = the rows captured BEFORE the chunk dispatch. A
        # row that completed its prefill THIS iteration (first token
        # just emitted) must sit this dispatch out — letting it decode
        # here would advance its cache with tokens the host never
        # accounted.
        self._sync_pages()
        dec_rows = {r for r, _ in decoding}
        is_decode = [r in dec_rows for r in range(self.slots)]
        if self._idx_stale:
            # Host-authoritative cache index: some live row rode an
            # earlier dispatch inert and had its device idx
            # scratch-clamped. Steady-state decode (no inert
            # passengers since the last graft) skips the transfer.
            self._graft_idx(np.asarray(
                [
                    (st.prompt_total + len(st.emitted) - 1)
                    if is_decode[r]
                    else (st.base_len if st is not None else 0)
                    for r, st in enumerate(self._slot_state)
                ],
                np.int32,
            ))
            self._idx_stale = False
        tokens = jnp.asarray(
            [st.emitted[-1] if dec else 0
             for st, dec in zip(self._slot_state, is_decode)],
            jnp.int32,
        )
        active = jnp.asarray(is_decode, jnp.bool_)
        ns = jnp.asarray(
            [st.n_sampled if dec else 0
             for st, dec in zip(self._slot_state, is_decode)],
            jnp.int32,
        )
        base = jnp.asarray(
            [st.prompt_total + len(st.emitted) - 1 if dec else 0
             for st, dec in zip(self._slot_state, is_decode)],
            jnp.int32,
        )
        if self.spec_k:
            rems = jnp.asarray(
                [st.remaining if dec else 0
                 for st, dec in zip(self._slot_state, is_decode)],
                jnp.int32,
            )
            eos_ids = jnp.asarray(
                [st.eos_id if dec and st.eos_id is not None else -1
                 for st, dec in zip(self._slot_state, is_decode)],
                jnp.int32,
            )
            if horizon > 1:
                toks, emits, accs, lives, self._cache, self._draft_cache = (
                    self._spec_horizon(
                        self.params, self.draft_params, self._cache,
                        self._draft_cache, tokens, active, rems, eos_ids,
                        temps, topks, topps, seeds, ns,
                        horizon=horizon, sampled=sampled, nucleus=nucleus,
                    )
                )
                self._mark_dispatch()
                toks, emits = np.asarray(toks), np.asarray(emits)
                accs, lives = np.asarray(accs), np.asarray(lives)
                for i in range(horizon):
                    for r in range(self.slots):
                        st = self._slot_state[r]
                        if st is None or st.pending is not None or not lives[i, r]:
                            continue
                        self.spec_offered += self.spec_k - 1
                        self.spec_accepted += int(accs[i, r])
                        for j in range(self.spec_k):
                            if emits[i, r, j] and self._slot_state[r] is st:
                                self._account(r, int(toks[i, r, j]), finished)
                return finished
            if sampled:
                drafts, a_rows, bonus, self._cache, self._draft_cache = (
                    self._spec_step_sampled(
                        self.params, self.draft_params, self._cache,
                        self._draft_cache, tokens, active, temps, topks,
                        topps, seeds, ns, nucleus=nucleus,
                    )
                )
            else:
                drafts, a_rows, bonus, self._cache, self._draft_cache = (
                    self._spec_step(
                        self.params, self.draft_params, self._cache,
                        self._draft_cache, tokens, active,
                    )
                )
            self._mark_dispatch()
            if prefilling:
                # Still-prefilling rows rode this dispatch inactive:
                # the in-graph scratch-clamp zeroed their device pages
                # AND idx, so later dispatches must restore both from
                # the host.
                self._pages_dirty = True
                self._idx_stale = True
            drafts = np.asarray(drafts)
            a_rows, bonus = np.asarray(a_rows), np.asarray(bonus)
            for r, st in decoding:
                if self._slot_state[r] is not st:
                    continue
                self.spec_offered += self.spec_k - 1
                self.spec_accepted += int(a_rows[r])
                for tok in [int(t) for t in drafts[r, : a_rows[r]]] + [
                    int(bonus[r])
                ]:
                    if self._slot_state[r] is not st:
                        break
                    self._account(r, tok, finished)
            return finished
        if horizon > 1:
            rems = jnp.asarray(
                [st.remaining if dec else 0
                 for st, dec in zip(self._slot_state, is_decode)],
                jnp.int32,
            )
            eos_ids = jnp.asarray(
                [st.eos_id if dec and st.eos_id is not None else -1
                 for st, dec in zip(self._slot_state, is_decode)],
                jnp.int32,
            )
            toks, lives, self._cache = self._step_horizon(
                self.params, self._cache, tokens, active, rems, eos_ids,
                temps, topks, topps, seeds, ns,
                horizon=horizon, sampled=sampled, nucleus=nucleus,
            )
            self._mark_dispatch()
            toks, lives = np.asarray(toks), np.asarray(lives)
            for i in range(horizon):
                for r in range(self.slots):
                    st = self._slot_state[r]
                    if st is not None and st.pending is None and lives[i, r]:
                        self._account(r, int(toks[i, r]), finished)
            return finished
        # Single-step decode: the mixed program at chunk width 1.
        toks, self._cache = self._paged_mixed(
            self.params, self._cache, tokens[:, None], base,
            active.astype(jnp.int32), temps, topks, topps, seeds, ns,
            sampled=sampled, nucleus=nucleus,
        )
        self._mark_dispatch()
        toks = np.asarray(toks)
        for r, st in decoding:
            if self._slot_state[r] is st:
                self._account(r, int(toks[r]), finished)
        return finished

    def _admit(self, req: _Request, row: int) -> int | None:
        """Prefix-append admission: prefill ``req``'s suffix onto its
        stored prefix cache(s) and splice into slot ``row`` (both
        caches on a speculative engine). Returns the ticket if the
        request finished at admission (budget of 1). Non-prefix
        requests go through :meth:`_admit_wave` (batched)."""
        L = req.prompt.size
        base_cache, base_draft, base_len = req.prefix
        bucket = min(self._bucket(L), self._cap - base_len)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :L] = req.prompt
        knobs = (jnp.float32(req.temperature), jnp.int32(req.top_k),
                 jnp.float32(req.top_p), jnp.int32(req.seed))
        kwargs = dict(
            sampled=req.temperature > 0,
            nucleus=req.temperature > 0 and 0.0 < req.top_p < 1.0,
        )
        if self.spec_k:
            first_tok, one_cache, one_draft = self._spec_append(
                self.params, self.draft_params, base_cache, base_draft,
                jnp.asarray(padded), jnp.int32(base_len), jnp.int32(L),
                *knobs, **kwargs,
            )
            self._draft_cache = self._insert(
                self._draft_cache, one_draft, jnp.int32(row),
                jnp.int32(base_len + L),
            )
        else:
            first_tok, one_cache = self._append(
                self.params, base_cache, jnp.asarray(padded),
                jnp.int32(base_len), jnp.int32(L), *knobs, **kwargs,
            )
        self.prefix_hits += 1
        self._cache = self._insert(
            self._cache, one_cache, jnp.int32(row), jnp.int32(base_len + L)
        )
        return self._register(row, req, int(first_tok))

    def _admit_wave(self, wave: list[tuple[int, "_Request"]]) -> list[int]:
        """Batched admission: ONE prefill dispatch + ONE cache merge for
        every request entering a free slot this iteration (two more for
        the draft on a speculative engine) — instead of two dispatches
        per request. Output is identical to per-request admission: rows
        are independent under causal attention, first tokens draw from
        the same per-row (seed, n=0) keys, and un-admitted rows rewind
        to index 0 (the free-slot convention).

        The trade: the batched program materializes a transient
        full-slot fresh cache, so peak HBM during a multi-request wave
        is ~2× the persistent cache (target and, on speculative
        engines, draft). Single-request waves — the trickle workload,
        where batching buys nothing — take the b=1 per-request path
        instead, which also keeps its memory profile."""
        if len(wave) == 1:
            row, req = wave[0]
            done = self._admit_single(row, req)
            return [done] if done is not None else []
        # The padded chunk must fit the SMALLER cache on speculative
        # engines (self._cap): the draft prefills the same bucket.
        bucket = max(
            min(self._bucket(req.prompt.size), self._cap) for _, req in wave
        )
        padded = np.zeros((self.slots, bucket), np.int32)
        true_lens = np.zeros((self.slots,), np.int32)
        admit = np.zeros((self.slots,), bool)
        temps = np.zeros((self.slots,), np.float32)
        topks = np.zeros((self.slots,), np.int32)
        topps = np.zeros((self.slots,), np.float32)
        seeds = np.zeros((self.slots,), np.int32)
        for row, req in wave:
            L = req.prompt.size
            padded[row, :L] = req.prompt
            true_lens[row] = L
            admit[row] = True
            temps[row] = req.temperature
            topks[row] = req.top_k
            topps[row] = req.top_p
            seeds[row] = req.seed
        sampled = any(req.temperature > 0 for _, req in wave)
        nucleus = any(
            req.temperature > 0 and 0.0 < req.top_p < 1.0 for _, req in wave
        )
        args = (jnp.asarray(padded), jnp.asarray(true_lens),
                jnp.asarray(temps), jnp.asarray(topks), jnp.asarray(topps),
                jnp.asarray(seeds))
        admit_v, lens_v = jnp.asarray(admit), jnp.asarray(true_lens)
        if self.spec_k:
            toks, t_rows, d_rows = self._spec_prefill_batch(
                self.params, self.draft_params, *args,
                sampled=sampled, nucleus=nucleus,
            )
            self._draft_cache = self._insert_batch(
                self._draft_cache, d_rows, admit_v, lens_v
            )
        else:
            toks, t_rows = self._prefill_batch(
                self.params, *args, sampled=sampled, nucleus=nucleus,
            )
        self._cache = self._insert_batch(self._cache, t_rows, admit_v, lens_v)
        self.admission_waves += 1
        toks = np.asarray(toks)
        finished = []
        for row, req in wave:
            done = self._register(row, req, int(toks[row]))
            if done is not None:
                finished.append(done)
        return finished

    def _admit_single(self, row: int, req: "_Request") -> int | None:
        """b=1 admission for a one-request wave: two small dispatches,
        no transient full-slot cache (see :meth:`_admit_wave`)."""
        L = req.prompt.size
        kwargs = dict(
            sampled=req.temperature > 0,
            nucleus=req.temperature > 0 and 0.0 < req.top_p < 1.0,
        )
        knobs = (jnp.float32(req.temperature), jnp.int32(req.top_k),
                 jnp.float32(req.top_p), jnp.int32(req.seed))
        if self.spec_k:
            # The padded chunk must fit the SMALLER cache: the draft
            # prefills the same bucket.
            bucket = min(self._bucket(L), self._cap)
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :L] = req.prompt
            first_tok, one_cache, one_draft = self._spec_prefill(
                self.params, self.draft_params, jnp.asarray(padded),
                jnp.int32(L), *knobs, **kwargs,
            )
            self._draft_cache = self._insert(
                self._draft_cache, one_draft, jnp.int32(row), jnp.int32(L)
            )
        else:
            bucket = min(self._bucket(L), self.model.max_decode_len)
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :L] = req.prompt
            first_tok, one_cache = self._prefill(
                self.params, jnp.asarray(padded), jnp.int32(L), *knobs,
                **kwargs,
            )
        self._cache = self._insert(
            self._cache, one_cache, jnp.int32(row), jnp.int32(L)
        )
        return self._register(row, req, int(first_tok))

    def _register(self, row: int, req: "_Request", tok: int) -> int | None:
        """Shared admission bookkeeping: record the first emitted token
        and occupy (or immediately finish) the slot."""
        self.tokens_emitted += 1
        self._m_tokens.inc()
        self._m_prefix_cache.inc(
            result="hit" if req.prefix is not None else "miss"
        )
        self._observe_ttft(req)
        st = _SlotState(
            ticket=req.ticket,
            emitted=[tok],
            remaining=req.max_new_tokens - 1,
            eos_id=req.eos_id,
            temperature=req.temperature,
            top_k=req.top_k,
            top_p=req.top_p,
            seed=req.seed,
        )
        self._slot_state[row] = st
        if st.remaining == 0 or (req.eos_id is not None and tok == req.eos_id):
            return self._finish(row)
        return None

    def _finish(self, row: int) -> int:
        st = self._slot_state[row]
        self._results[st.ticket] = st.emitted
        self._slot_state[row] = None
        if self._paged and st.blocks is not None:
            # Blocks free the moment the last reader is gone; shared
            # prefix pages survive on the registry's reference.
            self._release_blocks(row, st.blocks)
        # Dense: the slot's cache rows stay as-is; the next insert
        # overwrites idx (and the ragged kernel never reads past idx).
        return st.ticket
