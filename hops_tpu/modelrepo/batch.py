"""Batch inference over sharded data.

Reference (SURVEY.md §2.5, Batch_Inference_Imagenet_Spark.ipynb:283-325):
Spark ``mapPartitions`` over an image DataFrame with the model broadcast
per partition and ``repartition(num_executors*3)``. TPU-native: one
jitted forward, inputs sharded over the mesh's data axis, host loop over
chunks sized ``chips * per_chip_batch``; the ragged tail is padded to
keep shapes static (no recompiles).
"""

from __future__ import annotations

from typing import Any, Callable, Iterator

import jax
import numpy as np

from hops_tpu.parallel.strategy import Strategy


def batch_predict(
    apply_fn: Callable[[Any], Any],
    inputs: np.ndarray,
    per_chip_batch: int = 32,
    strategy: Strategy | None = None,
) -> np.ndarray:
    """Run ``apply_fn`` over ``inputs`` data-parallel across the slice.

    ``apply_fn`` maps a batch array to predictions (already closed over
    params). Returns stacked predictions aligned with ``inputs``.
    """
    strategy = strategy or Strategy()
    chunk = per_chip_batch * strategy.num_replicas_in_sync
    jitted = jax.jit(apply_fn)

    outs: list[np.ndarray] = []
    n = len(inputs)
    for start in range(0, n, chunk):
        block = inputs[start : start + chunk]
        valid = len(block)
        if valid < chunk:  # pad tail to the static shape
            pad = np.repeat(block[-1:], chunk - valid, axis=0)
            block = np.concatenate([block, pad], axis=0)
        placed = strategy.distribute_batch(block)
        preds = np.asarray(jitted(placed))
        outs.append(preds[:valid])
    if outs:
        return np.concatenate(outs, axis=0)
    # Empty input: derive the output shape without running the model.
    import jax.numpy as jnp

    probe = jax.eval_shape(apply_fn, jnp.zeros((1,) + inputs.shape[1:], inputs.dtype))
    return np.empty((0,) + probe.shape[1:], probe.dtype)


def batch_predict_stream(
    apply_fn: Callable[[Any], Any],
    batches: Iterator[np.ndarray],
    strategy: Strategy | None = None,
) -> Iterator[np.ndarray]:
    """Streaming variant: caller controls batching; each yielded batch
    must share one shape (pad upstream)."""
    strategy = strategy or Strategy()
    jitted = jax.jit(apply_fn)
    for block in batches:
        yield np.asarray(jitted(strategy.distribute_batch(block)))


def predict_with_model(
    name: str,
    inputs: np.ndarray,
    version: int | None = None,
    per_chip_batch: int = 32,
) -> np.ndarray:
    """Batch inference straight from the model registry (the reference's
    broadcast-model-per-partition pattern, minus Spark)."""
    from hops_tpu.modelrepo import registry

    bundle = registry.load_flax(name, version)
    module = bundle["module"]
    variables = {"params": bundle["params"], **bundle["extra_variables"]}
    return batch_predict(
        lambda x: module.apply(variables, x, train=False), inputs, per_chip_batch
    )
