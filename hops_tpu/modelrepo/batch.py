"""Batch inference over sharded data.

Reference (SURVEY.md §2.5, Batch_Inference_Imagenet_Spark.ipynb:283-325):
Spark ``mapPartitions`` over an image DataFrame with the model broadcast
per partition and ``repartition(num_executors*3)``. TPU-native: one
jitted forward, inputs sharded over the mesh's data axis, host loop over
chunks sized ``chips * per_chip_batch``; the ragged tail is padded to
keep shapes static (no recompiles).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Iterator

import jax
import numpy as np

from hops_tpu.parallel.strategy import Strategy
from hops_tpu.telemetry.metrics import RATIO_BUCKETS, REGISTRY


class AssemblyPool:
    """Reusable host assembly buffers keyed by ``(shape, dtype)`` —
    PR 3's ``loader._BufferPool`` discipline on the serving side.

    The dynamic batcher and the batch-inference chunk loop assemble a
    fresh padded host array per wave; at steady state every wave has
    the same bucketed shape, so the allocation (and the page faults of
    first touch) is pure churn. ``take`` hands back a previously
    released buffer when one of the right spec is free (a *hit* on the
    reuse counter) or allocates (a *miss* — the first wave of each
    shape, or concurrent waves deeper than the pool has seen). Callers
    must ``give`` the buffer back only once nothing reads it — the
    dispatch path copies host→device before returning, so returning it
    after the predict call resolves is safe.

    Per-spec free lists are capped at ``depth`` buffers so a burst of
    concurrent waves can't grow the pool beyond bounded steady-state
    memory.
    """

    def __init__(self, depth: int = 4):
        self.depth = depth
        self._lock = threading.Lock()
        # (shape, dtype-str) -> free buffers. # guarded by: self._lock
        self._free: dict[tuple, list[np.ndarray]] = {}
        # Per-instance tallies behind hit_rate(): the registry counter
        # below is get-or-create and therefore shared by EVERY pool in
        # the process — fine for dashboards, wrong for one pool's rate.
        self._hits = 0  # guarded by: self._lock
        self._misses = 0  # guarded by: self._lock
        self._m_reuse = REGISTRY.counter(
            "hops_tpu_batch_assembly_reuse_total",
            "Batch-assembly buffer checkouts, hit = reused allocation",
            labels=("site", "result"),
        )

    def take(self, shape: tuple[int, ...], dtype: Any,
             site: str = "serving") -> np.ndarray:
        key = (tuple(shape), np.dtype(dtype).str)
        with self._lock:
            stack = self._free.get(key)
            if stack:
                self._hits += 1
                self._m_reuse.inc(site=site, result="hit")
                return stack.pop()
            self._misses += 1
        self._m_reuse.inc(site=site, result="miss")
        return np.empty(shape, dtype)

    def give(self, buf: np.ndarray) -> None:
        key = (buf.shape, buf.dtype.str)
        with self._lock:
            stack = self._free.setdefault(key, [])
            if len(stack) < self.depth:
                stack.append(buf)

    def hit_rate(self) -> float:
        """THIS pool's lifetime hit fraction (bench surface)."""
        with self._lock:
            total = self._hits + self._misses
            return self._hits / total if total else 0.0


#: Process-global pool: serving predictors and batch_predict share it,
#: so a replica's steady state allocates zero assembly buffers per wave.
ASSEMBLY_POOL = AssemblyPool()


def batch_predict(
    apply_fn: Callable[[Any], Any],
    inputs: np.ndarray,
    per_chip_batch: int = 32,
    strategy: Strategy | None = None,
) -> np.ndarray:
    """Run ``apply_fn`` over ``inputs`` data-parallel across the slice.

    ``apply_fn`` maps a batch array to predictions (already closed over
    params). Returns stacked predictions aligned with ``inputs``.
    """
    strategy = strategy or Strategy()
    chunk = per_chip_batch * strategy.num_replicas_in_sync
    jitted = jax.jit(apply_fn)
    # Fill ratio says how much of each dispatch was pad waste (only the
    # ragged tail dips below 1.0); rows_total's scrape-side rate() is
    # batch-inference throughput.
    m_fill = REGISTRY.histogram(
        "hops_tpu_batch_fill_ratio",
        "Valid rows per batch-inference chunk over the chunk size",
        buckets=RATIO_BUCKETS,
    ).labels()
    m_rows = REGISTRY.counter(
        "hops_tpu_batch_rows_total", "Batch-inference rows predicted"
    ).labels()

    outs: list[np.ndarray] = []
    n = len(inputs)
    pad_buf = None
    for start in range(0, n, chunk):
        block = inputs[start : start + chunk]
        valid = len(block)
        if valid < chunk:  # pad tail to the static shape (pooled buffer)
            pad_buf = ASSEMBLY_POOL.take(
                (chunk,) + inputs.shape[1:], inputs.dtype, site="batch")
            pad_buf[:valid] = block
            pad_buf[valid:] = block[-1:]
            block = pad_buf
        placed = strategy.distribute_batch(block)
        preds = np.asarray(jitted(placed))
        if pad_buf is not None:
            # distribute_batch/jit copied host→device; safe to recycle.
            ASSEMBLY_POOL.give(pad_buf)
            pad_buf = None
        m_fill.observe(valid / chunk)
        m_rows.inc(valid)
        outs.append(preds[:valid])
    if outs:
        return np.concatenate(outs, axis=0)
    # Empty input: derive the output shape without running the model.
    import jax.numpy as jnp

    probe = jax.eval_shape(apply_fn, jnp.zeros((1,) + inputs.shape[1:], inputs.dtype))
    return np.empty((0,) + probe.shape[1:], probe.dtype)


def batch_predict_stream(
    apply_fn: Callable[[Any], Any],
    batches: Iterator[np.ndarray],
    strategy: Strategy | None = None,
) -> Iterator[np.ndarray]:
    """Streaming variant: caller controls batching; each yielded batch
    must share one shape (pad upstream)."""
    strategy = strategy or Strategy()
    jitted = jax.jit(apply_fn)
    for block in batches:
        yield np.asarray(jitted(strategy.distribute_batch(block)))


def predict_with_model(
    name: str,
    inputs: np.ndarray,
    version: int | None = None,
    per_chip_batch: int = 32,
) -> np.ndarray:
    """Batch inference straight from the model registry (the reference's
    broadcast-model-per-partition pattern, minus Spark)."""
    from hops_tpu.modelrepo import registry

    bundle = registry.load_flax(name, version)
    module = bundle["module"]
    variables = {"params": bundle["params"], **bundle["extra_variables"]}
    return batch_predict(
        lambda x: module.apply(variables, x, train=False), inputs, per_chip_batch
    )


def lm_generate_with_model(
    name: str,
    prompts: list,
    max_new_tokens: int | list[int] = 32,
    version: int | None = None,
    slots: int = 8,
    eos_id: int | None = None,
    **sampling: Any,
) -> list[list[int]]:
    """LM batch inference from the registry: generate for every prompt
    via :meth:`LMEngine.run_offline` — budget-sorted slot-waves, ONE
    fused prefill+decode dispatch per wave (the §2.5 batch-inference
    role for language models; classifiers use
    :func:`predict_with_model`). ``max_new_tokens`` may be per-prompt.
    ``sampling`` forwards per-request knobs (temperature, top_k, top_p,
    seed). Returns generated token lists aligned with ``prompts``."""
    from hops_tpu.modelrepo import registry
    from hops_tpu.modelrepo.lm_engine import LMEngine

    # Validate budgets BEFORE the checkpoint load / engine cache build:
    # bad input should fail in microseconds, not after a multi-GB
    # unpickle. np.ndim handles list/tuple/ndarray/scalar uniformly.
    if np.ndim(max_new_tokens) == 0:
        budgets = [int(max_new_tokens)] * len(prompts)
    else:
        budgets = [int(b) for b in np.asarray(max_new_tokens).reshape(-1)]
    if len(budgets) != len(prompts):
        raise ValueError(
            f"{len(budgets)} budgets for {len(prompts)} prompts"
        )
    bundle = registry.load_flax(name, version)
    module = bundle["module"].clone(ragged_decode=True)
    engine = LMEngine(module, bundle["params"], slots=slots)
    tickets = [
        engine.submit(p, max_new_tokens=b, eos_id=eos_id, **sampling)
        for p, b in zip(prompts, budgets)
    ]
    results = engine.run_offline()
    return [results[t] for t in tickets]
