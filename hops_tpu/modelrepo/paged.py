"""Block-pool accounting for the paged KV cache (lm_engine paged mode).

The device side of paging lives in ``models/transformer.py``
(``paged_decode``: pool-shaped cache variables addressed through
per-row page tables) and ``ops/attention.py``
(``paged_decode_attention``: logical->physical translation in the
kernel's index maps). THIS module is the host side: which physical
blocks are free, which are live, and how many requests reference each
— the bookkeeping the engine consults before every dispatch.

Reference counting is what makes prefix caching a page-table trick
instead of a cache copy: a registered prefix's full blocks are held by
the registry (one ref) and by every live request that shares them (one
ref each); a request's private blocks simply have refcount 1. Freeing
is uniform — drop one ref, release the block when it hits zero — so
the engine never needs to remember which of a slot's blocks were
shared. Copy-on-write happens at the first block the prefix does NOT
fill completely: sharers re-compute that boundary block's tokens into
a private block (writing into the shared one would corrupt every other
reader), which for <= one page of tokens is cheaper than a device copy
and keeps the dispatch programs uniform.

Block 0 is reserved as the SCRATCH block: free rows (all-zero page
table) and pad garbage land there, and the attention mask makes it
unreachable — the paged twin of the dense engine's "free rows clamp
idx to 0" convention.
"""

from __future__ import annotations

import collections
import threading


class BlockPoolExhausted(RuntimeError):
    """No free block: callers queue the admission or preempt a slot."""


class BlockPool:
    """Refcounted free-list over ``num_blocks`` physical cache blocks.

    Thread-safe: the engine itself is single-threaded, but serving
    surfaces (stats endpoints, the telemetry scraper) read utilization
    concurrently with the driver thread's alloc/free traffic.
    """

    def __init__(self, num_blocks: int, reserved: int = 1):
        if num_blocks <= reserved:
            raise ValueError(
                f"pool needs > {reserved} blocks (block 0..{reserved - 1} "
                f"reserved), got {num_blocks}"
            )
        self.num_blocks = num_blocks
        self.reserved = reserved
        self._lock = threading.Lock()
        # Free physical block ids, FIFO so freshly freed blocks rest
        # before reuse (easier to spot use-after-free in tests).
        self._free: collections.deque[int] = collections.deque(
            range(reserved, num_blocks)
        )  # guarded by: self._lock
        # Live refcounts per physical block. # guarded by: self._lock
        self._refs: dict[int, int] = {}
        self._peak_used = 0  # guarded by: self._lock

    # -- queries ---------------------------------------------------------

    @property
    def total(self) -> int:
        """Allocatable blocks (the reserved scratch blocks excluded)."""
        return self.num_blocks - self.reserved

    @property
    def available(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def used(self) -> int:
        with self._lock:
            return len(self._refs)

    @property
    def peak_used(self) -> int:
        with self._lock:
            return self._peak_used

    def refcount(self, block: int) -> int:
        with self._lock:
            return self._refs.get(block, 0)

    def stats(self) -> dict[str, float | int]:
        with self._lock:
            used = len(self._refs)
            total = self.num_blocks - self.reserved
            return {
                "blocks_total": total,
                "blocks_used": used,
                "blocks_peak_used": self._peak_used,
                "utilization": used / total if total else 0.0,
            }

    # -- mutation --------------------------------------------------------

    def alloc(self, n: int) -> list[int]:
        """``n`` fresh blocks at refcount 1, or :class:`BlockPoolExhausted`
        with nothing allocated (all-or-nothing, so a failed admission
        never leaks a partial allocation)."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        with self._lock:
            if n > len(self._free):
                raise BlockPoolExhausted(
                    f"need {n} blocks, {len(self._free)} free of "
                    f"{self.num_blocks - self.reserved}"
                )
            out = [self._free.popleft() for _ in range(n)]
            for b in out:
                self._refs[b] = 1
            self._peak_used = max(self._peak_used, len(self._refs))
            return out

    def ref(self, block: int) -> None:
        """One more reader of a live block (page-table sharing)."""
        with self._lock:
            if block not in self._refs:
                raise ValueError(f"ref of unallocated block {block}")
            self._refs[block] += 1

    def unref(self, block: int) -> bool:
        """Drop one reference; release the block to the free list when
        the last reader is gone. Returns whether it was released."""
        with self._lock:
            rc = self._refs.get(block)
            if rc is None:
                raise ValueError(f"unref of unallocated block {block}")
            if rc > 1:
                self._refs[block] = rc - 1
                return False
            del self._refs[block]
            self._free.append(block)
            return True

    def unref_all(self, blocks: list[int]) -> int:
        """Drop one ref from each of ``blocks`` (a finished or preempted
        slot's page list, shared prefix blocks included); returns how
        many were actually released."""
        return sum(self.unref(b) for b in blocks)
