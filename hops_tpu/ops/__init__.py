"""Pallas TPU kernels for the hot ops.

The reference has no custom kernels (its compute path is TF's, SURVEY.md
§2) — but the TPU build's perf ceiling is set by how well the hot loop
maps onto the MXU/VMEM, so the ops that XLA cannot fuse optimally are
hand-written here with Pallas:

- ``attention`` — blocked flash attention (fwd + bwd) with online
  softmax: O(seq) memory, never materializes the (seq, seq) score
  matrix in HBM; sliding-window variants skip out-of-window tiles.
- ``decode_attention`` — one near-bandwidth HBM pass over a
  fixed-capacity KV cache for autoregressive decoding, with optional
  int8 dequantization in VMEM (``quantize_kv``) and native GQA
  query-head grouping.
- ``chunked_softmax_xent`` — LM-head loss computed per sequence chunk
  under ``jax.checkpoint``: the (batch, seq, vocab) fp32 logits are
  never materialized (peak chunk x vocab instead).

Every kernel ships with a pure-XLA reference twin used for (a) numeric
tests, (b) non-TPU backends, (c) shapes the kernel doesn't support.
"""

from hops_tpu.ops.attention import (  # noqa: F401
    attention_reference,
    decode_attention,
    decode_attention_q8,
    decode_attention_reference,
    dequantize_kv,
    flash_attention,
    paged_decode_attention,
    paged_decode_attention_reference,
    paged_gather_kv,
    quantize_kv,
    repeat_kv,
)
from hops_tpu.ops.xent import chunked_softmax_xent  # noqa: F401
