"""Memory-efficient LM-head loss: chunked-vocab softmax cross-entropy.

The straightforward LM loss materializes full fp32 logits —
``(batch, seq, vocab)`` — twice (forward value + backward cotangent).
At the benchmark config (batch 8, seq 2048, vocab 32k) that is ~2.1 GB
per materialization, several times the model's own 90 MB of weights,
and it bounds the trainable batch x seq product long before the
transformer stack does.

:func:`chunked_softmax_xent` computes the identical loss directly from
the final hidden states and the unembed matrix, one sequence chunk at a
time under ``jax.checkpoint``: the forward keeps only the per-chunk
scalar losses, and the backward recomputes each chunk's logits on the
fly — peak logits memory drops from ``seq x vocab`` to
``chunk x vocab`` (64x at the default chunk). The matmuls stay
MXU-shaped (chunk x d @ d x vocab, bf16 inputs, fp32 accumulation), so
this trades a second pass of LM-head FLOPs for O(seq/chunk) less HBM —
the right trade on a bandwidth-bound chip.

Exactness: same log-sum-exp formulation as
``optax.softmax_cross_entropy_with_integer_labels`` in fp32 —
tests/test_ops.py verifies value and gradient parity.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def chunked_softmax_xent(
    hidden: jax.Array,
    unembed: jax.Array,
    targets: jax.Array,
    *,
    chunk: int = 128,
) -> jax.Array:
    """Mean next-token cross-entropy from hidden states.

    ``hidden``: (batch, seq, d) — the final-norm output;
    ``unembed``: (d, vocab) kernel; ``targets``: (batch, seq) int ids.
    Returns the scalar mean loss, identical (fp32 inputs) to computing
    full logits and feeding optax. ``chunk`` is a TOKEN count — the
    flattened ``batch*seq`` tokens are processed ``chunk`` at a time
    (padded up to a multiple); each step's logits block, and therefore
    peak LM-head memory, is ``chunk x vocab`` fp32 — the full vocab
    axis is present per chunk, never sliced.
    """
    b, s, d = hidden.shape
    n = b * s
    h = hidden.reshape(n, d)
    t = targets.reshape(n)
    pad = (-n) % chunk
    if pad:
        h = jnp.concatenate([h, jnp.zeros((pad, d), h.dtype)])
        t = jnp.concatenate([t, jnp.zeros((pad,), t.dtype)])
    valid = (jnp.arange(n + pad) < n).reshape(-1, chunk)
    h = h.reshape(-1, chunk, d)
    t = t.reshape(-1, chunk)

    @jax.checkpoint
    def chunk_loss(hc, tc, vc):
        # (chunk, vocab) exists only inside this (rematerialized) body.
        # bf16 inputs on the MXU, fp32 accumulation — the logits are
        # BORN fp32 here (the full-logits path rounds them through the
        # model dtype first, so bf16 models get slightly better loss
        # numerics on this path, exactness for fp32 models).
        logits = jax.lax.dot_general(
            hc, unembed.astype(hc.dtype), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        lse = jax.nn.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(logits, tc[:, None], axis=-1)[:, 0]
        return jnp.sum((lse - tgt) * vc)

    def body(acc, args):
        hc, tc, vc = args
        return acc + chunk_loss(hc, tc, vc), None

    total, _ = jax.lax.scan(body, jnp.float32(0), (h, t, valid.astype(jnp.float32)))
    return total / n
