"""Flash attention — blocked online-softmax attention as Pallas TPU kernels.

The reference never shards or fuses attention (it has no transformer at
all, SURVEY.md §5 "Long-context … Absent"), but long-context support is
first-class in this framework, and the memory wall for attention is the
(seq, seq) score matrix. This kernel keeps scores in VMEM one
(block_q, block_k) tile at a time, carrying the online-softmax
statistics (running max ``m``, running sum ``l``) in fp32, so HBM
traffic is O(seq·d) instead of O(seq²).

Layout: ``(batch, heads, seq, head_dim)``. Grid is
``(batch·heads, seq/block)``; K/V for one (batch, head) live whole in
VMEM (seq·d·2B — ~2 MB at seq=8192, d=128, bf16) and the kernel walks
them in ``block_k`` tiles with ``pl.ds``. Causal runs prune the K loop
to the lower triangle. The backward pass is two more kernels (dq and
dk/dv) using the saved logsumexp, the standard flash-attention-2 split.

For cross-device sequence parallelism see
``hops_tpu.parallel.ringattention`` which rotates K/V chunks over the
ICI ring and feeds each local chunk through this kernel's math.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = float("-inf")

# The (batch*heads) grid dim is embarrassingly parallel; the block dim
# revisits shared lse/output rows and must stay "arbitrary". Telling
# Mosaic so lets it overlap grid steps (measured: seq=8192 fwd 19.2ms ->
# 9.0ms together with the 256/512 default blocks; v5e, bf16, d=128).
_COMPILER_PARAMS = pltpu.CompilerParams(dimension_semantics=("parallel", "arbitrary"))


def attention_reference(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    sm_scale: float | None = None,
) -> jax.Array:
    """Pure-XLA attention: numeric ground truth + fallback path."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32)
    s = s * sm_scale
    if causal:
        q_pos = jnp.arange(q.shape[2])[:, None]
        k_pos = jnp.arange(k.shape[2])[None, :]
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


# ---------------------------------------------------------------------------
# Forward kernel
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, sm_scale, causal, block_k):
    block_q, head_dim = q_ref.shape[1], q_ref.shape[2]
    seq_k = k_ref.shape[1]
    num_k = seq_k // block_k
    qi = pl.program_id(1)

    q = q_ref[0].astype(jnp.float32) * sm_scale

    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, head_dim), jnp.float32)

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(
            q.astype(k.dtype),
            k,
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        # Fully-masked rows keep m == -inf; subtracting would give nan.
        m_safe = jnp.where(m_new == NEG_INF, 0.0, m_new)
        p = jnp.exp(s - m_safe[:, None])
        alpha = jnp.exp(jnp.where(m == NEG_INF, NEG_INF, m - m_safe))
        l = l * alpha + jnp.sum(p, axis=-1)
        vblk = v_ref[0, pl.ds(j * block_k, block_k), :]
        pv = jax.lax.dot_general(
            p.astype(vblk.dtype),
            vblk,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc = acc * alpha[:, None] + pv
        return m_new, l, acc

    if causal:
        # Only K blocks intersecting the lower triangle of this Q block.
        bound = jnp.minimum(num_k, pl.cdiv((qi + 1) * block_q, block_k))
    else:
        bound = num_k
    m, l, acc = jax.lax.fori_loop(0, bound, body, (m0, l0, acc0))

    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    # lse rides as a full (1, 1, seq_q) row per (batch·head) — TPU block
    # shapes must tile (8, 128) or span their dims, so each q-block
    # program dynamic-stores its slice of the shared row.
    lse_ref[0, 0, pl.ds(qi * block_q, block_q)] = jnp.where(
        m == NEG_INF, NEG_INF, m + jnp.log(l_safe)
    )


# ---------------------------------------------------------------------------
# Backward kernels (flash-attention-2 split: dq, then dk/dv)
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *, sm_scale, causal, block_k
):
    block_q = q_ref.shape[1]
    seq_k = k_ref.shape[1]
    num_k = seq_k // block_k
    qi = pl.program_id(1)

    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0, 0, pl.ds(qi * block_q, block_q)]
    delta = delta_ref[0, 0, pl.ds(qi * block_q, block_q)]

    def body(j, dq):
        k = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        s = s * sm_scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_pos = j * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        lse_safe = jnp.where(lse == NEG_INF, 0.0, lse)
        p = jnp.where(lse[:, None] == NEG_INF, 0.0, jnp.exp(s - lse_safe[:, None]))
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta[:, None]) * sm_scale
        return dq + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    bound = jnp.minimum(num_k, pl.cdiv((qi + 1) * block_q, block_k)) if causal else num_k
    dq = jax.lax.fori_loop(
        0, bound, body, jnp.zeros((block_q, q_ref.shape[2]), jnp.float32)
    )
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    *, sm_scale, causal, block_q,
):
    block_k, head_dim = k_ref.shape[1], k_ref.shape[2]
    seq_q = q_ref.shape[1]
    num_q = seq_q // block_q
    kj = pl.program_id(1)

    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, 0, pl.ds(i * block_q, block_q)]
        delta = delta_ref[0, 0, pl.ds(i * block_q, block_q)]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        s = s * sm_scale
        if causal:
            q_pos = i * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_pos = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        lse_safe = jnp.where(lse == NEG_INF, 0.0, lse)
        p = jnp.where(lse[:, None] == NEG_INF, 0.0, jnp.exp(s - lse_safe[:, None]))
        dv = dv + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta[:, None]) * sm_scale
        dk = dk + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return dk, dv

    start = (kj * block_k) // block_q if causal else 0
    zeros = jnp.zeros((block_k, head_dim), jnp.float32)
    dk, dv = jax.lax.fori_loop(start, num_q, body, (zeros, zeros))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# pallas_call plumbing + custom VJP
# ---------------------------------------------------------------------------


def _flat(x):
    b, h, s, d = x.shape
    return x.reshape(b * h, s, d)


def _fwd_call(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    bh, seq_q, d = q.shape
    seq_k = k.shape[1]
    grid = (bh, seq_q // block_q)
    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal, block_k=block_k
    )
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, seq_k, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, seq_k, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, 1, seq_q), lambda b, i: (b, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, seq_q, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, seq_q), jnp.float32),
        ],
        compiler_params=_COMPILER_PARAMS,
        interpret=interpret,
    )(q, k, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    o, _ = _fwd_call(_flat(q), _flat(k), _flat(v), causal, sm_scale, block_q, block_k, interpret)
    return o.reshape(q.shape)


def _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    o, lse = _fwd_call(
        _flat(q), _flat(k), _flat(v), causal, sm_scale, block_q, block_k, interpret
    )
    return o.reshape(q.shape), (q, k, v, o.reshape(q.shape), lse)


def _flash_bwd(causal, sm_scale, block_q, block_k, interpret, res, g):
    q, k, v, o, lse = res
    shape = q.shape
    qf, kf, vf, of, gf = _flat(q), _flat(k), _flat(v), _flat(o), _flat(g)
    bh, seq_q, d = qf.shape
    seq_k = kf.shape[1]
    delta = jnp.sum(of.astype(jnp.float32) * gf.astype(jnp.float32), axis=-1)[:, None, :]

    dq_kernel = functools.partial(
        _bwd_dq_kernel, sm_scale=sm_scale, causal=causal, block_k=block_k
    )
    dq = pl.pallas_call(
        dq_kernel,
        grid=(bh, seq_q // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, seq_k, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, seq_k, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, 1, seq_q), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, 1, seq_q), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, seq_q, d), q.dtype),
        compiler_params=_COMPILER_PARAMS,
        interpret=interpret,
    )(qf, kf, vf, gf, lse, delta)

    dkv_kernel = functools.partial(
        _bwd_dkv_kernel, sm_scale=sm_scale, causal=causal, block_q=block_q
    )
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(bh, seq_k // block_k),
        in_specs=[
            pl.BlockSpec((1, seq_q, d), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, seq_q, d), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, 1, seq_q), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, 1, seq_q), lambda b, j: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, seq_k, d), k.dtype),
            jax.ShapeDtypeStruct((bh, seq_k, d), v.dtype),
        ],
        compiler_params=_COMPILER_PARAMS,
        interpret=interpret,
    )(qf, kf, vf, gf, lse, delta)

    return dq.reshape(shape), dk.reshape(k.shape), dv.reshape(v.shape)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    sm_scale: float | None = None,
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """Blocked flash attention over ``(batch, heads, seq, head_dim)``.

    Falls back to the XLA reference when sequence lengths don't divide
    the block sizes. ``interpret=None`` auto-selects the Pallas
    interpreter off-TPU so tests exercise the same kernel code on the
    fake CPU mesh (SURVEY.md §4).
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    seq_q, seq_k = q.shape[2], k.shape[2]
    # Measured v5e defaults (BENCHMARKS.md): coarse 256/512 blocks win
    # from ~2k sequence; short sequences prefer fine 128/128 tiles.
    if block_q is None:
        block_q = 256 if seq_q >= 2048 else 128
    if block_k is None:
        block_k = 512 if seq_k >= 2048 else 128
    block_q = min(block_q, seq_q)
    block_k = min(block_k, seq_k)
    if seq_q % block_q or seq_k % block_k or (causal and seq_q != seq_k):
        return attention_reference(q, k, v, causal=causal, sm_scale=sm_scale)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash(q, k, v, causal, sm_scale, block_q, block_k, interpret)
