"""Flash attention — blocked online-softmax attention as Pallas TPU kernels.

The reference never shards or fuses attention (it has no transformer at
all, SURVEY.md §5 "Long-context … Absent"), but long-context support is
first-class in this framework, and the memory wall for attention is the
(seq, seq) score matrix. Scores live in VMEM one (block_q, block_k)
tile at a time, with the online-softmax statistics (running max ``m``,
running sum ``l``) carried in fp32 VMEM scratch, so HBM traffic is
O(seq·d) instead of O(seq²).

Layout: ``(batch, heads, seq, head_dim)``. Grid is
``(batch·heads, seq_q/block_q, seq_k/block_k)`` — Pallas streams each
K/V block from HBM per grid step (double-buffered by the pipeline), so
VMEM holds only one q/k/v tile plus the accumulators and sequence
length is unbounded (tested to 32k on one v5e chip; BENCHMARKS.md).
Causal runs skip fully-masked K blocks. The backward pass is two more
kernels (dq and dk/dv) using the saved logsumexp, the standard
flash-attention-2 split.

For cross-device sequence parallelism see
``hops_tpu.parallel.ringattention`` which rotates K/V chunks over the
ICI ring and feeds each local chunk through this kernel's math.
"""

from __future__ import annotations

import functools
import math
from typing import Any

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = float("-inf")
_LANES = 128  # VPU lane width: per-row stats are broadcast across lanes

# JAX renamed pltpu.TPUCompilerParams -> CompilerParams; resolve
# whichever the installed version carries so the module imports on both.
_CompilerParams = getattr(
    pltpu, "CompilerParams", getattr(pltpu, "TPUCompilerParams", None)
)
if _CompilerParams is None:  # pragma: no cover — future rename
    raise ImportError(
        "jax.experimental.pallas.tpu has neither CompilerParams nor "
        "TPUCompilerParams; update the compat shim in ops/attention.py"
    )

# The (batch·heads) grid dim is embarrassingly parallel; the q/k block
# dims carry scratch state between steps and must stay "arbitrary".
_COMPILER_PARAMS = _CompilerParams(
    dimension_semantics=("parallel", "arbitrary", "arbitrary")
)



def repeat_kv(q: jax.Array, k: jax.Array, v: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Broadcast GQA kv heads to match q's head count (no-op for MHA).

    The single definition of the grouping layout: kv head j serves the
    contiguous query heads ``j*g .. j*g + g - 1`` — the same order
    :func:`decode_attention`'s row folding assumes.
    """
    if q.shape[1] == k.shape[1]:
        return k, v
    g = q.shape[1] // k.shape[1]
    return jnp.repeat(k, g, axis=1), jnp.repeat(v, g, axis=1)


def attention_reference(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    sm_scale: float | None = None,
    q_offset: int | None = None,
    window: int | None = None,
) -> jax.Array:
    """Pure-XLA attention: numeric ground truth + fallback path.

    ``q_offset`` places query row i at absolute position ``i + q_offset``
    in the key sequence; the causal default aligns the queries with the
    *last* ``seq_q`` keys (the chunked-prefill convention: the q chunk
    extends an existing KV prefix).
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    if window is not None and (not causal or window < 1):
        raise ValueError("window requires causal=True and window >= 1")
    if q_offset is None:
        q_offset = k.shape[2] - q.shape[2] if causal else 0
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32)
    s = s * sm_scale
    if causal:
        # q_offset may be per-batch (shape (b,) — the ragged-decode
        # path, each row's chunk at its own absolute position) or a
        # scalar; the mask broadcasts to (b, 1, sq, sk) either way.
        off = jnp.asarray(q_offset)
        off = off[:, None, None] if off.ndim == 1 else off
        q_pos = jnp.arange(q.shape[2])[:, None] + off
        k_pos = jnp.arange(k.shape[2])[None, :]
        visible = q_pos >= k_pos
        if window is not None:
            visible &= q_pos - k_pos < window
        if visible.ndim == 3:
            visible = visible[:, None]
        s = jnp.where(visible, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


def _causal_mask(s, qi, kj, block_q, block_k, q_offset, window=None):
    q_pos = qi * block_q + q_offset + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    k_pos = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    visible = q_pos >= k_pos
    if window is not None:
        visible &= q_pos - k_pos < window
    return jnp.where(visible, s, NEG_INF)


def _block_runs(qi, kj, block_q, block_k, q_offset, causal, window):
    """Whether a (qi, kj) tile intersects the (windowed-)causal band —
    tiles past the diagonal AND tiles fully below the sliding window
    are skipped entirely, making long-sequence windowed attention
    O(seq * window) compute."""
    if not causal:
        return True
    runs = kj * block_k < (qi + 1) * block_q + q_offset
    if window is not None:
        # Tile's newest key vs the oldest position the tile's oldest
        # query still sees.
        runs = jnp.logical_and(
            runs, (kj + 1) * block_k - 1 >= qi * block_q + q_offset - (window - 1)
        )
    return runs


# ---------------------------------------------------------------------------
# Forward kernel: grid (bh, nq, nk), K/V streamed per grid step
# ---------------------------------------------------------------------------


def _online_softmax_update(sc, vb, m_scr, l_scr, acc_scr, p_scale=None):
    """Fold one masked score block ``sc`` (fp32, -inf at masked entries)
    and its value tile ``vb`` into the running (m, l, acc)
    online-softmax scratch. The NEG_INF guards keep fully-masked rows
    at l == 0 (finalize substitutes 1) instead of NaN. Shared by the
    training forward kernel and both decode kernels — this rescaling
    is the subtlest numerics in the file and must exist exactly once.

    ``p_scale`` (1, block_k) folds a per-key scale into the prob@value
    dot ONLY (the int8 path's v_scale — ``vb`` then holds raw int8
    values cast to its dtype); the softmax denominator ``l`` always
    sums the UNSCALED probs."""
    m = m_scr[:, :1]  # (rows, 1), broadcast across lanes
    l = l_scr[:, :1]
    m_new = jnp.maximum(m, jnp.max(sc, axis=-1, keepdims=True))
    m_safe = jnp.where(m_new == NEG_INF, 0.0, m_new)
    p = jnp.exp(sc - m_safe)
    alpha = jnp.exp(jnp.where(m == NEG_INF, NEG_INF, m - m_safe))
    l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
    pv = jax.lax.dot_general(
        (p if p_scale is None else p * p_scale).astype(vb.dtype), vb,
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
    )
    acc_scr[...] = acc_scr[...] * alpha + pv
    m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
    l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)


def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
    *, sm_scale, causal, block_q, block_k, q_offset, window,
):
    qi, kj = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # Causal: skip K blocks above the diagonal or below the window.
    run = _block_runs(qi, kj, block_q, block_k, q_offset, causal, window)

    @pl.when(run)
    def _step():
        q = q_ref[0]
        kb = k_ref[0]
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        s = s * sm_scale
        if causal:
            s = _causal_mask(s, qi, kj, block_q, block_k, q_offset, window)
        _online_softmax_update(s, v_ref[0], m_scr, l_scr, acc_scr)

    @pl.when(kj == nk - 1)
    def _finalize():
        m = m_scr[:, :1]
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[...] / l_safe).astype(o_ref.dtype)
        lse = jnp.where(m == NEG_INF, NEG_INF, m + jnp.log(l_safe))
        # lse rides as a full (1, 1, seq_q) row per (batch·head) — TPU
        # block shapes must tile (8, 128) or span their dims, so each
        # q-block program dynamic-stores its slice of the shared row.
        lse_ref[0, 0, pl.ds(qi * block_q, block_q)] = lse[:, 0]


# ---------------------------------------------------------------------------
# Backward kernels (flash-attention-2 split: dq, then dk/dv)
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_scr,
    *, sm_scale, causal, block_q, block_k, q_offset, window,
):
    qi, kj = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    run = _block_runs(qi, kj, block_q, block_k, q_offset, causal, window)

    @pl.when(run)
    def _step():
        q = q_ref[0].astype(jnp.float32)
        kb = k_ref[0].astype(jnp.float32)
        vb = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0, 0, pl.ds(qi * block_q, block_q)][:, None]
        delta = delta_ref[0, 0, pl.ds(qi * block_q, block_q)][:, None]
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        s = s * sm_scale
        if causal:
            s = _causal_mask(s, qi, kj, block_q, block_k, q_offset, window)
        lse_safe = jnp.where(lse == NEG_INF, 0.0, lse)
        p = jnp.where(lse == NEG_INF, 0.0, jnp.exp(s - lse_safe))
        dp = jax.lax.dot_general(
            do, vb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta) * sm_scale
        dq_scr[...] += jax.lax.dot_general(
            ds, kb, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(kj == nk - 1)
    def _finalize():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    dk_scr, dv_scr, *, sm_scale, causal, block_q, block_k, q_offset, window,
):
    kj, qi = pl.program_id(1), pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    run = _block_runs(qi, kj, block_q, block_k, q_offset, causal, window)

    @pl.when(run)
    def _step():
        q = q_ref[0].astype(jnp.float32)
        kb = k_ref[0].astype(jnp.float32)
        vb = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0, 0, pl.ds(qi * block_q, block_q)][:, None]
        delta = delta_ref[0, 0, pl.ds(qi * block_q, block_q)][:, None]
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        s = s * sm_scale
        if causal:
            s = _causal_mask(s, qi, kj, block_q, block_k, q_offset, window)
        lse_safe = jnp.where(lse == NEG_INF, 0.0, lse)
        p = jnp.where(lse == NEG_INF, 0.0, jnp.exp(s - lse_safe))
        dv_scr[...] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            do, vb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta) * sm_scale
        dk_scr[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# pallas_call plumbing + custom VJP
# ---------------------------------------------------------------------------


def _flat(x):
    b, h, s, d = x.shape
    return x.reshape(b * h, s, d)


def _fwd_call(q, k, v, causal, sm_scale, block_q, block_k, q_offset, window, interpret):
    bh, seq_q, d = q.shape
    seq_k = k.shape[1]
    grid = (bh, seq_q // block_q, seq_k // block_k)
    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal, block_q=block_q,
        block_k=block_k, q_offset=q_offset, window=window,
    )
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 1, seq_q), lambda b, i, j: (b, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, seq_q, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, seq_q), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=_COMPILER_PARAMS,
        interpret=interpret,
    )(q, k, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def _flash(q, k, v, causal, sm_scale, block_q, block_k, q_offset, window, interpret):
    o, _ = _fwd_call(
        _flat(q), _flat(k), _flat(v), causal, sm_scale, block_q, block_k,
        q_offset, window, interpret,
    )
    return o.reshape(q.shape)


def _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k, q_offset, window, interpret):
    o, lse = _fwd_call(
        _flat(q), _flat(k), _flat(v), causal, sm_scale, block_q, block_k,
        q_offset, window, interpret,
    )
    return o.reshape(q.shape), (q, k, v, o.reshape(q.shape), lse)


def _flash_bwd(causal, sm_scale, block_q, block_k, q_offset, window, interpret, res, g):
    q, k, v, o, lse = res
    shape = q.shape
    qf, kf, vf, of, gf = _flat(q), _flat(k), _flat(v), _flat(o), _flat(g)
    bh, seq_q, d = qf.shape
    seq_k = kf.shape[1]
    delta = jnp.sum(of.astype(jnp.float32) * gf.astype(jnp.float32), axis=-1)[:, None, :]

    dq_kernel = functools.partial(
        _bwd_dq_kernel, sm_scale=sm_scale, causal=causal, block_q=block_q,
        block_k=block_k, q_offset=q_offset, window=window,
    )
    dq = pl.pallas_call(
        dq_kernel,
        grid=(bh, seq_q // block_q, seq_k // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 1, seq_q), lambda b, i, j: (b, 0, 0)),
            pl.BlockSpec((1, 1, seq_q), lambda b, i, j: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, seq_q, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_COMPILER_PARAMS,
        interpret=interpret,
    )(qf, kf, vf, gf, lse, delta)

    dkv_kernel = functools.partial(
        _bwd_dkv_kernel, sm_scale=sm_scale, causal=causal, block_q=block_q,
        block_k=block_k, q_offset=q_offset, window=window,
    )
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(bh, seq_k // block_k, seq_q // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, 1, seq_q), lambda b, j, i: (b, 0, 0)),
            pl.BlockSpec((1, 1, seq_q), lambda b, j, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, seq_k, d), k.dtype),
            jax.ShapeDtypeStruct((bh, seq_k, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=_COMPILER_PARAMS,
        interpret=interpret,
    )(qf, kf, vf, gf, lse, delta)

    return dq.reshape(shape), dk.reshape(k.shape), dv.reshape(v.shape)


_flash.defvjp(_flash_fwd, _flash_bwd)


def _fit_block(seq: int, preferred: int) -> int | None:
    """Largest block ≤ preferred that divides ``seq`` (128-granular)."""
    for b in (preferred, 2048, 1024, 512, 384, 256, 128):
        if b <= preferred and seq % b == 0:
            return b
    return None


# Below this key length the whole score matrix fits comfortably in VMEM
# and XLA's fused attention beats the Pallas kernel's scratch bookkeeping
# (measured on v5e, causal bf16 b4/h8/d128: flash 0.84-0.98x at
# seq<=1024, 1.16x at 1536, 1.28-3.8x beyond — BENCHMARKS.md
# "attention routing" table). Routed by measurement, not hope; pass
# block sizes explicitly to force the kernel below this.
_XLA_FASTER_BELOW = 1536


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    sm_scale: float | None = None,
    block_q: int | None = None,
    block_k: int | None = None,
    q_offset: int | None = None,
    window: int | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """Blocked flash attention over ``(batch, heads, seq, head_dim)``.

    ``window`` (causal only): query p attends keys in
    ``[p - window + 1, p]`` — Mistral-style sliding-window attention.
    Tiles fully below the window are skipped in all three kernels, so
    long-sequence compute is O(seq * window).

    Cross-length causal calls (chunked prefill: ``seq_q < seq_k``) run
    in-kernel with the query chunk placed at ``q_offset`` (default: the
    last ``seq_q`` key positions). Query rows whose positions precede
    every key (possible only with a negative offset) return zeros —
    unlike the XLA reference, which NaNs on an all-masked softmax row. Short sequences route to the XLA
    reference where it measures faster; sequences that don't divide any
    128-multiple block also fall back. ``interpret=None`` auto-selects
    the Pallas interpreter off-TPU so tests exercise the same kernel
    code on the fake CPU mesh (SURVEY.md §4).
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    if window is not None and not causal:
        raise ValueError("window requires causal=True")
    if window is not None and window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    seq_q, seq_k = q.shape[2], k.shape[2]
    if q_offset is None:
        q_offset = seq_k - seq_q if causal else 0
    forced = block_q is not None or block_k is not None
    # Measured v5e sweet spots per sequence length (BENCHMARKS.md):
    # short sequences want fine tiles, long ones coarse tiles (fewer
    # K/V refetches across q blocks). A preferred size that doesn't
    # divide the sequence shrinks to the largest 128-multiple divisor
    # rather than silently punting to the O(seq²) reference.
    if seq_k <= 1024:
        default_q, default_k = 128, 128
    elif seq_k <= 2048:
        default_q, default_k = 512, 1024
    elif seq_k <= 4096:
        default_q, default_k = 1024, 1024
    else:
        default_q, default_k = 1024, 2048
    if block_q is None:
        block_q = _fit_block(seq_q, default_q)
    if block_k is None:
        block_k = _fit_block(seq_k, default_k)
    if block_q:
        block_q = min(block_q, seq_q)
    if block_k:
        block_k = min(block_k, seq_k)
    if (
        not block_q
        or not block_k
        or seq_q % block_q
        or seq_k % block_k
        or (seq_k < _XLA_FASTER_BELOW and not forced)
    ):
        return attention_reference(
            q, k, v, causal=causal, sm_scale=sm_scale, q_offset=q_offset,
            window=window,
        )
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash(
        q, k, v, causal, sm_scale, block_q, block_k, q_offset, window, interpret
    )


# ---------------------------------------------------------------------------
# Decode attention: stream a fixed-capacity KV cache once per step
# ---------------------------------------------------------------------------


def decode_attention_reference(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    valid_len: jax.Array,
    sm_scale: float | None = None,
    window: int | None = None,
) -> jax.Array:
    """XLA ground truth for :func:`decode_attention`.

    ``q`` is ``(b, h, s, d)`` — the last ``s`` tokens, already RoPE'd,
    occupying absolute positions ``valid_len - s .. valid_len - 1``
    of the ``(b, h, capacity, d)`` caches. Exactly causal attention
    with the query chunk placed at offset ``valid_len - s``, so it
    delegates to :func:`attention_reference` (whose masking is pure
    traced arithmetic, hence a traced ``valid_len`` works). XLA lowers
    this to a badly-tiled matvec fusion at s=1 (~90 GB/s measured;
    BENCHMARKS.md "KV-cached decoding") — kept only as ground truth
    and shape fallback. Fewer kv heads than q heads (GQA) broadcast.
    ``valid_len`` may be a scalar or a (b,) vector (ragged decode).
    """
    vl = _normalize_valid_len(valid_len, q.shape[0])
    k, v = repeat_kv(q, k, v)
    out = attention_reference(
        q, k, v, causal=True, sm_scale=sm_scale,
        q_offset=vl - q.shape[2], window=window,
    )
    # Honor the kernel's free-slot contract on this path too: a vl == 0
    # row has every key masked, which NaNs the XLA softmax — the kernel
    # substitutes l = 1 and emits zeros, so do the same here.
    return jnp.where((vl > 0)[:, None, None, None], out, 0.0)


def _normalize_valid_len(valid_len: jax.Array, b: int) -> jax.Array:
    """``valid_len`` as a (b,) int32 vector: a scalar broadcasts
    (uniform decode), a (b,) vector passes through (ragged decode —
    each batch row's cache at its own position). Anything else is a
    caller bug."""
    vl = jnp.asarray(valid_len, jnp.int32)
    if vl.ndim == 0:
        return jnp.broadcast_to(vl, (b,))
    if vl.shape != (b,):
        raise ValueError(
            f"valid_len must be a scalar or shape ({b},), got {vl.shape}"
        )
    return vl


def _read_vl(ref, i):
    """``valid_len`` for grid row ``i`` from the scalar-prefetch
    operand (pre-expanded to one entry per (batch, kv-head) grid row).
    Some Pallas versions unwrap a 1-element operand to 0-d in BlockSpec
    index maps — accept both (the rank is static, so this branches at
    trace time)."""
    return ref if getattr(ref, "ndim", None) == 0 else ref[i]


def _decode_block_range(vl, *, block_k, s, window):
    """(first, last) k-block indices that can contain visible keys for a
    decode step whose chunk ends at traced position ``vl``: validity
    caps the top at ``ceil(vl/block_k)-1``; a sliding window lifts the
    bottom to the block holding ``vl - s - window + 1``. Shared by the
    kernels' compute guard and the BlockSpec index maps so the two can
    never disagree."""
    last = (vl + block_k - 1) // block_k - 1
    if window is None:
        first = jnp.int32(0)
    else:
        first = jnp.maximum(vl - s - window + 1, 0) // block_k
    return first, last


def _decode_mask(vl, qi, kj, *, block_q, block_k, s, rows, window):
    """(block_q, block_k) visibility of k positions to query rows.

    Row ``r`` of the folded (group*chunk) q tile holds chunk position
    ``r % s`` = absolute position ``vl - s + r % s``; rows >= ``rows``
    are padding and see nothing. Computed in-kernel from the
    scalar-prefetched ``vl`` — no XLA-materialized bias buffer."""
    row = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    k_pos = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    q_pos = vl - s + row % s
    visible = (row < rows) & (k_pos <= q_pos)
    if window is not None:
        visible &= q_pos - k_pos < window
    return visible


def _group_block_range(vl_ref, bi, *, block_bh, block_k, s, window):
    """(first, last) k-block range covering EVERY row of grid group
    ``bi`` (``block_bh`` consecutive (batch, kv-head) rows): the union
    of the per-row `_decode_block_range`s. The DMA clamp coarsens to
    this union — per-row visibility still comes from `_decode_mask`, so
    grouping trades some over-fetch on ragged batches for ``block_bh``×
    fewer grid steps (the per-step fixed cost was the measured
    bottleneck: ~2.3 us/step vs 0.2 us of DMA at block_k=512)."""
    firsts, lasts = [], []
    for g in range(block_bh):
        f, l = _decode_block_range(
            _read_vl(vl_ref, bi * block_bh + g),
            block_k=block_k, s=s, window=window,
        )
        firsts.append(f)
        lasts.append(l)
    return functools.reduce(jnp.minimum, firsts), functools.reduce(jnp.maximum, lasts)


def _decode_kernel(
    vl_ref, q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr,
    *, sm_scale, block_bh, block_q, block_k, s, rows, window,
):
    """One (bh-group, qi, kj) grid step of cache attention.

    ``vl_ref`` is the scalar-prefetched ``valid_len`` (SMEM): the
    causal/validity mask is computed in-kernel from it, and grid steps
    whose k block lies outside the group's `_group_block_range` skip
    compute — their BlockSpec index maps clamp to the range edge, so
    Mosaic revisits the previous block window and issues no HBM copy.
    HBM traffic is therefore O(max valid_len in the group), not
    O(capacity). Each step streams ``block_bh`` rows' tiles in one DMA
    and loops the (tiny) per-row attention math over them in-VMEM.
    """
    bi, qi, kj = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    first, last = _group_block_range(
        vl_ref, bi, block_bh=block_bh, block_k=block_k, s=s, window=window
    )

    @pl.when((kj >= first) & (kj <= last))
    def _body():
        for g in range(block_bh):
            vl = _read_vl(vl_ref, bi * block_bh + g)
            sc = jax.lax.dot_general(
                q_ref[g], k_ref[g], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            visible = _decode_mask(
                vl, qi, kj, block_q=block_q, block_k=block_k, s=s,
                rows=rows, window=window,
            )
            sc = jnp.where(visible, sc * sm_scale, NEG_INF)
            _online_softmax_update(
                sc, v_ref[g], m_scr.at[g], l_scr.at[g], acc_scr.at[g]
            )

    @pl.when(kj == nk - 1)
    def _finalize():
        l = l_scr[...][:, :, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[...] = (acc_scr[...] / l_safe).astype(o_ref.dtype)


def decode_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    valid_len: jax.Array,
    *,
    k_scale: jax.Array | None = None,
    v_scale: jax.Array | None = None,
    sm_scale: float | None = None,
    block_k: int | None = None,
    block_bh: int | None = None,
    window: int | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """Attention for KV-cached decoding: ``q`` (b, h, s, d) against
    fixed-capacity caches (b, h, capacity, d) of which the first
    ``valid_len`` positions are written (``valid_len`` is traced — the
    cache index AFTER the current chunk was stored; query row i sits at
    absolute position ``valid_len - s + i``). A scalar ``valid_len``
    is the uniform-batch case; a ``(b,)`` vector gives every row its
    own position — the ragged/continuous-batching path, where each
    grid row masks and clamps its DMA by its own length (a ``vl == 0``
    row attends nothing and outputs zeros).

    The XLA formulation (:func:`decode_attention_reference`) lowers the
    s=1 matvec + mask + softmax chain to a fusion that sustains only
    ~90 GB/s on v5e (BENCHMARKS.md "KV-cached decoding" — 85% of decode
    step time). Here K/V stream through the MXU in ``block_k`` tiles
    with fp32 online-softmax scratch. ``valid_len`` rides scalar
    prefetch: the mask is computed in-kernel, and k blocks past the
    valid prefix (or, with ``window``, before the window) are skipped
    by both the compute guard and the clamped BlockSpec index maps —
    Mosaic elides the HBM copy when consecutive grid steps map to the
    same block, so **decode HBM traffic is proportional to
    ``valid_len``, not cache capacity**. Query rows tile in ``block_q``
    chunks (multi-row warm-cache appends of any size stay on the
    kernel path); pad rows are fully masked and sliced off. No VJP —
    this is an inference op.

    With ``k_scale``/``v_scale`` (both or neither; fp32
    ``(b, h, capacity)`` from :func:`quantize_kv`) the caches are int8
    and tiles dequantize in VMEM — half the HBM bytes. The routing,
    masking, and block scaffolding are THIS function for both
    precisions; only the kernel body differs.
    """
    if (k_scale is None) != (v_scale is None):
        raise ValueError("pass both k_scale and v_scale, or neither")
    if window is not None and window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    quantized = k_scale is not None
    b, h, s, d = q.shape
    hkv, cap = k.shape[1], k.shape[2]
    if h % hkv:
        raise ValueError(f"{h} query heads not divisible by {hkv} kv heads")
    # GQA: the G query heads sharing a kv head fold into the row dim —
    # one (b*hkv, G*s, d) q tile attends each kv tile, so the kernel
    # streams the SMALL cache once (no head-repeat materialization).
    g = h // hkv
    rows = g * s
    valid_len = _normalize_valid_len(valid_len, b)  # scalar or (b,) ragged
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    if block_k is None:
        block_k = _fit_block(cap, 512)
    else:
        block_k = min(block_k, cap)
    # Single-token decode (small rows) runs as one padded-to-sublane q
    # tile; large warm-cache appends tile the rows in 64-row blocks.
    block_q = 64 if rows > 64 else max(8, -(-rows // 8) * 8)
    q_rows = -(-rows // block_q) * block_q
    # An explicit block_k that doesn't divide the capacity would floor
    # out of the grid and silently skip the cache tail — fall back.
    if not block_k or cap % block_k:
        if quantized:
            k = dequantize_kv(k, k_scale)
            v = dequantize_kv(v, v_scale)
            return decode_attention_reference(
                q.astype(jnp.float32), k, v, valid_len, sm_scale, window
            ).astype(q.dtype)
        return decode_attention_reference(q, k, v, valid_len, sm_scale, window)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    bh = b * hkv
    if block_bh is None:
        # Default 1: grouping rows per grid step was hypothesized to
        # amortize per-step cost, but hardware says otherwise — at
        # (b8, h8, d128, cap 16k) block_bh=8 measured 5.9 ms vs 4.9 ms
        # for block_bh=1, and the marginal streaming rate at block_bh=1
        # is already ~1 ms/GB (the HBM roofline; the fixed ~1 ms floor
        # is per-dispatch latency, not kernel time). The knob stays for
        # experimentation on other topologies.
        block_bh = 1
    elif bh % block_bh:
        raise ValueError(f"block_bh {block_bh} must divide b*kv_heads {bh}")
    qf = q.reshape(bh, rows, d)
    if q_rows != rows:
        qf = jnp.pad(qf, ((0, 0), (0, q_rows - rows), (0, 0)))
    # One valid_len per (batch, kv-head) grid row — pre-expanding the
    # (b,) vector to (bh,) keeps the index maps free of a batch/head
    # division.
    vl = jnp.repeat(valid_len, hkv)

    # Index maps receive (*grid_indices, *scalar_prefetch_refs); kernel
    # bodies receive the scalar refs FIRST — Pallas's convention.
    def kv_index(bi, qi, kj, vl_ref):
        # Out-of-range grid steps revisit the range edge's block: same
        # window as an in-range neighbor step -> Mosaic issues no copy.
        first, last = _group_block_range(
            vl_ref, bi, block_bh=block_bh, block_k=block_k, s=s, window=window
        )
        return bi, jnp.clip(kj, first, last), 0

    kv_specs = [
        pl.BlockSpec(
            (block_bh, block_q, d), lambda bi, qi, kj, vl_ref: (bi, qi, 0)
        ),
        pl.BlockSpec((block_bh, block_k, d), kv_index),
        pl.BlockSpec((block_bh, block_k, d), kv_index),
    ]
    # Scales ride as (bh, 1, cap): a 2-D (bh, cap) operand with block
    # (1, block_k) fails Mosaic's block-shape rule on real TPU (the
    # second-to-last block dim must divide 8 or equal the array dim —
    # interpret mode never checks). The lane-major layout also hands
    # the kernel (1, block_k) tiles that broadcast over score columns
    # with no relayout.
    def scale_index(bi, qi, kj, vl_ref):
        return bi, 0, kv_index(bi, qi, kj, vl_ref)[1]

    scale_specs = [
        pl.BlockSpec((block_bh, 1, block_k), scale_index),
        pl.BlockSpec((block_bh, 1, block_k), scale_index),
    ]
    args = (qf, _flat(k), _flat(v))
    if quantized:
        kernel, in_specs = _decode_q8_kernel, kv_specs + scale_specs
        args += (k_scale.reshape(bh, 1, cap), v_scale.reshape(bh, 1, cap))
    else:
        kernel, in_specs = _decode_kernel, kv_specs
    out = pl.pallas_call(
        functools.partial(
            kernel, sm_scale=sm_scale, block_bh=block_bh, block_q=block_q,
            block_k=block_k, s=s, rows=rows, window=window,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(bh // block_bh, q_rows // block_q, cap // block_k),
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (block_bh, block_q, d), lambda bi, qi, kj, vl_ref: (bi, qi, 0)
            ),
            scratch_shapes=[
                pltpu.VMEM((block_bh, block_q, _LANES), jnp.float32),
                pltpu.VMEM((block_bh, block_q, _LANES), jnp.float32),
                pltpu.VMEM((block_bh, block_q, d), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((bh, q_rows, d), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary")
        ),
        interpret=interpret,
    )(vl, *args)
    return out[:, :rows].reshape(b, hkv, g, s, d).reshape(b, h, s, d)


# ---------------------------------------------------------------------------
# Paged KV cache: block-pool storage addressed through per-row page tables
# ---------------------------------------------------------------------------


def paged_gather_kv(pool: jax.Array, pages: jax.Array) -> jax.Array:
    """Materialize the dense ``(b, hkv, max_blocks*page, d)`` view of a
    ``(hkv, nblocks, page, d)`` block pool under a ``(b, max_blocks)``
    page table — the reference formulation (and the ground truth the
    kernel is tested against). The real kernel never does this gather:
    it translates logical block -> physical block inside the BlockSpec
    index map, so pool attention costs the same HBM bytes as dense."""
    hkv, _, ps, d = pool.shape
    b, mb = pages.shape
    # pool[:, pages] -> (hkv, b, mb, ps, d); batch-major for attention.
    return jnp.moveaxis(pool[:, pages], 1, 0).reshape(b, hkv, mb * ps, d)


def paged_gather_scales(pool_s: jax.Array, pages: jax.Array) -> jax.Array:
    """Scale-table twin of :func:`paged_gather_kv`: a ``(hkv, nblocks,
    page)`` per-position scale pool gathers to the dense ``(b, hkv,
    max_blocks*page)`` view (:func:`quantize_kv`'s scale layout)."""
    hkv, _, ps = pool_s.shape
    b, mb = pages.shape
    return jnp.moveaxis(pool_s[:, pages], 1, 0).reshape(b, hkv, mb * ps)


def paged_decode_attention_reference(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    valid_len: jax.Array,
    pages: jax.Array,
    sm_scale: float | None = None,
    window: int | None = None,
    k_scale: jax.Array | None = None,
    v_scale: jax.Array | None = None,
) -> jax.Array:
    """XLA ground truth for :func:`paged_decode_attention`: gather the
    dense view, then :func:`decode_attention_reference`. Kept for (a)
    numeric tests, (b) page sizes the kernel's tiling can't take. With
    ``k_scale``/``v_scale`` pools the gathered int8 view dequantizes
    before the reference math (the kernel folds the same scales into
    its dots instead)."""
    dk = paged_gather_kv(k, pages)
    dv = paged_gather_kv(v, pages)
    if k_scale is not None:
        dk = dequantize_kv(dk, paged_gather_scales(k_scale, pages))
        dv = dequantize_kv(dv, paged_gather_scales(v_scale, pages))
        return decode_attention_reference(
            q.astype(jnp.float32), dk, dv, valid_len, sm_scale, window
        ).astype(q.dtype)
    return decode_attention_reference(q, dk, dv, valid_len, sm_scale, window)


def _paged_decode_kernel(
    vl_ref, pages_ref, q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr,
    *, sm_scale, block_q, page, s, rows, window,
):
    """One (bh, qi, kj) grid step of page-table cache attention.

    Identical math to :func:`_decode_kernel` at ``block_bh=1`` with
    ``block_k = page`` — the ONLY difference is that the k/v BlockSpec
    index maps resolved grid block ``kj`` through the scalar-prefetched
    page table before this body ran, so ``k_ref``/``v_ref`` hold the
    PHYSICAL pool block while every position in the mask math below is
    LOGICAL (``kj * page + lane``). Blocks past the row's valid prefix
    are skipped by the same compute guard / clamped-index-map pairing
    as the dense kernel, so HBM traffic is O(valid_len) here too.
    """
    bi, qi, kj = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    vl = _read_vl(vl_ref, bi)
    first, last = _decode_block_range(vl, block_k=page, s=s, window=window)

    @pl.when((kj >= first) & (kj <= last))
    def _body():
        sc = jax.lax.dot_general(
            q_ref[0], k_ref[0, 0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        visible = _decode_mask(
            vl, qi, kj, block_q=block_q, block_k=page, s=s, rows=rows,
            window=window,
        )
        sc = jnp.where(visible, sc * sm_scale, NEG_INF)
        _online_softmax_update(
            sc, v_ref[0, 0], m_scr.at[0], l_scr.at[0], acc_scr.at[0]
        )

    @pl.when(kj == nk - 1)
    def _finalize():
        l = l_scr[...][:, :, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[...] = (acc_scr[...] / l_safe).astype(o_ref.dtype)


def _paged_decode_q8_kernel(
    vl_ref, pages_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref,
    m_scr, l_scr, acc_scr,
    *, sm_scale, block_q, page, s, rows, window,
):
    """:func:`_paged_decode_kernel` over int8 pool blocks — the paged
    twin of :func:`_decode_q8_kernel`: the physical block's int8 tiles
    dot as raw casts (int8 is exact in bf16), the per-position fp32
    k-scales fold into the score columns and the v-scales into the
    prob@value dot, so no dequantized ``(page, d)`` tile is ever
    materialized and HBM streams ~1/4 the fp32 bytes per visible
    token. The scale tables ride the SAME page-table translation as
    the blocks (their BlockSpec index maps share ``kv_index``), so a
    value and its scale can never come from different physical
    blocks."""
    bi, qi, kj = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    vl = _read_vl(vl_ref, bi)
    first, last = _decode_block_range(vl, block_k=page, s=s, window=window)

    @pl.when((kj >= first) & (kj <= last))
    def _body():
        kb = k_ref[0, 0].astype(q_ref.dtype)
        sc = jax.lax.dot_general(
            q_ref[0], kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        sc = sc * ks_ref[0, 0]  # (1, page) broadcasts over q rows
        visible = _decode_mask(
            vl, qi, kj, block_q=block_q, block_k=page, s=s, rows=rows,
            window=window,
        )
        sc = jnp.where(visible, sc * sm_scale, NEG_INF)
        _online_softmax_update(
            sc, v_ref[0, 0].astype(q_ref.dtype),
            m_scr.at[0], l_scr.at[0], acc_scr.at[0],
            p_scale=vs_ref[0, 0],
        )

    @pl.when(kj == nk - 1)
    def _finalize():
        l = l_scr[...][:, :, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[...] = (acc_scr[...] / l_safe).astype(o_ref.dtype)


def paged_decode_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    valid_len: jax.Array,
    pages: jax.Array,
    *,
    k_scale: jax.Array | None = None,
    v_scale: jax.Array | None = None,
    sm_scale: float | None = None,
    window: int | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """:func:`decode_attention` over a PAGED KV cache.

    ``k``/``v`` are shared block pools ``(hkv, nblocks, page, d)`` —
    one physical allocation serving every batch row — and ``pages`` is
    the ``(b, max_blocks)`` int32 page table mapping each row's logical
    block ``j`` (cache positions ``j*page .. (j+1)*page - 1``) to a
    physical pool block. ``valid_len`` is the per-row (or scalar) cache
    index AFTER the current chunk, exactly as in the dense kernel; the
    query chunk occupies logical positions ``valid_len - s ..
    valid_len - 1``.

    The page translation happens in the BlockSpec index maps (the page
    table rides scalar prefetch next to ``valid_len``), so the kernel
    DMAs each visible physical block exactly once per grid row — HBM
    traffic is O(valid_len), the same bytes as the dense kernel, with
    no gathered intermediate. Blocks past a row's valid prefix clamp to
    the range edge and are skipped, identical to the dense kernel's
    free-slot behavior (a ``valid_len == 0`` row outputs zeros). A row
    whose page-table entries are 0 by convention points at a reserved
    scratch block; masking makes its contents unreachable.

    Pool rows the page table never references are never read. Page
    sizes that don't tile (``page % 8 != 0``) fall back to the gathered
    reference formulation.

    With ``k_scale``/``v_scale`` (both or neither; fp32 ``(hkv,
    nblocks, page)`` per-position scale pools living beside the page
    table) the pools are int8 and the kernel folds the scales into its
    dots in-VMEM — ~1/4 the fp32 HBM bytes per live token, which is
    what lets an equal-memory pool hold ~4x the blocks. Routing,
    masking, and the page translation are THIS function for both
    precisions.
    """
    if (k_scale is None) != (v_scale is None):
        raise ValueError("pass both k_scale and v_scale, or neither")
    quantized = k_scale is not None
    if window is not None and window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    b, h, s, d = q.shape
    hkv, nblocks, page, dk = k.shape
    if dk != d:
        raise ValueError(f"pool head_dim {dk} != query head_dim {d}")
    if h % hkv:
        raise ValueError(f"{h} query heads not divisible by {hkv} kv heads")
    if quantized:
        for name, sc in (("k_scale", k_scale), ("v_scale", v_scale)):
            if sc.shape != (hkv, nblocks, page):
                raise ValueError(
                    f"scale pool {name} shape {sc.shape} != "
                    f"{(hkv, nblocks, page)}"
                )
    if pages.shape[0] != b:
        raise ValueError(
            f"page table rows {pages.shape[0]} != batch {b}"
        )
    max_blocks = pages.shape[1]
    valid_len = _normalize_valid_len(valid_len, b)
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    if page % 8:
        # Sub-sublane pages can't be a Mosaic block; the gathered
        # reference is the shape fallback (tests use it as ground truth).
        return paged_decode_attention_reference(
            q, k, v, valid_len, pages, sm_scale, window,
            k_scale=k_scale, v_scale=v_scale,
        ).astype(q.dtype)
    if interpret is None:
        if jax.default_backend() != "tpu":
            # Non-TPU backends take the XLA reference twin: the paged
            # grid has one step per PAGE per (batch, kv-head) row, and
            # interpret mode executes grid steps as a host loop —
            # orders of magnitude slower than the gathered XLA
            # formulation. Pass interpret=True to force the kernel
            # (the unit tests do, to pin kernel/reference parity).
            return paged_decode_attention_reference(
                q, k, v, valid_len, pages, sm_scale, window,
                k_scale=k_scale, v_scale=v_scale,
            ).astype(q.dtype)
        interpret = False

    g = h // hkv
    rows = g * s
    bh = b * hkv
    block_q = 64 if rows > 64 else max(8, -(-rows // 8) * 8)
    q_rows = -(-rows // block_q) * block_q
    qf = q.reshape(bh, rows, d)
    if q_rows != rows:
        qf = jnp.pad(qf, ((0, 0), (0, q_rows - rows), (0, 0)))
    vl = jnp.repeat(valid_len, hkv)  # one entry per (batch, kv-head) row
    pages32 = jnp.asarray(pages, jnp.int32)

    # Index maps receive (*grid_indices, *scalar_prefetch_refs). The
    # logical->physical translation lives HERE: grid block kj clamps to
    # the row's visible range (out-of-range steps revisit the edge
    # block -> Mosaic issues no copy), then the page table picks the
    # pool block to DMA.
    def kv_index(bi, qi, kj, vl_ref, pages_ref):
        first, last = _decode_block_range(
            _read_vl(vl_ref, bi), block_k=page, s=s, window=window
        )
        kjc = jnp.maximum(jnp.clip(kj, first, last), 0)  # vl==0: last=-1
        return bi % hkv, pages_ref[bi // hkv, kjc], 0, 0

    # Scale pools ride as (hkv, nblocks, 1, page): the lane-major
    # layout hands the kernel (1, page) tiles that broadcast over score
    # columns with no relayout (same Mosaic block-shape reasoning as
    # the dense q8 path), and the index map is kv_index itself — the
    # scale tile always comes from the same physical block as its
    # values.
    q_spec = pl.BlockSpec(
        (1, block_q, d), lambda bi, qi, kj, vl_ref, pages_ref: (bi, qi, 0)
    )
    in_specs = [
        q_spec,
        pl.BlockSpec((1, 1, page, d), kv_index),
        pl.BlockSpec((1, 1, page, d), kv_index),
    ]
    args = (qf, k, v)
    if quantized:
        kernel = _paged_decode_q8_kernel
        scale_spec = pl.BlockSpec(
            (1, 1, 1, page),
            lambda bi, qi, kj, vl_ref, pages_ref: (
                *kv_index(bi, qi, kj, vl_ref, pages_ref)[:2], 0, 0),
        )
        in_specs += [scale_spec, scale_spec]
        args += (k_scale.reshape(hkv, nblocks, 1, page),
                 v_scale.reshape(hkv, nblocks, 1, page))
    else:
        kernel = _paged_decode_kernel
    out = pl.pallas_call(
        functools.partial(
            kernel, sm_scale=sm_scale, block_q=block_q,
            page=page, s=s, rows=rows, window=window,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(bh, q_rows // block_q, max_blocks),
            in_specs=in_specs,
            out_specs=q_spec,
            scratch_shapes=[
                pltpu.VMEM((1, block_q, _LANES), jnp.float32),
                pltpu.VMEM((1, block_q, _LANES), jnp.float32),
                pltpu.VMEM((1, block_q, d), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((bh, q_rows, d), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary")
        ),
        interpret=interpret,
    )(vl, pages32, *args)
    return out[:, :rows].reshape(b, hkv, g, s, d).reshape(b, h, s, d)


# ---------------------------------------------------------------------------
# int8 KV cache: half the decode HBM traffic, dequantized in-kernel
# ---------------------------------------------------------------------------


def quantize_kv(x: jax.Array, eps: float = 1e-8) -> tuple[jax.Array, jax.Array]:
    """Per-position symmetric int8 quantization over the head dim.

    ``x`` (..., seq, d) -> (int8 values, fp32 scales (..., seq)) with
    ``x ≈ values * scales[..., None]``. Decode is HBM-bound on the KV
    cache (BENCHMARKS.md "KV-cached decoding"), so storing it int8
    halves the bytes the decode kernel streams; the scale adds 4
    bytes per d-vector (<4% at d=64).
    """
    scale = jnp.max(jnp.abs(x).astype(jnp.float32), axis=-1) / 127.0
    scale = jnp.maximum(scale, eps)
    q = jnp.round(x.astype(jnp.float32) / scale[..., None])
    return jnp.clip(q, -127, 127).astype(jnp.int8), scale


def dequantize_kv(values: jax.Array, scales: jax.Array, dtype: Any = jnp.float32) -> jax.Array:
    """Inverse of :func:`quantize_kv`."""
    return (values.astype(jnp.float32) * scales[..., None].astype(jnp.float32)).astype(dtype)


def _decode_q8_kernel(
    vl_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr,
    *, sm_scale, block_bh, block_q, block_k, s, rows, window,
):
    """:func:`_decode_kernel` over int8 K/V blocks. int8 values are
    EXACT in bf16 (|x| <= 127), so the MXU dots run on raw casts and
    the fp32 scales fold into the score columns (k_scale) and the
    prob@value dot (v_scale) — no dequantized (block_k, d) tile is
    ever materialized, which is what made the first hardware
    measurement of this kernel slower than the bf16 cache it was meant
    to beat. HBM sees half the bytes."""
    bi, qi, kj = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    first, last = _group_block_range(
        vl_ref, bi, block_bh=block_bh, block_k=block_k, s=s, window=window
    )

    @pl.when((kj >= first) & (kj <= last))
    def _body():
        for g in range(block_bh):
            vl = _read_vl(vl_ref, bi * block_bh + g)
            kb = k_ref[g].astype(q_ref.dtype)
            sc = jax.lax.dot_general(
                q_ref[g], kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            sc = sc * ks_ref[g]  # (1, block_k) broadcasts over q rows
            visible = _decode_mask(
                vl, qi, kj, block_q=block_q, block_k=block_k, s=s,
                rows=rows, window=window,
            )
            sc = jnp.where(visible, sc * sm_scale, NEG_INF)
            _online_softmax_update(
                sc, v_ref[g].astype(q_ref.dtype),
                m_scr.at[g], l_scr.at[g], acc_scr.at[g],
                p_scale=vs_ref[g],
            )

    @pl.when(kj == nk - 1)
    def _finalize():
        l = l_scr[...][:, :, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[...] = (acc_scr[...] / l_safe).astype(o_ref.dtype)


def decode_attention_q8(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    k_scale: jax.Array,
    v_scale: jax.Array,
    valid_len: jax.Array,
    **kwargs: Any,
) -> jax.Array:
    """:func:`decode_attention` over an int8-quantized KV cache:
    ``k``/``v`` are int8 ``(b, h, capacity, d)`` with fp32 scales
    ``(b, h, capacity)`` from :func:`quantize_kv`. Thin wrapper — the
    routing/masking/scaffolding live in :func:`decode_attention` so
    the two precisions can never diverge."""
    return decode_attention(
        q, k, v, valid_len, k_scale=k_scale, v_scale=v_scale, **kwargs
    )
