"""TensorBoard-contract module: per-run logdir + scalar/profiler APIs.

Matches the surface of the reference's ``hops.tensorboard``
(``tensorboard.logdir()`` — notebooks/ml/Experiment/Tensorflow/
mnist.ipynb:55-61, SURVEY.md §2.3): user code asks for the current
run's directory and writes logs/checkpoints/events there. Scalars go to
a JSONL event stream readable by the registry tooling; profiler traces
use ``jax.profiler`` into the same dir (viewable in TensorBoard/XProf —
the reference's `profile_batch` equivalent, SURVEY.md §5).
"""

from __future__ import annotations

import contextlib
import threading
from pathlib import Path
from typing import Iterator

import jax

from hops_tpu.runtime import rundir
from hops_tpu.runtime.logging import MetricLogger
from hops_tpu.telemetry.spans import StepTimer

_writers: dict[str, MetricLogger] = {}
# Step-cadence telemetry derived from the scalar stream: the first
# scalar() of each NEW step marks a step boundary, so existing training
# wrappers feed hops_tpu_step_seconds / hops_tpu_steps_total (and the
# heartbeat gauge) without code changes. One timer PER RUN DIR: search
# trials log concurrently from a thread pool, and a shared clock would
# measure inter-trial gaps instead of step times (they still feed the
# same loop="experiment" series).
_step_timers: dict[str, StepTimer] = {}
_last_step: dict[str, int] = {}
_step_lock = threading.Lock()


def logdir() -> str:
    """The active run's log/checkpoint/working directory."""
    return rundir.logdir()


def _writer() -> MetricLogger:
    ld = logdir()
    if ld not in _writers:
        _writers[ld] = MetricLogger(Path(ld) / "metrics.jsonl")
    return _writers[ld]


def scalar(step: int, tag: str, value) -> None:
    """Log a scalar event into the run's metric stream (and tick the
    step-telemetry clock when ``step`` advances)."""
    ld = logdir()
    _writer().log(step, tag, value)
    with _step_lock:
        last = _last_step.get(ld)
        if last is not None and step <= last:
            return
        _last_step[ld] = step
        timer = _step_timers.get(ld)
        if timer is None:
            timer = _step_timers[ld] = StepTimer(loop="experiment")
        if last is None:  # first scalar of a run only arms the clock
            timer.arm()
        else:
            timer.tick()


def flush() -> None:
    for w in _writers.values():
        w.flush()


def close(run_logdir: str | None = None) -> None:
    """Close and evict the writer for ``run_logdir`` (default: the active
    run). Launchers call this when a run finalizes so long-lived drivers
    don't accumulate open file handles."""
    key = run_logdir or rundir.logdir()
    with _step_lock:
        _last_step.pop(key, None)
        _step_timers.pop(key, None)
    w = _writers.pop(key, None)
    if w is not None:
        w.close()


@contextlib.contextmanager
def profile(tag: str = "trace") -> Iterator[None]:
    """Capture a jax.profiler trace window into the run dir (the
    reference's Keras ``profile_batch='5,10'`` — SURVEY.md §5)."""
    with jax.profiler.trace(str(Path(logdir()) / tag)):
        yield
