"""Experiment layer — launchers, hparam drivers, registry, tensorboard.

Reference surface (SURVEY.md §2.3): ``experiment.launch / .mirrored /
.grid_search / .differential_evolution`` plus ``tensorboard.logdir()``.
The maggy-style async driver lives in ``hops_tpu.search`` and is
re-exported as ``experiment.lagom``.
"""

from hops_tpu.experiment import registry, tensorboard  # noqa: F401
from hops_tpu.experiment.core import (  # noqa: F401
    collective_all_reduce,
    launch,
    mirrored,
    parameter_server,
)


def grid_search(*args, **kwargs):
    """Exhaustive cartesian hparam sweep (reference:
    ``experiment.grid_search``, grid_search_fashion_mnist.ipynb:311)."""
    from hops_tpu.search.drivers import grid_search as _gs

    return _gs(*args, **kwargs)


def differential_evolution(*args, **kwargs):
    """Genetic search over bounded ranges (reference:
    ``experiment.differential_evolution``, evolutionary_search_mnist.ipynb:267)."""
    from hops_tpu.search.drivers import differential_evolution as _de

    return _de(*args, **kwargs)


def lagom(*args, **kwargs):
    """Async parallel-trial driver (reference: ``maggy.experiment.lagom``,
    SURVEY.md §2.4)."""
    from hops_tpu.search.drivers import lagom as _lagom

    return _lagom(*args, **kwargs)
