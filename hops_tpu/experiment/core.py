"""Experiment launchers: ``launch`` / ``mirrored`` / ``collective_all_reduce``.

The reference's core UX (SURVEY.md §2.3): the user hands the launcher a
**wrapper function containing the whole training program**; the launcher
provisions the run (directory, logging, distribution context), executes
it, collects the returned metrics dict, syncs the logdir into the
project's Experiments dataset, registers the run, and returns
``(experiment_dir, metrics_dict)`` where the dict carries a ``'log'``
path — e.g. ``('…/Experiments/application_…_3', {'accuracy': 0.83,
'log': '…/output.log'})``.

On Spark the launcher scheduled the wrapper onto executors; here the
wrapper runs SPMD on the slice: ``launch`` gives it the default device,
``mirrored`` a single-host data-parallel mesh, ``collective_all_reduce``
the full-slice mesh (every host executes the same wrapper; host 0 is
chief). ``parameter_server`` exists as a documented alias (SURVEY.md
§2.9 row 3).
"""

from __future__ import annotations

import contextlib
import io
import sys
import time
import traceback
from pathlib import Path
from typing import Any, Callable

from hops_tpu.experiment import registry
from hops_tpu.parallel import multihost
from hops_tpu.parallel.strategy import (
    CollectiveAllReduceStrategy,
    MirroredStrategy,
    Strategy,
)
from hops_tpu.runtime import rundir
from hops_tpu.runtime.logging import attach_run_log, detach_run_log, get_logger, scalarize
from hops_tpu.telemetry.metrics import REGISTRY

log = get_logger(__name__)

#: Experiments span seconds (smoke tests) to hours (real training).
_DURATION_BUCKETS = (0.1, 0.5, 1.0, 5.0, 15.0, 60.0, 300.0, 1800.0, 7200.0)


class _Tee(io.TextIOBase):
    def __init__(self, *streams):
        self.streams = streams

    def write(self, s):
        for st in self.streams:
            st.write(s)
        return len(s)

    def flush(self):
        for st in self.streams:
            st.flush()


def _normalize_metrics(result: Any, metric_key: str | None) -> dict[str, Any]:
    if result is None:
        metrics: dict[str, Any] = {"metric": None}
    elif isinstance(result, dict):
        metrics = dict(result)
        if metric_key is not None:
            metrics["metric"] = metrics.get(metric_key)
        elif "metric" not in metrics and len(metrics) == 1:
            metrics["metric"] = next(iter(metrics.values()))
    else:
        metrics = {"metric": result}
    return metrics


def _run_wrapper(
    fn: Callable[..., Any],
    kwargs: dict[str, Any] | None,
    name: str,
    kind: str,
    local_logdir: bool,
    metric_key: str | None,
    strategy: Strategy | None,
) -> tuple[str, dict[str, Any]]:
    """Shared launcher mechanics for all experiment kinds."""
    run = rundir.new_run(name=name, local_logdir=local_logdir)
    chief = multihost.is_chief()
    if chief:
        registry.register(
            {"run_id": run.run_id, "name": name, "kind": kind, "status": "RUNNING"}
        )
    start = time.time()
    out_path = Path(run.logdir) / "output.log"
    handler = attach_run_log(out_path)
    status, metrics, err = "FINISHED", {}, None
    with rundir.activate(run):
        out_file = out_path.open("a")
        tee_out = _Tee(sys.stdout, out_file)
        try:
            with contextlib.redirect_stdout(tee_out):
                ctx = strategy.scope() if strategy is not None else contextlib.nullcontext()
                with ctx:
                    result = fn(**kwargs) if kwargs else fn()
            metrics = _normalize_metrics(result, metric_key)
        except Exception as e:  # noqa: BLE001 — failures must land in the registry
            status, err = "FAILED", e
            tee_out.write(traceback.format_exc())
        finally:
            tee_out.flush()
            out_file.close()
            detach_run_log(handler)
            from hops_tpu.experiment import tensorboard as _tb

            _tb.close(run.logdir)
    final_path = run.finalize()
    # Launcher telemetry: run outcomes by kind, and wall time. Step
    # cadence (step time / steps/sec) rides the tensorboard.scalar
    # stream and run_preemptible's StepTimer, not the launcher.
    REGISTRY.counter(
        "hops_tpu_experiment_runs_total",
        "Experiment runs by launcher kind and final status",
        labels=("kind", "status"),
    ).inc(kind=kind, status=status)
    REGISTRY.histogram(
        "hops_tpu_experiment_duration_seconds",
        "Wall time of experiment runs",
        labels=("kind",), buckets=_DURATION_BUCKETS,
    ).observe(time.time() - start, kind=kind)
    if chief:
        registry.register(
            {
                "run_id": run.run_id,
                "name": name,
                "kind": kind,
                "status": status,
                "metrics": {k: scalarize(v) for k, v in metrics.items()},
                "metric_key": metric_key,
                "duration_s": time.time() - start,
                "path": final_path,
                "num_replicas": strategy.num_replicas_in_sync if strategy else 1,
            }
        )
    if err is not None:
        raise err
    metrics["log"] = str(Path(final_path) / "output.log")
    return final_path, metrics


def launch(
    fn: Callable[..., Any],
    args: dict[str, Any] | None = None,
    name: str = "no-name",
    local_logdir: bool = False,
    metric_key: str | None = None,
) -> tuple[str, dict[str, Any]]:
    """Single experiment (reference: ``experiment.launch``,
    notebooks/ml/Experiment/Tensorflow/mnist.ipynb:228)."""
    return _run_wrapper(fn, args, name, "launch", local_logdir, metric_key, None)


def mirrored(
    fn: Callable[..., Any],
    args: dict[str, Any] | None = None,
    name: str = "no-name",
    local_logdir: bool = False,
    metric_key: str | None = None,
    grad_comms: Any | None = None,
) -> tuple[str, dict[str, Any]]:
    """Single-host data-parallel training over this host's chips
    (reference: ``experiment.mirrored`` + ``MirroredStrategy``,
    mirroredstrategy_mnist_example.ipynb:231). The wrapper sees the
    strategy via ``parallel.get_strategy()`` or by constructing
    ``MirroredStrategy()`` itself. ``grad_comms`` (a
    ``parallel.grad_comms.GradCommsConfig``) becomes the strategy's
    default gradient-communication config."""
    return _run_wrapper(
        fn, args, name, "mirrored", local_logdir, metric_key,
        MirroredStrategy(grad_comms=grad_comms),
    )


def collective_all_reduce(
    fn: Callable[..., Any],
    args: dict[str, Any] | None = None,
    name: str = "no-name",
    local_logdir: bool = False,
    metric_key: str | None = None,
    grad_comms: Any | None = None,
    update_sharding: str = "replicated",
) -> tuple[str, dict[str, Any]]:
    """Whole-slice data-parallel training; gradient AllReduce over
    ICI/DCN (reference: multi-worker ``experiment.mirrored`` with
    ``MultiWorkerMirroredStrategy``+NCCL, and the
    ``collective_all_reduce`` mode named in BASELINE.json).
    ``grad_comms``/``update_sharding`` pass through to
    ``CollectiveAllReduceStrategy`` — ``update_sharding=
    "cross_replica"`` selects the ZeRO-1 sharded weight update."""
    return _run_wrapper(
        fn, args, name, "collective_all_reduce", local_logdir, metric_key,
        CollectiveAllReduceStrategy(
            update_sharding=update_sharding, grad_comms=grad_comms
        ),
    )


def parameter_server(
    fn: Callable[..., Any],
    args: dict[str, Any] | None = None,
    name: str = "no-name",
    local_logdir: bool = False,
    metric_key: str | None = None,
) -> tuple[str, dict[str, Any]]:
    """Alias of :func:`collective_all_reduce` — parameter servers have no
    TPU-native analog (SURVEY.md §2.9 row 3); the docs-only reference
    mode lowers to the same XLA collective path."""
    return collective_all_reduce(fn, args, name, local_logdir, metric_key)
