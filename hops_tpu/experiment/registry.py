"""Experiments registry — the local equivalent of the Hopsworks
Experiments service the reference registered every run with
(SURVEY.md §3.1 "registers run in Experiments service").

Backed by an append-only JSONL index in the project's Experiments
dataset; the latest record per run_id wins, so status transitions
(RUNNING -> FINISHED/FAILED) are appends, not rewrites.
"""

from __future__ import annotations

import json
import time
from pathlib import Path
from typing import Any

from hops_tpu.runtime import fs


def _index_path() -> Path:
    p = Path(fs.project_path("Experiments")) / "index.jsonl"
    p.parent.mkdir(parents=True, exist_ok=True)
    return p


def register(record: dict[str, Any]) -> None:
    record = dict(record)
    record.setdefault("time", time.time())
    with _index_path().open("a") as f:
        f.write(json.dumps(record, default=str) + "\n")
    # Make the run findable (the platform indexed runs into ES for the
    # Experiments UI search; SURVEY.md §2.2 elasticsearch row). Indexing
    # is best-effort: the JSONL append above is the record of truth, and
    # a search-index failure must not fail run registration.
    try:
        from hops_tpu.messaging import searchindex

        searchindex.index_run(record)
    except Exception as exc:  # pragma: no cover - defensive
        from hops_tpu.runtime.logging import get_logger

        get_logger(__name__).warning("run search-indexing failed: %s", exc)


def list_runs(name: str | None = None) -> list[dict[str, Any]]:
    """All runs (latest record per run_id), optionally filtered by name."""
    path = _index_path()
    if not path.exists():
        return []
    latest: dict[str, dict[str, Any]] = {}
    for line in path.read_text().splitlines():
        if not line.strip():
            continue
        rec = json.loads(line)
        latest[rec["run_id"]] = {**latest.get(rec["run_id"], {}), **rec}
    runs = sorted(latest.values(), key=lambda r: r.get("time", 0))
    if name is not None:
        runs = [r for r in runs if r.get("name") == name]
    return runs


def get_run(run_id: str) -> dict[str, Any] | None:
    for rec in list_runs():
        if rec["run_id"] == run_id:
            return rec
    return None


def best_run(
    name: str | None = None, metric: str = "metric", direction: str = "max"
) -> dict[str, Any] | None:
    """Best finished run by a metric (the experiment-level counterpart of
    ``model.get_best_model`` — SURVEY.md §2.5)."""
    candidates = [
        r
        for r in list_runs(name)
        if r.get("status") == "FINISHED" and _metric_of(r, metric) is not None
    ]
    if not candidates:
        return None
    key = lambda r: _metric_of(r, metric)  # noqa: E731
    return max(candidates, key=key) if direction.lower() == "max" else min(candidates, key=key)


def _metric_of(rec: dict[str, Any], metric: str) -> float | None:
    m = rec.get("metrics") or {}
    v = m.get(metric, rec.get(metric))
    try:
        return float(v)
    except (TypeError, ValueError):
        return None
