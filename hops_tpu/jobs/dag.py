"""DAG operators + a minimal scheduler — the Airflow layer, standalone.

The reference orchestrates with three Airflow pieces
(``airflow/launch_jobs.py:79-130``, ``feature_group_validation.py:76-93``):
``HopsworksLaunchOperator`` (submit a job, optionally wait),
``HopsworksJobSuccessSensor`` (block until latest execution succeeds)
and ``HopsworksFeatureValidationResult`` (fail the pipeline on bad
data). The same three operators exist here over the local jobs API,
plus a dependency-ordered runner so ``task0 >> [task1, task2] >> gate``
pipelines execute without an Airflow install; the classes are plain
objects, so they can equally be wrapped by a real scheduler.
"""

from __future__ import annotations

import time
from typing import Any

from hops_tpu.jobs import api
from hops_tpu.runtime.logging import get_logger

log = get_logger(__name__)


class Operator:
    """Base task node; ``a >> b`` makes ``b`` depend on ``a``."""

    def __init__(self, task_id: str, dag: "DAG | None" = None):
        self.task_id = task_id
        self.upstream: list[Operator] = []
        self.downstream: list[Operator] = []
        self.state = "PENDING"  # PENDING | SUCCESS | FAILED | SKIPPED
        self.dag = dag
        if dag is not None:
            dag.add(self)

    def __rshift__(self, other):
        others = other if isinstance(other, (list, tuple)) else [other]
        for o in others:
            o.upstream.append(self)
            self.downstream.append(o)
        return other

    def __rrshift__(self, others):
        for o in others:
            o.__rshift__(self)
        return self

    def __lshift__(self, other):
        others = other if isinstance(other, (list, tuple)) else [other]
        for o in others:
            o.__rshift__(self)
        return other

    def execute(self, context: dict[str, Any]) -> None:
        raise NotImplementedError


class PythonOperator(Operator):
    def __init__(self, task_id: str, python_callable, dag=None, op_kwargs=None):
        super().__init__(task_id, dag)
        self.python_callable = python_callable
        self.op_kwargs = op_kwargs or {}

    def execute(self, context):
        context[self.task_id] = self.python_callable(**self.op_kwargs)


class JobLaunchOperator(Operator):
    """Submit a registered job (reference: ``HopsworksLaunchOperator``,
    launch_jobs.py:98-107 — job must already exist in the project)."""

    def __init__(
        self,
        task_id: str,
        job_name: str,
        job_arguments: list[str] | None = None,
        wait_for_completion: bool = True,
        timeout_s: float = 600.0,
        dag=None,
    ):
        super().__init__(task_id, dag)
        self.job_name = job_name
        self.job_arguments = job_arguments
        self.wait = wait_for_completion
        self.timeout_s = timeout_s

    def execute(self, context):
        ex = api.start_job(self.job_name, self.job_arguments)
        context[self.task_id] = ex.execution_id
        if self.wait:
            done = api.wait_for_completion(self.job_name, ex.execution_id, self.timeout_s)
            if done.state != "FINISHED":
                raise RuntimeError(
                    f"job {self.job_name} execution {ex.execution_id} ended {done.state}"
                )


class JobSuccessSensor(Operator):
    """Block until the job's newest execution finishes successfully
    (reference: ``HopsworksJobSuccessSensor``, launch_jobs.py:120-123)."""

    def __init__(self, task_id: str, job_name: str, timeout_s: float = 600.0, poke_s: float = 0.2, dag=None):
        super().__init__(task_id, dag)
        self.job_name = job_name
        self.timeout_s = timeout_s
        self.poke_s = poke_s

    def execute(self, context):
        deadline = time.monotonic() + self.timeout_s
        while time.monotonic() < deadline:
            exs = api.get_executions(self.job_name)
            if exs and exs[0].final:
                if exs[0].state == "FINISHED":
                    return
                raise RuntimeError(
                    f"job {self.job_name} latest execution ended {exs[0].state}"
                )
            time.sleep(self.poke_s)
        raise TimeoutError(f"sensor {self.task_id} timed out on job {self.job_name}")


class FeatureValidationResult(Operator):
    """Fail the pipeline when a feature group's latest validation is not
    SUCCESS (reference: ``HopsworksFeatureValidationResult``,
    feature_group_validation.py:88-93 — "unit test for data")."""

    def __init__(self, task_id: str, feature_group_name: str, version: int = 1, dag=None):
        super().__init__(task_id, dag)
        self.feature_group_name = feature_group_name
        self.version = version

    def execute(self, context):
        import hops_tpu.featurestore as hsfs

        fs = hsfs.connection().get_feature_store()
        fg = fs.get_feature_group(self.feature_group_name, self.version)
        validations = fg.get_validations()
        if not validations:
            raise RuntimeError(f"feature group {self.feature_group_name} never validated")
        latest = validations[-1]
        if latest.get("status") not in ("SUCCESS", "WARNING"):
            raise RuntimeError(
                f"feature group {self.feature_group_name} validation {latest.get('status')}"
            )
        context[self.task_id] = latest


class DAG:
    """Dependency-ordered executor with fail-fast downstream skipping."""

    def __init__(self, dag_id: str):
        self.dag_id = dag_id
        self.tasks: list[Operator] = []

    def add(self, op: Operator) -> None:
        self.tasks.append(op)
        op.dag = self

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def run(self) -> dict[str, Any]:
        """Execute topologically; returns the shared context. Raises the
        first task failure after marking downstreams SKIPPED."""
        context: dict[str, Any] = {}
        done: set[str] = set()
        failure: Exception | None = None
        pending = list(self.tasks)
        while pending:
            ready = [
                t
                for t in pending
                if t.state == "PENDING" and all(u.task_id in done for u in t.upstream)
            ]
            if not ready:
                stuck = [t.task_id for t in pending if t.state == "PENDING"]
                raise RuntimeError(
                    f"dag {self.dag_id}: unsatisfiable dependencies (cycle or "
                    f"upstream task not in this DAG) for tasks {stuck}"
                )
            for task in ready:
                if any(u.state != "SUCCESS" for u in task.upstream):
                    task.state = "SKIPPED"
                    done.add(task.task_id)
                    pending.remove(task)
                    continue
                try:
                    log.info("dag %s: running %s", self.dag_id, task.task_id)
                    task.execute(context)
                    task.state = "SUCCESS"
                except Exception as e:  # noqa: BLE001 — recorded, re-raised below
                    task.state = "FAILED"
                    failure = failure or e
                done.add(task.task_id)
                pending.remove(task)
        if failure is not None:
            raise failure
        return context
