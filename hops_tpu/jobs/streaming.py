"""Streaming runners — structured streaming over the pubsub layer.

Covers two reference pieces: the Flink/Beam runner lifecycle
(``beam.create_runner``/``start_runner``, jobs_flink_client.py:45-51)
and the Kafka structured-streaming job (StructuredStreamingKafka.scala:
83-101 — readStream → decode → parquet sink with a checkpoint
location). A runner is a named, long-lived consumer loop: it drains a
pubsub topic, batches records, appends them to a parquet sink, and
persists its offset so a restarted runner resumes exactly where it
stopped (the ``checkpointLocation`` contract).
"""

from __future__ import annotations

import json
import os
import threading
import time
from pathlib import Path
from typing import Any, Callable

import pandas as pd

from hops_tpu.messaging import pubsub
from hops_tpu.runtime import fs
from hops_tpu.runtime.logging import get_logger

log = get_logger(__name__)

_runners: dict[str, "StreamingRunner"] = {}


class StreamingRunner:
    """Topic → parquet-sink pump with checkpointed offsets."""

    def __init__(
        self,
        name: str,
        topic: str,
        sink_dir: str | None = None,
        transform: Callable[[list[dict[str, Any]]], pd.DataFrame] | None = None,
        poll_interval_s: float = 0.1,
        max_batch: int = 1024,
    ):
        self.name = name
        self.topic = topic
        self.sink_dir = Path(sink_dir or fs.project_path(f"Streaming/{name}"))
        self.sink_dir.mkdir(parents=True, exist_ok=True)
        self.transform = transform
        self.poll_interval_s = poll_interval_s
        self.max_batch = max_batch
        self.state = "CREATED"  # CREATED | RUNNING | STOPPED
        # Serializes _pump_once between the loop thread and stop(drain=True).
        self._pump_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._checkpoint = self.sink_dir / "_checkpoint.json"
        self._part = 0
        self._consumer: pubsub.Consumer | None = None

    def _load_checkpoint(self) -> None:
        if self._checkpoint.exists():
            ck = json.loads(self._checkpoint.read_text())
            self._part = ck.get("next_part", 0)
            if self._consumer is not None:
                self._consumer.offset = ck.get("offset", 0)

    def _save_checkpoint(self) -> None:
        # Atomic replace: a crash mid-write must not brick the restart.
        tmp = self._checkpoint.with_suffix(".tmp")
        tmp.write_text(
            json.dumps({"next_part": self._part, "offset": self._consumer.offset})
        )
        tmp.replace(self._checkpoint)

    def _pump_once(self) -> int:
        with self._pump_lock:
            records = self._consumer.poll(self.max_batch)
            if not records:
                return 0
            values = [r["value"] for r in records]
            df = self.transform(values) if self.transform else pd.DataFrame(values)
            out = self.sink_dir / f"part-{self._part:05d}.parquet"
            # Atomic publish: read_sink() may glob concurrently (its
            # checkpointLocation contract allows external readers), and
            # a half-written parquet file is a reader crash.
            tmp = out.with_suffix(f".tmp{os.getpid()}")
            df.to_parquet(tmp, index=False)
            os.replace(tmp, out)
            self._part += 1
            self._save_checkpoint()
            return len(records)

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                n = self._pump_once()
            except Exception:  # noqa: BLE001 — a bad batch must not kill the runner
                log.exception("runner %s: batch failed", self.name)
                n = 0
            if n == 0:
                self._stop.wait(self.poll_interval_s)

    def start(self) -> "StreamingRunner":
        if self.state == "RUNNING":
            return self
        self._consumer = pubsub.Consumer(self.topic, group=f"runner-{self.name}", from_beginning=True)
        self._load_checkpoint()
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True, name=f"runner-{self.name}")
        self._thread.start()
        self.state = "RUNNING"
        return self

    def stop(self, drain: bool = True) -> None:
        if self.state != "RUNNING":
            return
        if drain:
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline and self._pump_once_safe():
                pass
        self._stop.set()
        self._thread.join(timeout=10)
        self.state = "STOPPED"

    def _pump_once_safe(self) -> int:
        try:
            return self._pump_once()
        except Exception:  # noqa: BLE001
            return 0

    def read_sink(self) -> pd.DataFrame:
        parts = sorted(self.sink_dir.glob("part-*.parquet"))
        if not parts:
            return pd.DataFrame()
        return pd.concat([pd.read_parquet(p) for p in parts], ignore_index=True)


def create_runner(name: str, topic: str, **kwargs: Any) -> StreamingRunner:
    """Create or fetch a named runner (``beam.create_runner`` shape).

    Re-creating an existing name with a different topic is an error —
    silently handing back the old runner would sink the wrong stream.
    """
    if name in _runners:
        existing = _runners[name]
        if existing.topic != topic:
            raise ValueError(
                f"runner {name!r} already consumes topic {existing.topic!r}, "
                f"not {topic!r}"
            )
        return existing
    runner = StreamingRunner(name, topic, **kwargs)
    _runners[name] = runner
    return runner


def start_runner(name: str) -> StreamingRunner:
    return _runners[name].start()


def get_runner(name: str) -> StreamingRunner:
    return _runners[name]


def stop_runner(name: str, drain: bool = True) -> None:
    _runners[name].stop(drain=drain)
