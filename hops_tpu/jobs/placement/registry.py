"""Host registry: which machines the placement layer may place on.

Two population modes, composable:

- **Static config**: ``HostRegistry(hosts=[Host("h0", "10.0.0.4", 7070),
  ...])`` or :meth:`HostRegistry.from_config` on the same shape as
  JSON/dicts — the operator hands placement a fixed fleet.
- **Join-via-announce**: hostds started with ``--announce DIR`` write
  ``DIR/<name>.json`` atomically and re-stamp it every heartbeat;
  ``HostRegistry(announce_dir=DIR)`` lists every record whose content
  last CHANGED within ``ttl_s`` as live. A host that dies simply stops
  heartbeating and ages out — no deregistration RPC to lose.

Aging is **receiver-side, on the monotonic clock**: the registry
remembers when *it* first observed each announce's current content and
ages from that arrival time. The sender's ``ts`` stamp is display
metadata only — a hostd with a skewed wall clock (hours behind, or
stamping from the future) can neither be prematurely expired nor
immortalized, and an NTP step on the registry's own host cannot mass-
expire the fleet. This is half of the lease contract
(:mod:`~hops_tpu.jobs.placement.lease` is the other half): both sides
measure the same TTL on clocks that only move forward.

The registry answers "who exists"; health ("who answers") is the
:class:`~hops_tpu.jobs.placement.client.PlacementClient`'s per-host
breakers. Keeping those separate means a partitioned host stays in the
registry (it may heal) while the client routes around it.

Registry file format (one JSON object per announce file)::

    {"name": "h0", "address": "10.0.0.4", "port": 7070,
     "pid": 4242, "ts": 1754450000.0}
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from pathlib import Path
from typing import Any, Callable, Iterable

from hops_tpu.runtime.logging import get_logger

log = get_logger(__name__)


@dataclasses.dataclass(frozen=True)
class Host:
    """One placement target: a machine running a hostd agent."""

    name: str
    address: str
    port: int

    @property
    def endpoint(self) -> str:
        return f"http://{self.address}:{self.port}"

    @property
    def key(self) -> str:
        return f"{self.address}:{self.port}"


class HostRegistry:
    """The set of hosts placement may use (static, announced, or both).

    Thread-safe for the read path; :meth:`add` / :meth:`remove` mutate
    the static set (tests, operator reconfiguration). Announce records
    are re-read on every :meth:`hosts` call — they are tiny files and
    the placement client only consults the registry on control-plane
    actions, never per request.
    """

    def __init__(
        self,
        hosts: Iterable[Host] = (),
        *,
        announce_dir: str | Path | None = None,
        ttl_s: float = 10.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self._static: dict[str, Host] = {h.name: h for h in hosts}
        self._announce_dir = Path(announce_dir) if announce_dir else None
        self.ttl_s = float(ttl_s)
        self._clock = clock  # injectable for clock-skew tests
        self._obs_lock = threading.Lock()
        #: announce name → (content fingerprint, arrival on self._clock).
        #: Arrival-time aging: liveness = "this file's content changed
        #: within ttl_s of OUR monotonic clock", never the sender's ts.
        self._seen: dict[str, tuple[str, float]] = {}  # guarded by: self._obs_lock

    @classmethod
    def from_config(cls, config: Iterable[dict[str, Any]] | str | Path,
                    **kwargs: Any) -> "HostRegistry":
        """Build from a list of ``{"name", "address", "port"}`` dicts or
        a JSON file holding one."""
        if isinstance(config, (str, Path)):
            config = json.loads(Path(config).read_text())
        return cls(
            [Host(c["name"], c.get("address", "127.0.0.1"), int(c["port"]))
             for c in config],
            **kwargs,
        )

    # -- membership ----------------------------------------------------------

    def add(self, host: Host) -> None:
        self._static[host.name] = host

    def remove(self, name: str) -> None:
        self._static.pop(name, None)

    def _announced(self) -> list[Host]:
        d = self._announce_dir
        if d is None or not d.is_dir():
            return []
        live: list[Host] = []
        present: set[str] = set()
        with self._obs_lock:
            now = self._clock()
            for p in sorted(d.glob("*.json")):
                try:
                    text = p.read_text()
                    rec = json.loads(text)
                    # The heartbeat re-stamps ts every announce, so the
                    # file CONTENT is the fingerprint: new content means
                    # the hostd is alive and beat recently. We age from
                    # when WE first saw that content — the sender's ts
                    # value itself is never compared against a clock.
                    prev = self._seen.get(p.name)
                    if prev is None or prev[0] != text:
                        self._seen[p.name] = (text, now)
                        arrival = now
                    else:
                        arrival = prev[1]
                    present.add(p.name)
                    if now - arrival > self.ttl_s:
                        continue  # stale: the hostd stopped heartbeating
                    live.append(
                        Host(rec["name"], rec["address"], int(rec["port"])))
                except (OSError, ValueError, KeyError, TypeError):
                    # A half-written or malformed record is skipped, not
                    # fatal: announces are atomic (write+rename) so this
                    # is only ever external corruption, and the next
                    # heartbeat repairs it.
                    log.warning("host registry: unreadable announce %s", p.name)
            # Retracted/removed announces must not pin observations: a
            # host that retracts and later re-announces the same bytes
            # would otherwise inherit its old arrival time.
            for name in list(self._seen):
                if name not in present:
                    del self._seen[name]
        return live

    def hosts(self) -> list[Host]:
        """All known hosts: static members plus live announces (an
        announce with a static member's name supersedes it — the
        announce carries the actual bound port)."""
        merged = dict(self._static)
        for h in self._announced():
            merged[h.name] = h
        return [merged[k] for k in sorted(merged)]

    def get(self, name: str) -> Host | None:
        for h in self.hosts():
            if h.name == name:
                return h
        return None

    # -- announce (written by hostd) ------------------------------------------

    @staticmethod
    def announce(announce_dir: str | Path, host: Host,
                 pid: int | None = None) -> None:
        """Atomically (re)stamp a hostd's announce record. Called by the
        hostd's heartbeat loop at a cadence well under ``ttl_s``."""
        d = Path(announce_dir)
        d.mkdir(parents=True, exist_ok=True)
        rec = {
            "name": host.name,
            "address": host.address,
            "port": host.port,
            "pid": pid if pid is not None else os.getpid(),
            "ts": time.time(),
        }
        tmp = d / f".{host.name}.json.tmp{os.getpid()}"
        tmp.write_text(json.dumps(rec))
        os.replace(tmp, d / f"{host.name}.json")

    @staticmethod
    def retract(announce_dir: str | Path, name: str) -> None:
        """Remove a hostd's announce on clean shutdown (a crash just
        ages out via ``ttl_s``)."""
        try:
            (Path(announce_dir) / f"{name}.json").unlink(missing_ok=True)
        except OSError:
            log.warning("host registry: could not retract announce for %s",
                        name)
