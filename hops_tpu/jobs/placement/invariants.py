"""Post-hoc invariant audit over placement flight events.

"At most one live unit per slot" is the whole point of the fencing
design — and a property no single process can assert at runtime,
because the violation IS two processes disagreeing. So it is audited
after the fact, from the flight recorder's event stream: the
:class:`~hops_tpu.jobs.placement.client.PlacementClient` records every
``generation`` mint/bump, hostd records every ``fence``, and the data
planes record every ``generation_rejected`` refusal. Those events are
totally ordered by the recorder's sequence number, which makes the
invariant checkable:

- a unit is **live** (authoritative for its slot) from its mint until
  a later mint/bump supersedes it — so "one live unit per slot at
  every instant" holds iff each slot's generation events are strictly
  increasing (two live units would require a mint that does NOT
  supersede the previous occupant);
- a generation can be minted at most once (a duplicate would be two
  units claiming the same identity);
- no unit may refuse its OWN token (``have == got`` in a
  ``generation_rejected`` event means the fencing check itself is
  broken).

A superseded unit still *running* — the zombie window between
re-placement and its fence/reap — is fine and expected: it is no
longer live in the invariant's sense, and the stamped-header check
refuses it at the data plane, which is exactly what the
``generation_rejected`` events document.

Chaos drills end with ``assert not audit_slot_invariant(events)``;
the bench's partition leg does the same. See docs/operations.md
"Partition tolerance & fencing".
"""

from __future__ import annotations

from typing import Any, Iterable

from hops_tpu.runtime import flight


def audit_slot_invariant(events: Iterable[dict[str, Any]]) -> list[str]:
    """Replay ``generation``/``generation_rejected`` flight events (in
    recorder order — pass ``FlightRecorder.events()`` output or a
    superset); returns human-readable violations, empty when the
    one-live-unit-per-slot invariant held at every instant."""
    violations: list[str] = []
    latest: dict[str, int] = {}
    minted: dict[tuple[str, int], int] = {}
    for e in events:
        kind = e.get("kind")
        data = e.get("data", {})
        slot = data.get("slot")
        if slot is None:
            continue
        seq = e.get("seq")
        if kind == "generation":
            action = data.get("action")
            if action not in ("mint", "bump"):
                continue
            try:
                gen = int(data.get("generation", 0))
            except (TypeError, ValueError):
                violations.append(
                    f"seq {seq}: slot {slot}: unparseable generation "
                    f"{data.get('generation')!r}")
                continue
            prev = latest.get(slot, 0)
            if gen <= prev:
                violations.append(
                    f"seq {seq}: slot {slot}: {action} of generation {gen} "
                    f"does not supersede {prev} — two live units")
            else:
                latest[slot] = gen
            if action == "mint":
                if (slot, gen) in minted:
                    violations.append(
                        f"seq {seq}: slot {slot}: generation {gen} minted "
                        f"twice (first at seq {minted[(slot, gen)]})")
                minted[(slot, gen)] = seq
        elif kind == "generation_rejected":
            have, got = data.get("have"), data.get("got")
            if have is not None and have == got:
                violations.append(
                    f"seq {seq}: slot {slot}: unit refused its OWN token "
                    f"{have!r} — fencing check broken")
    return violations


def audit(recorder: "flight.FlightRecorder | None" = None,
          after_seq: int = 0) -> list[str]:
    """Audit the process-wide flight recorder (or ``recorder``),
    optionally only events past ``after_seq`` — a drill snapshots
    ``FLIGHT.seq`` first so earlier tests' events stay out."""
    rec = recorder if recorder is not None else flight.FLIGHT
    return audit_slot_invariant(rec.events(after_seq=after_seq))
