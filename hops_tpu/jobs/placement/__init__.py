"""Multi-host placement: host agents, a placement client, shard servers.

Every distributed piece of the platform used to be single-host: fleet
replicas were local ``Popen`` children of the ``ReplicaManager``,
feature-store shards were local files, and the router only ever spoke
to ``127.0.0.1``. This package is the control plane that removes that
assumption — the TPU build's equivalent of the reference platform's
jobs service (PAPER.md L6, ``jobs-client/``):

- :mod:`~hops_tpu.jobs.placement.registry` — :class:`Host` +
  :class:`HostRegistry`: the set of machines placement may use, from a
  static list or a join-via-announce directory hostds heartbeat into.
- :mod:`~hops_tpu.jobs.placement.hostd` — the per-host agent: a stdlib
  HTTP daemon accepting spawn / drain / reap / kill / health verbs for
  the UNITS on its host (``serving_host --fleet-worker`` replicas and
  :mod:`~hops_tpu.jobs.placement.shardd` feature-shard servers).
- :mod:`~hops_tpu.jobs.placement.client` — :class:`PlacementClient`:
  what ``ReplicaManager`` (and through it the autoscaler and rollouts)
  drives instead of local ``Popen``. Per-host circuit breakers,
  deadlines on every RPC, and placement across the surviving hosts
  when one dies — the ``placement.rpc`` fault point makes partitions
  deterministically injectable.
- :mod:`~hops_tpu.jobs.placement.shardd` — one feature-store shard
  (``featurestore.online.OnlineStore``) behind HTTP, warm-startable
  from a PR 8 snapshot manifest, jax-free so it starts in milliseconds.
- :mod:`~hops_tpu.jobs.placement.lease` — :class:`Lease`: the TTL
  contract behind hostd's self-fencing (a host that cannot renew
  kills its own units before survivors re-place them).
- :mod:`~hops_tpu.jobs.placement.invariants` — the post-hoc audit
  proving "at most one live unit per slot" from flight events.

Data plane vs control plane: the placement client places units and
manages their lifecycle; request traffic (router forwards, shard
``multi_get`` fan-out) goes DIRECT to each unit's ``host:port`` — the
hostd is never on the hot path. Partition tolerance spans both: the
client mints ``(slot, generation)`` identity for every unit, data
planes refuse superseded generations, and the lease fences the host
side (docs/operations.md "Partition tolerance & fencing").

See docs/operations.md "Multi-host placement".
"""

from hops_tpu.jobs.placement.client import (
    GENERATION_HEADER,
    PlacedUnit,
    PlacementClient,
    PlacementError,
)
from hops_tpu.jobs.placement.hostd import Hostd
from hops_tpu.jobs.placement.invariants import audit_slot_invariant
from hops_tpu.jobs.placement.lease import Lease
from hops_tpu.jobs.placement.registry import Host, HostRegistry

__all__ = [
    "GENERATION_HEADER",
    "Host",
    "HostRegistry",
    "Hostd",
    "Lease",
    "PlacedUnit",
    "PlacementClient",
    "PlacementError",
    "audit_slot_invariant",
]
