"""PlacementClient — place and manage units across the host fleet.

The single-host ``ReplicaManager`` owned its workers with ``Popen``;
this client is the drop-in control plane that replaces that: every verb
is an HTTP RPC to the target host's :mod:`~hops_tpu.jobs.placement.
hostd` agent over the shared keep-alive
:class:`~hops_tpu.runtime.httpclient.HTTPPool`, and every RPC is

- **bounded**: ``with_deadline`` around the whole exchange (spawn gets
  its own, larger budget — a replica unit pays jax startup);
- **breaker-guarded per host**: a partitioned or dead host fails fast
  and stops being a placement candidate until its breaker half-opens;
- **injectable**: the ``placement.rpc`` fault point fires before each
  RPC, keyed by host name — chaos tests partition a single host
  deterministically.

Placement policy: least-placed healthy host first (ties broken by
name), with retry-on-next-host when a candidate fails — a host dying
mid-scale-up costs one breaker strike, not a failed spawn. That is
what "the autoscaler re-places on survivors" means mechanically: the
autoscaler just calls ``manager.spawn()``; this client routes it away
from the dead host.

This client also MINTS placement identity: every spawn fills a
``slot`` (caller-named or auto) at a fresh ``generation`` — a
monotonic per-slot counter this client owns. The cfg handed to the
unit carries both, forwarders stamp ``X-Hops-Generation:
<slot>:<current generation>`` on data-plane requests, and a unit whose
own token differs refuses with a typed 410. ``bump_generation`` is the
fencing verb: called BEFORE re-placing a lost unit, it supersedes the
old one so a zombie healing from a partition is rejected at the data
plane — "at most one live unit per slot", enforced, and audited post
hoc by :mod:`~hops_tpu.jobs.placement.invariants` from the
``generation``/``fence`` flight events recorded here.

Metrics (docs/operations.md "Multi-host placement"):
``hops_tpu_placement_rpc_total{host,verb,outcome}``,
``hops_tpu_placement_rpc_seconds{verb}``,
``hops_tpu_placement_hosts{state}``,
``hops_tpu_placement_units{host,kind}``.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
from typing import Any

from hops_tpu.jobs.placement.registry import Host, HostRegistry
from hops_tpu.runtime import faultinject, flight
from hops_tpu.runtime.httpclient import HTTPPool
from hops_tpu.runtime.logging import get_logger
from hops_tpu.runtime.resilience import CircuitBreaker, with_deadline
from hops_tpu.telemetry.metrics import REGISTRY

log = get_logger(__name__)

_m_rpc = REGISTRY.counter(
    "hops_tpu_placement_rpc_total",
    "Placement control-plane RPCs by host, verb and outcome "
    "(ok | error | rejected)",
    labels=("host", "verb", "outcome"),
)
_m_rpc_seconds = REGISTRY.histogram(
    "hops_tpu_placement_rpc_seconds",
    "Placement control-plane RPC latency per verb",
    labels=("verb",),
)
_m_hosts = REGISTRY.gauge(
    "hops_tpu_placement_hosts",
    "Registry hosts by health as the placement client sees them "
    "(healthy = breaker admits traffic, ejected = breaker open)",
    labels=("state",),
)
_m_units = REGISTRY.gauge(
    "hops_tpu_placement_units",
    "Units this placement client has placed, per host and kind",
    labels=("host", "kind"),
)


class PlacementError(RuntimeError):
    """A placement verb failed (host unreachable, agent error, or no
    healthy host left to place on)."""


#: The wire header carrying the placement identity a forward was
#: routed under (see module docs): ``X-Hops-Generation: <slot>:<gen>``.
GENERATION_HEADER = "X-Hops-Generation"


@dataclasses.dataclass
class PlacedUnit:
    """Handle to one unit placed on some host: the manager's record of
    where its worker lives, and the argument to every lifecycle verb.
    ``slot``/``generation`` are the identity MINTED for this unit; the
    slot's *current* generation lives in the client
    (:meth:`PlacementClient.current_generation`) and moves past this
    snapshot when the unit is superseded."""

    host: Host
    uid: str
    kind: str
    port: int
    pid: int | None = None
    slot: str | None = None
    generation: int = 0

    @property
    def address(self) -> str:
        return self.host.address


class PlacementClient:
    """Control-plane client over a :class:`HostRegistry` (see module
    docs). Thread-safe: the router's manager, the autoscaler and a
    rollout all drive one client."""

    def __init__(
        self,
        registry: HostRegistry,
        *,
        rpc_timeout_s: float = 5.0,
        spawn_timeout_s: float = 90.0,
        breaker_failures: int = 3,
        breaker_reset_s: float = 5.0,
        pool: HTTPPool | None = None,
    ):
        self.registry = registry
        self.rpc_timeout_s = rpc_timeout_s
        self.spawn_timeout_s = spawn_timeout_s
        self._breaker_failures = breaker_failures
        self._breaker_reset_s = breaker_reset_s
        self._pool = pool if pool is not None else HTTPPool(identity="placement")
        self._lock = threading.Lock()
        self._breakers: dict[str, CircuitBreaker] = {}  # guarded by: self._lock
        self._placed: dict[str, int] = {}  # per-host unit count, guarded by: self._lock
        self._generations: dict[str, int] = {}  # slot → current gen, guarded by: self._lock
        self._slot_seq = 0  # auto-slot counter, guarded by: self._lock

    # -- host view ------------------------------------------------------------

    def _breaker(self, host: Host) -> CircuitBreaker:
        with self._lock:
            br = self._breakers.get(host.name)
            if br is None:
                br = self._breakers[host.name] = CircuitBreaker(
                    name=f"placement-{host.name}",
                    failure_threshold=self._breaker_failures,
                    reset_timeout_s=self._breaker_reset_s,
                )
            return br

    def hosts(self) -> list[Host]:
        return self.registry.hosts()

    def healthy_hosts(self) -> list[Host]:
        """Hosts whose breaker currently admits traffic (this CONSUMES
        a half-open probe slot for an open breaker — exactly one caller
        gets to try the maybe-healed host)."""
        healthy = [h for h in self.hosts() if self._breaker(h).allow()]
        self._publish_host_gauges()
        return healthy

    def _publish_host_gauges(self) -> None:
        hosts = self.hosts()
        ejected = sum(
            1 for h in hosts if self._breaker(h).state == "open")
        _m_hosts.set(len(hosts) - ejected, state="healthy")
        _m_hosts.set(ejected, state="ejected")

    def probe(self, host: Host) -> bool:
        """One bounded ``/healthz`` probe; feeds the host's breaker."""
        try:
            self._rpc(host, "health", "GET", "/healthz")
            return True
        except PlacementError:
            return False

    def units(self, host: Host) -> list[dict[str, Any]]:
        return self._rpc(host, "units", "GET", "/units").get("units", [])

    # -- the RPC --------------------------------------------------------------

    def _rpc(
        self,
        host: Host,
        verb: str,
        method: str,
        path: str,
        body: dict[str, Any] | None = None,
        *,
        timeout_s: float | None = None,
    ) -> dict[str, Any]:
        budget = timeout_s if timeout_s is not None else self.rpc_timeout_s
        breaker = self._breaker(host)
        if not breaker.allow():
            _m_rpc.inc(host=host.name, verb=verb, outcome="rejected")
            raise PlacementError(
                f"host {host.name} ejected (breaker open, retry in "
                f"{breaker.retry_after_s():.1f}s)")
        data = json.dumps(body or {}).encode() if method == "POST" else None
        try:
            # Chaos point: a partition to ONE host is a keyed
            # error/latency spec here — the breaker and the
            # retry-on-next-host policy are what absorb it.
            faultinject.fire("placement.rpc", key=host.name)
            t0 = time.perf_counter()

            def _exchange():
                return self._pool.request(
                    method, f"{host.endpoint}{path}", data,
                    {"Content-Type": "application/json"} if data else None,
                    timeout_s=budget)

            status, payload, _ = with_deadline(
                _exchange, budget * 1.25, op=f"placement.{verb}")
            _m_rpc_seconds.observe(time.perf_counter() - t0, verb=verb)
        except (OSError, TimeoutError) as e:
            breaker.record_failure()
            _m_rpc.inc(host=host.name, verb=verb, outcome="error")
            self._publish_host_gauges()
            raise PlacementError(
                f"placement {verb} to {host.name} ({host.key}) failed: "
                f"{type(e).__name__}: {e}") from e
        try:
            parsed = json.loads(payload) if payload else {}
        except ValueError:
            parsed = {"error": payload[:200].decode(errors="replace")}
        if status >= 500:
            breaker.record_failure()
            _m_rpc.inc(host=host.name, verb=verb, outcome="error")
            self._publish_host_gauges()
            raise PlacementError(
                f"placement {verb} on {host.name} failed: "
                f"{parsed.get('error', status)}")
        breaker.record_success()
        _m_rpc.inc(host=host.name, verb=verb, outcome="ok")
        if status >= 400:
            raise PlacementError(
                f"placement {verb} on {host.name} rejected ({status}): "
                f"{parsed.get('error')}")
        return parsed

    # -- placement verbs ------------------------------------------------------

    def _candidates(self, prefer: str | None) -> list[Host]:
        with self._lock:
            placed = dict(self._placed)
        hosts = sorted(
            self.healthy_hosts(),
            key=lambda h: (placed.get(h.name, 0), h.name))
        if prefer is not None:
            hosts.sort(key=lambda h: h.name != prefer)
        return hosts

    def spawn(self, kind: str, cfg: dict[str, Any], *,
              prefer: str | None = None,
              slot: str | None = None) -> PlacedUnit:
        """Place one unit on the least-placed healthy host, retrying the
        next candidate when a host fails — the caller sees one spawn,
        however many hosts died under it. The unit fills ``slot``
        (auto-minted when None; pass the old slot to RE-place) at a
        freshly minted generation, both injected into its cfg."""
        with self._lock:
            if slot is None:
                self._slot_seq += 1
                slot = f"{kind}-{self._slot_seq}"
            gen = self._generations.get(slot, 0) + 1
            self._generations[slot] = gen
        cfg = dict(cfg)
        cfg["slot"], cfg["generation"] = slot, gen
        errors: list[str] = []
        for host in self._candidates(prefer):
            try:
                rec = self._rpc(
                    host, "spawn", "POST", "/units/spawn",
                    {"kind": kind, "cfg": cfg},
                    timeout_s=self.spawn_timeout_s)
            except PlacementError as e:
                errors.append(str(e))
                log.warning("placement: spawn of %s failed on %s, trying "
                            "next host: %s", kind, host.name, e)
                continue
            unit = PlacedUnit(host=host, uid=rec["uid"], kind=kind,
                              port=int(rec["port"]), pid=rec.get("pid"),
                              slot=slot, generation=gen)
            flight.record("generation", action="mint", slot=slot,
                          generation=gen, unit_kind=kind, host=host.name,
                          uid=unit.uid)
            with self._lock:
                self._placed[host.name] = self._placed.get(host.name, 0) + 1
            _m_units.set(self._placed_count(host.name, kind),
                         host=host.name, kind=kind)
            return unit
        raise PlacementError(
            "no healthy host could place a "
            f"{kind} unit: {'; '.join(errors) or 'registry is empty'}")

    # -- generations (fencing tokens) -----------------------------------------

    def bump_generation(self, slot: str) -> int:
        """Supersede ``slot``'s current occupant BEFORE re-placing it:
        any unit still holding an older generation — a zombie healing
        from a partition — is now refused at the data plane (typed 410
        against the stamped header) and reaped by ``reconcile()``."""
        with self._lock:
            gen = self._generations.get(slot, 0) + 1
            self._generations[slot] = gen
        flight.record("generation", action="bump", slot=slot, generation=gen)
        log.warning("placement: slot %s bumped to generation %d "
                    "(previous occupant superseded)", slot, gen)
        return gen

    def current_generation(self, slot: str) -> int:
        with self._lock:
            return self._generations.get(slot, 0)

    def generation_header(self, unit: PlacedUnit) -> dict[str, str]:
        """Headers stamping ``unit``'s slot at its CURRENT generation
        (empty when the unit carries no identity). Deliberately the
        live counter, not the unit's snapshot: a stale routing view
        aiming at a superseded unit must present the newer token so
        the zombie rejects it."""
        if unit is None or unit.slot is None:
            return {}
        return {GENERATION_HEADER:
                f"{unit.slot}:{self.current_generation(unit.slot)}"}

    def _placed_count(self, host_name: str, kind: str) -> int:
        # The gauge tracks per-(host, kind); the balance counter is
        # per-host only — re-derive the labelled value from the agent
        # would cost an RPC, so approximate with the host total.
        with self._lock:
            return self._placed.get(host_name, 0)

    def _unit_verb(self, unit: PlacedUnit, verb: str) -> dict[str, Any]:
        out = self._rpc(unit.host, verb, "POST",
                        f"/units/{unit.uid}/{verb}")
        if verb in ("reap", "kill"):
            with self._lock:
                n = self._placed.get(unit.host.name, 0)
                self._placed[unit.host.name] = max(0, n - 1)
            _m_units.set(self._placed_count(unit.host.name, unit.kind),
                         host=unit.host.name, kind=unit.kind)
        return out

    def drain(self, unit: PlacedUnit) -> dict[str, Any]:
        return self._unit_verb(unit, "drain")

    def reap(self, unit: PlacedUnit) -> dict[str, Any]:
        return self._unit_verb(unit, "reap")

    def kill(self, unit: PlacedUnit) -> dict[str, Any]:
        """Chaos verb: SIGKILL the unit's worker, no drain."""
        return self._unit_verb(unit, "kill")

    def close(self) -> None:
        self._pool.close()
