"""The lease: hostd's suicide pact with the registry.

A TTL alone cannot make partitions safe — when the registry ages a
host out, the host itself has no idea it is gone and keeps serving:
its replicas answer with a stale model, its feature shards accept
writes, while the autoscaler re-places the "lost" capacity on
survivors. Split-brain, by construction. The classic fix (Gray &
Cheriton's leases, and every fencing design since) is to make the TTL
a **contract held by both sides**:

- the registry promises to keep the host in membership for ``ttl_s``
  after each observed heartbeat (receiver-side monotonic arrival
  aging — see :mod:`~hops_tpu.jobs.placement.registry`);
- the host promises that if it cannot RENEW within that same window,
  it stops serving on its own: hostd drains and kills every unit it
  runs (``Hostd.self_fence``). A host that cannot reach the registry
  must assume the registry has already given it up.

Both sides measure on clocks that only move forward: the lease runs on
``time.monotonic()`` (injectable for tests), so an NTP step — forward
or back — can neither fire a spurious fence nor hold one open. The
registry's side ages by arrival time for the same reason. Sender wall
clocks are display metadata everywhere.

For the fence to be safe the lease TTL must be **at least** the
registry TTL (hostd defaults to ``3 × heartbeat_s``, the registry
default is looser): membership must lapse before or with the fence,
never after, or survivors would route to a host that has already
killed its units. The reverse gap — registry ages the host out while
its lease still has time left — is the zombie window; the generation
tokens minted by the placement client close it at the data plane
(docs/operations.md "Partition tolerance & fencing").

Metrics (docs/operations.md "Partition tolerance & fencing"):
``hops_tpu_placement_lease_renewals_total{host,outcome}`` counts
renewal attempts (``ok`` / ``error``);
``hops_tpu_placement_lease_fenced_total{host}`` counts self-fences.
"""

from __future__ import annotations

import threading
import time
from typing import Callable

from hops_tpu.runtime.logging import get_logger
from hops_tpu.telemetry.metrics import REGISTRY

log = get_logger(__name__)

_m_renewals = REGISTRY.counter(
    "hops_tpu_placement_lease_renewals_total",
    "Lease renewal attempts by the hostd heartbeat, per outcome",
    labels=("host", "outcome"),
)
_m_fenced = REGISTRY.counter(
    "hops_tpu_placement_lease_fenced_total",
    "Self-fences: a hostd killed its own units after its lease expired",
    labels=("host",),
)


class Lease:
    """One host's renewable TTL grant, measured on a monotonic clock.

    Starts renewed (construction IS the first grant — hostd announces
    before the heartbeat thread exists). ``renew()`` on every
    successful announce; ``expired()`` once ``ttl_s`` passes without
    one; ``mark_fenced()`` latches the fence decision exactly once per
    expiry episode so the heartbeat loop fences once, not every tick,
    and un-latches on the renewal that follows a heal."""

    def __init__(self, owner: str, ttl_s: float, *,
                 clock: Callable[[], float] = time.monotonic):
        if ttl_s <= 0:
            raise ValueError(f"lease ttl must be positive, got {ttl_s}")
        self.owner = owner
        self.ttl_s = float(ttl_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._renewed_at = clock()  # guarded by: self._lock
        self._fenced = False  # guarded by: self._lock

    def renew(self) -> None:
        """A successful heartbeat announce: restart the TTL window and
        clear any fence latch (the host has rejoined; its units were
        already killed at fence time, so rejoining is split-brain-safe)."""
        with self._lock:
            was_fenced = self._fenced
            self._renewed_at = self._clock()
            self._fenced = False
        _m_renewals.inc(host=self.owner, outcome="ok")
        if was_fenced:
            log.warning("lease %s: renewed after fence — host rejoins empty",
                        self.owner)

    def renewal_failed(self) -> None:
        """Account one failed announce (the TTL keeps running)."""
        _m_renewals.inc(host=self.owner, outcome="error")

    def remaining_s(self) -> float:
        """Seconds of grant left (negative once expired)."""
        with self._lock:
            return self.ttl_s - (self._clock() - self._renewed_at)

    def expired(self) -> bool:
        return self.remaining_s() <= 0.0

    def mark_fenced(self) -> bool:
        """Latch the fence decision; True exactly once per expiry
        episode (callers fence iff this returns True)."""
        with self._lock:
            if self._fenced:
                return False
            self._fenced = True
        _m_fenced.inc(host=self.owner)
        return True

    @property
    def fenced(self) -> bool:
        with self._lock:
            return self._fenced
