"""hostd — the per-host placement agent.

One hostd runs on every machine the platform may place work on. It is
the only thing the :class:`~hops_tpu.jobs.placement.client.
PlacementClient` talks to: an event-loop HTTP daemon (one
:class:`~hops_tpu.runtime.httpserver.HTTPServer`) that spawns, drains,
reaps and health-checks the UNITS on its host —

- ``replica`` units: one ``serving._RunningServing`` each, hosted
  either as a detached ``serving_host --fleet-worker`` process (the
  production shape — same worker, same ``cfg.json``/``state.json``
  announce protocol the local ``ReplicaManager`` used) or as an
  in-process server thread (``inprocess_units=True`` — the fast tier
  for tests and benches, since a process replica pays jax startup);
- ``shard`` units: one :class:`~hops_tpu.jobs.placement.shardd.
  ShardServer` each (process or thread) — jax-free, so even the
  process shape starts in milliseconds.

Verbs (JSON in, JSON out; unit states mirror the fleet's
``starting -> ready -> draining -> stopped`` machine)::

    GET  /healthz                   {"status": "ok", "host", "units"}
    GET  /units                     {"units": [ {uid, kind, port, pid,
                                                 state}, ... ]}
    POST /units/spawn               {"kind": "replica"|"shard",
                                     "cfg": {...}}  -> unit record
    POST /units/<uid>/drain         replica: forwards /admin/drain
    POST /units/<uid>/reap          graceful stop (SIGTERM, then KILL)
    POST /units/<uid>/kill          chaos verb: SIGKILL, no drain

Process units are spawned in the hostd's OWN process group (no
``start_new_session``): when the host dies — in the chaos drill,
``SIGKILL`` to the group — its units die with it, exactly like a real
machine failure takes everything on the machine.

Join-via-announce: given ``announce_dir``, the hostd heartbeats its
:class:`~hops_tpu.jobs.placement.registry.Host` record every
``heartbeat_s`` so registries list it while it lives and age it out
when it stops.

Each heartbeat announce renews a :class:`~hops_tpu.jobs.placement.
lease.Lease` (TTL ``lease_ttl_s``, default ``3 × heartbeat_s``). When
renewals keep failing past the TTL — the host is partitioned from the
registry — the hostd honors the suicide pact: :meth:`Hostd.self_fence`
drains and kills every unit it runs, so a cut-off host can never keep
serving a placement the survivors have re-placed. The agent itself
stays up and keeps trying to renew; after the partition heals it
rejoins empty. Announces pass the ``transport.send`` fault point
(destination ``registry``), and the hostd registers its agent port and
every unit port under its host name via ``faultinject.name_endpoint``
— one ``cut("h1")`` severs the whole host, agent and units alike.

See docs/operations.md "Multi-host placement" and "Partition
tolerance & fencing".
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.request
from pathlib import Path
from typing import Any

from hops_tpu.jobs.placement.lease import Lease
from hops_tpu.jobs.placement.registry import Host, HostRegistry
from hops_tpu.runtime import faultinject, flight
from hops_tpu.runtime.httpserver import HTTPServer
from hops_tpu.runtime.logging import get_logger

log = get_logger(__name__)

UNIT_KINDS = ("replica", "shard")


class _Unit:
    """One placed worker on this host."""

    def __init__(self, uid: str, kind: str, *, slot: str | None = None,
                 generation: int = 0):
        self.uid = uid
        self.kind = kind
        self.state = "starting"
        self.port: int | None = None
        self.proc: subprocess.Popen | None = None
        self.server: Any = None  # in-process _RunningServing / ShardServer
        self.dir: Path | None = None
        # Placement identity (minted by PlacementClient, carried in
        # cfg): which slot this unit fills and at which generation —
        # the fence/audit trail's ground truth.
        self.slot = slot
        self.generation = generation

    @property
    def pid(self) -> int | None:
        return self.proc.pid if self.proc is not None else None

    def record(self) -> dict[str, Any]:
        return {"uid": self.uid, "kind": self.kind, "state": self.state,
                "port": self.port, "pid": self.pid, "slot": self.slot,
                "generation": self.generation}


class Hostd:
    """The agent (see module docs). ``port=0`` binds an ephemeral port;
    ``unit_root`` is where process units keep their ``cfg.json`` /
    ``state.json`` / logs (a temp dir per test, a data dir in prod)."""

    def __init__(
        self,
        name: str,
        *,
        port: int = 0,
        bind: str = "127.0.0.1",
        inprocess_units: bool = False,
        unit_root: str | Path | None = None,
        announce_dir: str | Path | None = None,
        heartbeat_s: float = 3.0,
        lease_ttl_s: float | None = None,
        spawn_timeout_s: float = 60.0,
    ):
        self.name = name
        self.inprocess_units = inprocess_units
        self.spawn_timeout_s = spawn_timeout_s
        self._unit_root = Path(unit_root) if unit_root else None
        self._lock = threading.Lock()
        self._units: dict[str, _Unit] = {}  # guarded by: self._lock
        self._counter = 0  # guarded by: self._lock
        self._server = _make_server(self, bind, port)
        self.port = self._server.port
        self.address = bind
        faultinject.name_endpoint(f"{bind}:{self.port}", name)
        self._announce_dir = Path(announce_dir) if announce_dir else None
        self._hb_stop = threading.Event()
        self._hb_thread: threading.Thread | None = None
        self.lease: Lease | None = None
        if self._announce_dir is not None:
            # Construction is the first renewal: announce before the
            # heartbeat thread exists, lease granted from "now".
            HostRegistry.announce(self._announce_dir, self.host())
            self.lease = Lease(
                name,
                lease_ttl_s if lease_ttl_s is not None else 3.0 * heartbeat_s)
            self._hb_thread = threading.Thread(
                target=self._heartbeat, args=(heartbeat_s,),
                name=f"hostd-{name}-hb", daemon=True)
            self._hb_thread.start()
        log.info("hostd %s up on %s:%d (units=%s)", name, bind, self.port,
                 "inprocess" if inprocess_units else "process")

    def host(self) -> Host:
        return Host(self.name, self.address, self.port)

    def _heartbeat(self, interval_s: float) -> None:
        while not self._hb_stop.wait(interval_s):
            self._renew_lease()

    def _renew_lease(self) -> None:
        """One heartbeat: announce (= renew), or fence once the lease
        has run out. The announce passes the ``transport.send`` fault
        point as this host → ``registry``, so a partition cut on this
        host's egress starves the lease exactly like a real cut."""
        try:
            faultinject.fire_transport(self.name, "registry")
            HostRegistry.announce(self._announce_dir, self.host())
        except OSError as e:
            self.lease.renewal_failed()
            log.warning(
                "hostd %s: lease renewal failed (%s: %s); %.1fs of lease left",
                self.name, type(e).__name__, e,
                max(self.lease.remaining_s(), 0.0))
        else:
            self.lease.renew()
        if self.lease.expired() and self.lease.mark_fenced():
            self.self_fence(
                f"lease expired: no successful renewal in "
                f"{self.lease.ttl_s:.1f}s")

    def self_fence(self, reason: str) -> None:
        """The suicide-pact half of the lease contract: this host has
        been unable to renew for a full TTL, so the registry (and
        everything placing against it) has already given it up and may
        be re-placing its units on survivors. Drain and kill every
        unit NOW — a partitioned host must never keep serving. The
        agent stays up; after the partition heals the next successful
        renewal rejoins the (now empty) host."""
        units = self.units()
        flight.record("fence", host=self.name, reason=reason,
                      units=[u.record() for u in units])
        log.error("hostd %s: SELF-FENCE (%s) — draining and killing %d "
                  "unit(s)", self.name, reason, len(units))
        for unit in units:
            try:
                self.drain(unit.uid)
            except Exception as e:  # noqa: BLE001 — best-effort drain;
                # the reap below is the guarantee
                log.warning("hostd %s: fence drain of %s failed: %s",
                            self.name, unit.uid, e)
            try:
                self.reap(unit.uid)
            except Exception as e:  # noqa: BLE001 — keep fencing the rest
                log.warning("hostd %s: fence reap of %s failed: %s",
                            self.name, unit.uid, e)

    # -- unit bookkeeping -----------------------------------------------------

    def units(self) -> list[_Unit]:
        with self._lock:
            return list(self._units.values())

    def _get(self, uid: str) -> _Unit | None:
        with self._lock:
            return self._units.get(uid)

    def _unit_dir(self, unit: _Unit) -> Path:
        root = self._unit_root
        if root is None:
            from hops_tpu.runtime import fs

            root = Path(fs.project_path("Serving")) / f"{self.name}.hostd"
        d = root / unit.uid
        d.mkdir(parents=True, exist_ok=True)
        return d

    # -- spawn ----------------------------------------------------------------

    def spawn(self, kind: str, cfg: dict[str, Any]) -> dict[str, Any]:
        if kind not in UNIT_KINDS:
            raise ValueError(f"unknown unit kind {kind!r} (expect one of "
                             f"{UNIT_KINDS})")
        with self._lock:
            uid = f"u{self._counter}"
            self._counter += 1
            unit = _Unit(uid, kind, slot=cfg.get("slot"),
                         generation=int(cfg.get("generation", 0)))
            self._units[uid] = unit
        try:
            if self.inprocess_units:
                self._spawn_inprocess(unit, cfg)
            else:
                self._spawn_process(unit, cfg)
            unit.state = "ready" if kind == "shard" else unit.state
            if kind == "replica":
                # The worker announced its port; readiness (the
                # /healthz gate) is the ReplicaManager's job — it owns
                # the replica state machine end to end.
                unit.state = "ready"
        except Exception:
            self._teardown(unit)
            unit.state = "failed"
            with self._lock:
                self._units.pop(unit.uid, None)
            raise
        if unit.port is not None:
            # Partition keying: the unit belongs to this host, so a
            # cut of the host name black-holes its data plane too.
            faultinject.name_endpoint(f"{self.address}:{unit.port}", self.name)
        log.info("hostd %s: unit %s (%s) up on port %s", self.name, uid,
                 kind, unit.port)
        return unit.record()

    def _spawn_inprocess(self, unit: _Unit, cfg: dict[str, Any]) -> None:
        if unit.kind == "shard":
            from hops_tpu.jobs.placement.shardd import ShardServer

            unit.server = ShardServer(cfg)
        else:
            # Lazy: importing serving pulls jax — a shard-only hostd
            # (or the shardd CLI) must never pay that.
            from hops_tpu.modelrepo import serving

            unit.server = serving._RunningServing(cfg)
        unit.port = unit.server.port

    def _spawn_process(self, unit: _Unit, cfg: dict[str, Any]) -> None:
        udir = self._unit_dir(unit)
        unit.dir = udir
        (udir / "state.json").unlink(missing_ok=True)
        (udir / "cfg.json").write_text(json.dumps(cfg, indent=2, default=str))
        from hops_tpu.jobs.api import _child_pythonpath
        from hops_tpu.runtime import fs

        env = dict(os.environ)
        env["HOPS_TPU_WORKSPACE"] = str(fs.workspace_root())
        env["HOPS_TPU_PROJECT"] = fs.project_name()
        env["PYTHONPATH"] = _child_pythonpath(env.get("PYTHONPATH"))
        mod = ("hops_tpu.modelrepo.serving_host" if unit.kind == "replica"
               else "hops_tpu.jobs.placement.shardd")
        argv = [sys.executable, "-m", mod]
        argv += (["--fleet-worker", str(udir)] if unit.kind == "replica"
                 else [str(udir)])
        with open(udir / "worker.log", "a") as logfile:
            # SAME process group as the hostd (no start_new_session):
            # a dead host takes its units with it — the machine-failure
            # semantics the chaos drill SIGKILLs for.
            unit.proc = subprocess.Popen(
                argv, stdout=logfile, stderr=subprocess.STDOUT, env=env)
        deadline = time.monotonic() + self.spawn_timeout_s
        state_file = udir / "state.json"
        while time.monotonic() < deadline:
            if state_file.exists():
                state = json.loads(state_file.read_text())
                if state.get("pid") == unit.proc.pid:
                    unit.port = state["port"]
                    return
            if unit.proc.poll() is not None:
                tail = (udir / "worker.log").read_text()[-2000:]
                raise RuntimeError(
                    f"unit {unit.uid} worker exited "
                    f"rc={unit.proc.returncode}; log tail:\n{tail}")
            time.sleep(0.05)
        unit.proc.kill()
        raise RuntimeError(
            f"unit {unit.uid} did not announce a port within "
            f"{self.spawn_timeout_s}s")

    # -- drain / reap / kill --------------------------------------------------

    def drain(self, uid: str) -> dict[str, Any]:
        unit = self._get(uid)
        if unit is None:
            raise KeyError(uid)
        if unit.kind == "replica" and unit.port is not None:
            if unit.server is not None:
                unit.server.drain()
            else:
                req = urllib.request.Request(
                    f"http://127.0.0.1:{unit.port}/admin/drain", data=b"{}",
                    headers={"Content-Type": "application/json"})
                try:
                    with urllib.request.urlopen(req, timeout=2.0):
                        pass
                except OSError:
                    log.warning("hostd %s: unit %s unreachable for drain "
                                "(already dead?)", self.name, uid)
        unit.state = "draining"
        return unit.record()

    def _teardown(self, unit: _Unit, *, grace_s: float = 5.0) -> None:
        if unit.server is not None:
            unit.server.stop()
            unit.server = None
        if unit.proc is not None and unit.proc.poll() is None:
            unit.proc.terminate()
            try:
                unit.proc.wait(timeout=grace_s)
            except subprocess.TimeoutExpired:
                unit.proc.kill()
                unit.proc.wait(timeout=grace_s)

    def reap(self, uid: str) -> dict[str, Any]:
        unit = self._get(uid)
        if unit is None:
            return {"uid": uid, "state": "stopped"}
        self._teardown(unit)
        unit.state = "stopped"
        with self._lock:
            self._units.pop(uid, None)
        log.info("hostd %s: unit %s reaped", self.name, uid)
        return unit.record()

    def kill(self, uid: str) -> dict[str, Any]:
        """Chaos verb: SIGKILL / abrupt stop, no drain."""
        unit = self._get(uid)
        if unit is None:
            return {"uid": uid, "state": "stopped"}
        if unit.proc is not None and unit.proc.poll() is None:
            os.kill(unit.proc.pid, signal.SIGKILL)
            unit.proc.wait(timeout=10)
        if unit.server is not None:
            unit.server.stop()
            unit.server = None
        unit.state = "stopped"
        with self._lock:
            self._units.pop(uid, None)
        log.warning("hostd %s: unit %s KILLED (chaos)", self.name, uid)
        return unit.record()

    # -- verb dispatch (the HTTP surface) -------------------------------------

    def handle(self, method: str, path: str, body: dict) -> tuple[int, dict]:
        if method == "GET" and path == "/healthz":
            return 200, {"status": "ok", "host": self.name,
                         "units": len(self.units()),
                         "fenced": bool(self.lease is not None
                                        and self.lease.fenced)}
        if method == "GET" and path == "/units":
            return 200, {"units": [u.record() for u in self.units()]}
        if method == "POST" and path == "/units/spawn":
            try:
                return 200, self.spawn(body["kind"], body["cfg"])
            except ValueError as e:
                return 400, {"error": str(e)}
            except Exception as e:  # noqa: BLE001 — spawn failure is the
                # client's retry-on-next-host signal, not a daemon crash
                return 500, {"error": f"{type(e).__name__}: {e}"}
        if method == "POST" and path.startswith("/units/"):
            parts = path.strip("/").split("/")
            if len(parts) == 3 and parts[2] in ("drain", "reap", "kill"):
                uid, verb = parts[1], parts[2]
                try:
                    return 200, getattr(self, verb)(uid)
                except KeyError:
                    return 404, {"error": f"no such unit: {uid}"}
        return 404, {"error": f"no such verb: {method} {path}"}

    # -- lifecycle ------------------------------------------------------------

    def stop(self) -> None:
        """Clean shutdown: reap every unit, retract the announce."""
        self._hb_stop.set()
        for unit in self.units():
            self.reap(unit.uid)
        self._server.stop()
        if self._announce_dir is not None:
            HostRegistry.retract(self._announce_dir, self.name)

    def chaos_kill(self) -> None:
        """Die like a machine: the agent stops answering and every unit
        dies with it — no drains, no reaps, no announce retraction (the
        record ages out, exactly like a crashed host's would)."""
        self._hb_stop.set()
        for unit in self.units():
            if unit.proc is not None and unit.proc.poll() is None:
                os.kill(unit.proc.pid, signal.SIGKILL)
                unit.proc.wait(timeout=10)
            if unit.server is not None:
                unit.server.stop()
                unit.server = None
            unit.state = "stopped"
        self._server.stop()
        log.warning("hostd %s: CHAOS-KILLED with %d units", self.name,
                    len(self.units()))


def _make_server(hostd: Hostd, bind: str, port: int) -> HTTPServer:
    def route(method, path, headers, body):
        try:
            # The agent-side half of the partition fault point: a
            # chaos spec keyed by this host's name stalls/errors the
            # verb INSIDE the agent, after transport succeeded.
            faultinject.fire("placement.rpc", key=hostd.name)
            payload = json.loads(body or b"{}") if method == "POST" else {}
            status, out = hostd.handle(method, path, payload)
        except Exception as e:  # noqa: BLE001 — agent stays up; the
            # error is the client's breaker food
            log.warning("hostd %s: %s %s failed: %s: %s", hostd.name,
                        method, path, type(e).__name__, e)
            status, out = 500, {"error": f"{type(e).__name__}: {e}"}
        data = json.dumps(out, default=str).encode()
        return status, {"Content-Type": "application/json"}, data

    return HTTPServer(route, bind=bind, port=port,
                      name=f"hostd-{hostd.name}", workers=8)


def main(argv: list[str] | None = None) -> None:
    """``python -m hops_tpu.jobs.placement.hostd --name h0 [...]`` —
    run one agent until terminated (the ``serving_host`` process
    model: signals blocked before server threads exist, sigwait)."""
    parser = argparse.ArgumentParser(
        prog="python -m hops_tpu.jobs.placement.hostd",
        description=__doc__.split("\n")[0],
    )
    parser.add_argument("--name", required=True, help="host name")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--bind", default="127.0.0.1")
    parser.add_argument("--announce", default=None,
                        help="registry announce directory (join mode)")
    parser.add_argument("--heartbeat", type=float, default=3.0,
                        help="announce/lease-renewal cadence, seconds")
    parser.add_argument("--lease-ttl", type=float, default=None,
                        help="self-fence after this long without a "
                             "successful renewal (default 3x heartbeat)")
    parser.add_argument("--unit-root", default=None)
    parser.add_argument("--inprocess-units", action="store_true")
    args = parser.parse_args(argv)

    sigs = {signal.SIGTERM, signal.SIGINT}
    signal.pthread_sigmask(signal.SIG_BLOCK, sigs)

    hostd = Hostd(
        args.name, port=args.port, bind=args.bind,
        inprocess_units=args.inprocess_units,
        unit_root=args.unit_root, announce_dir=args.announce,
        heartbeat_s=args.heartbeat, lease_ttl_s=args.lease_ttl,
    )
    print(json.dumps({"name": hostd.name, "port": hostd.port,
                      "pid": os.getpid()}), flush=True)
    signal.sigwait(sigs)
    hostd.stop()
    os._exit(0)


if __name__ == "__main__":
    main()
