"""shardd — one feature-store shard behind HTTP.

``ShardedOnlineStore`` keys rows by ``crc32(primary key) % N`` and, in
the single-host build, opens all N ``OnlineStore`` shard files locally.
Placed mode keeps the client exactly as it is — routing, per-shard
breakers, parallel fan-out, straggler hedging — and swaps each local
shard for a remote one: an instance of this server, placed on some host
by the :mod:`~hops_tpu.jobs.placement.hostd` agent.

Deliberately **jax-free** (the import chain stops at
``featurestore.online``): a shard server is a lookup daemon, and paying
a multi-second jax initialization per shard would dominate every
placement and chaos-heal latency. That is also why this is its own
process model rather than a ``serving_host`` mode.

Verbs (JSON in, JSON out by default, HTTP/1.1 keep-alive for the
pool). ``/healthz`` doubles as the codec handshake: it advertises
``"codecs"`` and a client that sees ``"packed"`` there may send
``Accept: application/x-hops-packed`` on ``/get_many`` to receive the
row batch as a packed columnar frame (``runtime/wirecodec.py``) instead
of JSON — per shard, falling back to JSON whenever the batch cannot be
packed. A ``"codecs": ["json"]`` config entry pins a shard JSON-only
(mixed fleets are a supported state, e.g. mid-rollout)::

    GET  /healthz            {"status": "ok", "store", "shard", "rows",
                              "codecs"}
    GET  /stats              {"rows": N}
    POST /get_many {"pks": [[...], ...]}        -> {"rows": [row|null, ...]}
    POST /put      {"records": [...]}           -> {"applied": N}
    POST /delete   {"records": [...]}           -> {}
    GET  /scan                                  -> {"rows": [...]}

Warm start: a ``snapshot`` path in the config names a
``ShardedOnlineStore.snapshot`` directory (PR 8's integrity-manifest
format); the server verifies THIS shard's file against the manifest
(size + SHA-256 — verify-before-trust) and loads it before serving, so
a re-placed shard starts warm instead of empty.

Config (``cfg.json`` for the CLI, a dict for in-process units)::

    {"store": "profile", "version": 1, "shard_index": 0, "shards": 4,
     "primary_key": ["uid"], "root": "/data/online", "port": 0,
     "snapshot": "/data/snaps/profile_1",        # optional
     "slot": "profile/0", "generation": 2}       # placement identity

A configured ``(slot, generation)`` arms the fencing gate: data verbs
stamped with an ``X-Hops-Generation`` token that differs from the
shard's own are refused with a typed 410 (no breaker strike client
side) — how a zombie shard healing from a partition is kept from
serving stale rows or absorbing writes after its slot was re-placed.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import signal
from pathlib import Path
from typing import Any

import pandas as pd

from hops_tpu.featurestore.online import OnlineStore
from hops_tpu.runtime import flight, wirecodec
from hops_tpu.runtime.httpserver import HTTPServer
from hops_tpu.runtime.logging import get_logger
from hops_tpu.telemetry.metrics import REGISTRY

log = get_logger(__name__)

_m_gen_rejected = REGISTRY.counter(
    "hops_tpu_fleet_generation_rejected_total",
    "Requests refused with a typed 410 because they stamped a "
    "generation newer than the unit's own — a superseded zombie "
    "fenced at the data plane, per unit kind",
    labels=("kind",),
)


class SnapshotCorruptError(RuntimeError):
    """A warm-start snapshot failed its manifest integrity check."""


def _file_sha256(path: Path, chunk: int = 1 << 20) -> str:
    # Local twin of runtime.checkpoint._file_sha256: importing that
    # module would pull jax into every shard server.
    h = hashlib.sha256()
    with path.open("rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                return h.hexdigest()
            h.update(block)


class ShardServer:
    """One ``OnlineStore`` shard served over HTTP (see module docs)."""

    def __init__(self, cfg: dict[str, Any]):
        self.store_name = cfg["store"]
        self.version = int(cfg.get("version", 1))
        self.shard_index = int(cfg["shard_index"])
        self.n_shards = int(cfg.get("shards", 1))
        self.primary_key = [k.lower() for k in cfg["primary_key"]]
        self.codecs = tuple(cfg.get("codecs", ("json", "packed")))
        if "json" not in self.codecs:
            raise ValueError(
                "shardd codecs must include 'json' (the negotiation "
                f"fallback): {self.codecs!r}")
        self.label = f"{self.store_name}_{self.version}"
        # Placement identity (minted by the PlacementClient): compared
        # against the X-Hops-Generation stamp on every data verb so a
        # superseded shard — a zombie healed from a partition — refuses
        # with a typed 410 instead of serving stale rows or taking
        # writes the live generation will never see.
        self.slot = cfg.get("slot")
        self.generation = int(cfg.get("generation", 0))
        self.token = (f"{self.slot}:{self.generation}"
                      if self.slot is not None else None)
        root = Path(cfg["root"])
        root.mkdir(parents=True, exist_ok=True)
        self._store = OnlineStore(
            root / f"{self.label}.shard{self.shard_index}")
        if cfg.get("snapshot"):
            loaded = self.warm_start(cfg["snapshot"])
            log.info("shardd %s shard %d: warm-started %d rows from %s",
                     self.label, self.shard_index, loaded, cfg["snapshot"])
        self._server = _make_server(
            self, int(cfg.get("port", 0)), cfg.get("bind", "127.0.0.1"))
        self.port = self._server.port

    # -- warm start -----------------------------------------------------------

    def warm_start(self, snapshot_dir: str | Path) -> int:
        """Verify this shard's file against the snapshot manifest and
        load its rows. Raises :class:`SnapshotCorruptError` on any
        integrity mismatch — serving from a corrupt warm start is worse
        than starting cold."""
        d = Path(snapshot_dir)
        manifest = json.loads((d / "manifest.json").read_text())
        if int(manifest.get("shards", self.n_shards)) != self.n_shards:
            raise SnapshotCorruptError(
                f"snapshot {d} holds {manifest.get('shards')} shards, "
                f"server expects {self.n_shards}")
        fname = f"shard{self.shard_index}.jsonl"
        meta = manifest.get("files", {}).get(fname)
        if meta is None:
            raise SnapshotCorruptError(f"snapshot {d} has no {fname}")
        p = d / fname
        try:
            size = p.stat().st_size
        except OSError as e:
            raise SnapshotCorruptError(
                f"snapshot {d}: {fname} unreadable ({e})") from None
        if size != meta["size"]:
            raise SnapshotCorruptError(
                f"snapshot {d}: {fname} size {size} != manifest {meta['size']}")
        if _file_sha256(p) != meta["sha256"]:
            raise SnapshotCorruptError(
                f"snapshot {d}: {fname} checksum mismatch")
        with p.open() as f:
            rows = [json.loads(line) for line in f if line.strip()]
        return self._put_rows(rows)

    # -- verb implementations -------------------------------------------------

    def _put_rows(self, rows: list[dict]) -> int:
        if not rows:
            return 0
        # Group by column signature (the ShardedOnlineStore contract):
        # one put per homogeneous slice so a mixed batch never NaN-pads
        # missing columns into stored rows.
        by_cols: dict[frozenset, list[dict]] = {}
        for rec in rows:
            by_cols.setdefault(frozenset(rec), []).append(rec)
        applied = 0
        for recs in by_cols.values():
            applied += self._store.put_dataframe(
                pd.DataFrame(recs), self.primary_key)
        return applied

    def handle(self, method: str, path: str, body: dict) -> tuple[int, dict]:
        if method == "GET" and path == "/healthz":
            return 200, {"status": "ok", "store": self.label,
                         "shard": self.shard_index,
                         "rows": self._store.count(),
                         "codecs": list(self.codecs),
                         "slot": self.slot,
                         "generation": self.generation}
        if method == "GET" and path == "/stats":
            return 200, {"rows": self._store.count()}
        if method == "GET" and path == "/scan":
            return 200, {"rows": list(self._store.scan())}
        if method == "POST" and path == "/get_many":
            return 200, {"rows": self._store.get_many(body["pks"])}
        if method == "POST" and path == "/put":
            return 200, {"applied": self._put_rows(body["records"])}
        if method == "POST" and path == "/delete":
            if body.get("records"):
                self._store.delete_keys(
                    pd.DataFrame(body["records"]), self.primary_key)
            return 200, {}
        return 404, {"error": f"no such verb: {method} {path}"}

    def stop(self) -> None:
        self._server.stop()
        self._store.close()


def _make_server(shard: ShardServer, port: int,
                 bind: str = "127.0.0.1") -> HTTPServer:
    def route(method, path, headers, body):
        # Fencing gate on the data verbs (health/stats stay open — the
        # reconcile sweep identifies zombies through them): a stamped
        # generation newer than this shard's own token means the shard
        # has been superseded; refuse typed so the client degrades
        # without a breaker strike. See docs/operations.md "Partition
        # tolerance & fencing".
        stamped = headers.get("x-hops-generation")
        if (stamped and shard.token and stamped != shard.token
                and path.rstrip("/") not in ("/healthz", "/stats")):
            _m_gen_rejected.inc(kind="shard")
            flight.record("generation_rejected", unit_kind="shard",
                          store=shard.label, shard=shard.shard_index,
                          slot=shard.slot, have=shard.token, got=stamped)
            data = json.dumps({"error": "superseded generation",
                               "slot": shard.slot, "have": shard.token,
                               "got": stamped}).encode()
            return 410, {"Content-Type": "application/json"}, data
        try:
            payload = json.loads(body or b"{}") if method == "POST" else {}
            status, out = shard.handle(method, path, payload)
        except Exception as e:  # noqa: BLE001 — a shard fault must reach the
            # client as a 500 (breaker food), never kill the server
            log.warning("shardd %s shard %d: %s %s failed: %s: %s",
                        shard.label, shard.shard_index, method, path,
                        type(e).__name__, e)
            status, out = 500, {"error": f"{type(e).__name__}: {e}"}
        if (status == 200 and method == "POST" and path == "/get_many"
                and "packed" in shard.codecs
                and wirecodec.MEDIA_TYPE in headers.get("accept", "")):
            try:
                frame = wirecodec.encode_rows(out["rows"])
            except wirecodec.WireCodecError:
                # Un-packable batch (shouldn't happen for stored rows)
                # — negotiation falls back to JSON, client sniffs the
                # Content-Type.
                log.warning("shardd %s shard %d: get_many batch not "
                            "packable; answering JSON", shard.label,
                            shard.shard_index, exc_info=True)
            else:
                return status, {"Content-Type": wirecodec.MEDIA_TYPE}, frame
        data = json.dumps(out, default=str).encode()
        return status, {"Content-Type": "application/json"}, data

    return HTTPServer(route, bind=bind, port=port,
                      name=f"shardd-{shard.label}-{shard.shard_index}",
                      workers=8)


def main(argv: list[str] | None = None) -> None:
    """``python -m hops_tpu.jobs.placement.shardd DIR`` — host the shard
    configured at ``DIR/cfg.json``, announce ``DIR/state.json``
    atomically (the hostd polls for it), then wait for termination —
    the ``serving_host --fleet-worker`` process model."""
    parser = argparse.ArgumentParser(
        prog="python -m hops_tpu.jobs.placement.shardd",
        description=__doc__.split("\n")[0],
    )
    parser.add_argument("dir", help="unit directory holding cfg.json")
    args = parser.parse_args(argv)

    sigs = {signal.SIGTERM, signal.SIGINT}
    signal.pthread_sigmask(signal.SIG_BLOCK, sigs)

    udir = Path(args.dir)
    cfg = json.loads((udir / "cfg.json").read_text())
    server = ShardServer(cfg)
    state = {"store": server.label, "shard": server.shard_index,
             "port": server.port, "pid": os.getpid()}
    tmp = udir / f".state.json.tmp{os.getpid()}"
    tmp.write_text(json.dumps(state))
    os.replace(tmp, udir / "state.json")
    print(json.dumps(state), flush=True)
    signal.sigwait(sigs)
    os._exit(0)


if __name__ == "__main__":
    main()
