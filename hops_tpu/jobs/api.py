"""Job registry + supervised execution.

API surface mirrors the verbs the reference's REST clients exercised:
``jobs.create_job`` / ``start_job`` (jobs_spark_client.py:53-54),
``jobs.get_executions`` / ``stop_job`` (jobs_flink_client.py:33-41,55),
with the templated-JSON job config (jobs_spark_client.py:28-37)
replaced by the typed config layer (``runtime.config``).

A job runs a Python application file in a supervised subprocess whose
stdout/stderr land in the execution's log file under the project's
``Jobs`` dataset; execution state transitions
INITIALIZING → RUNNING → FINISHED/FAILED/KILLED match the states the
Flink client polled for (jobs_flink_client.py:55-61).
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import subprocess
import sys
import threading
import time
import uuid
from pathlib import Path
from typing import Any

from hops_tpu.runtime import config as config_lib
from hops_tpu.runtime import fs
from hops_tpu.runtime.logging import get_logger

log = get_logger(__name__)

_procs: dict[str, subprocess.Popen] = {}

# Execution bootstrap: runs the app file as __main__ with its argv, but
# first re-applies JAX_PLATFORMS if a sitecustomize pre-imported jax
# (which snapshots the env var before the job's intent can take effect).
# Without this, a cpu-destined job still initializes the accelerator
# backend — and hangs outright if the accelerator is unreachable. The
# platform-forcing trick matches tests/conftest.py and launch.py.
_BOOTSTRAP = """\
import os, sys, runpy
_p = os.environ.get("JAX_PLATFORMS")
if _p and "jax" in sys.modules:
    sys.modules["jax"].config.update("jax_platforms", _p)
sys.argv = sys.argv[1:]
sys.path.insert(0, os.path.dirname(os.path.abspath(sys.argv[0])))
runpy.run_path(sys.argv[0], run_name="__main__")
"""
_procs_lock = threading.Lock()


@dataclasses.dataclass
class JobConfig:
    """Typed job config — the reference's ``job_config.json`` template.

    ``app_file`` is the Python entry file (the reference's
    ``{APP_FILE}`` placeholder); ``dependencies`` are extra files/dirs
    staged next to it; ``chips`` requests a sub-slice (0 = whole slice,
    mapped to device-visibility env for the child process).
    """

    app_file: str = ""
    default_args: list[str] = dataclasses.field(default_factory=list)
    dependencies: list[str] = dataclasses.field(default_factory=list)
    env: dict[str, str] = dataclasses.field(default_factory=dict)
    chips: int = 0
    job_type: str = "PYTHON"  # PYTHON | STREAMING


def _jobs_root() -> Path:
    p = Path(fs.project_path("Jobs"))
    p.mkdir(parents=True, exist_ok=True)
    return p


def _job_dir(name: str) -> Path:
    return _jobs_root() / name


@dataclasses.dataclass
class Execution:
    """One run of a job (the reference's execution record)."""

    job_name: str
    execution_id: str
    state: str = "INITIALIZING"
    submitted_at: float = 0.0
    finished_at: float | None = None
    args: list[str] = dataclasses.field(default_factory=list)
    exit_code: int | None = None
    log_path: str = ""

    @property
    def final(self) -> bool:
        return self.state in ("FINISHED", "FAILED", "KILLED")

    def _path(self) -> Path:
        return _job_dir(self.job_name) / "executions" / f"{self.execution_id}.json"

    def save(self) -> None:
        # Atomic replace: wait_for_completion polls this file at 10 Hz,
        # so a truncate-then-write would expose empty/partial JSON.
        path = self._path()
        path.parent.mkdir(parents=True, exist_ok=True)
        tmp = path.with_suffix(f".tmp{os.getpid()}")
        tmp.write_text(json.dumps(dataclasses.asdict(self), indent=2))
        os.replace(tmp, path)

    def stdout(self) -> str:
        p = Path(self.log_path)
        return p.read_text() if p.exists() else ""


class Job:
    def __init__(self, name: str, config: JobConfig):
        self.name = name
        self.config = config

    def save(self) -> "Job":
        d = _job_dir(self.name)
        d.mkdir(parents=True, exist_ok=True)
        (d / "job.json").write_text(
            json.dumps({"name": self.name, "config": config_lib.to_dict(self.config)}, indent=2)
        )
        return self

    @classmethod
    def load(cls, name: str) -> "Job":
        meta = json.loads((_job_dir(name) / "job.json").read_text())
        return cls(name, config_lib.from_dict(JobConfig, meta["config"]))


def create_job(name: str, config: JobConfig | dict[str, Any]) -> Job:
    """Register (or update) a job; mirrors ``jobs.create_job``."""
    if isinstance(config, dict):
        config = config_lib.from_dict(JobConfig, config)
    app = Path(config.app_file)
    if not app.is_absolute():
        config.app_file = str(Path(fs.project_path()) / app)
    return Job(name, config).save()


def get_job(name: str) -> Job:
    return Job.load(name)


def get_jobs() -> list[str]:
    return sorted(p.name for p in _jobs_root().iterdir() if (p / "job.json").exists())


def delete_job(name: str) -> None:
    fs.rmr(_job_dir(name))


def _child_pythonpath(existing: str | None) -> str:
    """Import path for job children: inherited/job-config ``PYTHONPATH``,
    then the framework's own location, then the parent's on-disk
    ``sys.path`` entries.

    A clean checkout is neither pip-installed nor on ``PYTHONPATH``, so
    without this a child spawned by ``start_job`` cannot
    ``import hops_tpu`` at all. The reference's client stages its
    dependencies alongside the job for the same reason
    (jobs-client/spark/jobs_spark_client.py:49-54).
    """
    import hops_tpu

    # Job-configured / inherited PYTHONPATH keeps precedence over
    # everything — including the parent's framework checkout — so a job
    # can pin its own staged dependencies (even a staged hops_tpu);
    # the framework root after that covers the bare-checkout case;
    # sys.path[0] (the parent script's directory) is excluded so stray
    # modules next to the launcher don't shadow the child's imports.
    entries = existing.split(os.pathsep) if existing else []
    entries.append(str(Path(hops_tpu.__file__).resolve().parent.parent))
    entries += [p for p in sys.path[1:] if p and Path(p).exists()]
    deduped = list(dict.fromkeys(entries))
    return os.pathsep.join(deduped)


def start_job(name: str, args: list[str] | None = None) -> Execution:
    """Launch an execution as a supervised subprocess; returns immediately.

    The child inherits the project workspace (``HOPS_TPU_WORKSPACE``)
    so its runs/artifacts land in the same project tree the parent
    sees — the in-cluster stand-in for the REST submission hop.
    """
    job = Job.load(name)
    ex = Execution(
        job_name=name,
        execution_id=uuid.uuid4().hex[:12],
        args=list(args or job.config.default_args),
        submitted_at=time.time(),
    )
    logdir = _job_dir(name) / "executions"
    logdir.mkdir(parents=True, exist_ok=True)
    ex.log_path = str(logdir / f"{ex.execution_id}.log")
    ex.save()

    env = dict(os.environ)
    env.update(job.config.env)
    env["HOPS_TPU_WORKSPACE"] = str(fs.workspace_root())
    env["HOPS_TPU_PROJECT"] = fs.project_name()
    env["HOPS_TPU_JOB_NAME"] = name
    env["HOPS_TPU_EXECUTION_ID"] = ex.execution_id
    env["PYTHONPATH"] = _child_pythonpath(env.get("PYTHONPATH"))

    logfile = open(ex.log_path, "w")
    try:
        proc = subprocess.Popen(
            [sys.executable, "-c", _BOOTSTRAP, job.config.app_file, *ex.args],
            stdout=logfile,
            stderr=subprocess.STDOUT,
            env=env,
            cwd=str(_job_dir(name)),
        )
    except OSError as e:
        logfile.write(f"spawn failed: {e}\n")
        logfile.close()
        ex.state, ex.finished_at, ex.exit_code = "FAILED", time.time(), -1
        ex.save()
        return ex

    with _procs_lock:
        _procs[f"{name}/{ex.execution_id}"] = proc
    ex.state = "RUNNING"
    ex.save()

    def _reap():
        code = proc.wait()
        logfile.close()
        # The record read-modify-write races with stop_job's KILLED
        # verdict; _procs_lock serializes both.
        with _procs_lock:
            cur = get_execution(name, ex.execution_id)
            cur.exit_code = code
            cur.finished_at = time.time()
            if cur.state != "KILLED":
                cur.state = "FINISHED" if code == 0 else "FAILED"
            cur.save()
            _procs.pop(f"{name}/{ex.execution_id}", None)

    threading.Thread(target=_reap, daemon=True, name=f"job-reap-{name}").start()
    return ex


def get_execution(name: str, execution_id: str) -> Execution:
    p = _job_dir(name) / "executions" / f"{execution_id}.json"
    return Execution(**json.loads(p.read_text()))


def get_executions(name: str) -> list[Execution]:
    """Newest-first execution list; mirrors ``jobs.get_executions``."""
    d = _job_dir(name) / "executions"
    if not d.exists():
        return []
    exs = [Execution(**json.loads(p.read_text())) for p in d.glob("*.json")]
    return sorted(exs, key=lambda e: e.submitted_at, reverse=True)


def stop_job(name: str, execution_id: str | None = None) -> None:
    """Kill running execution(s) of a job; mirrors ``jobs.stop_job``."""
    for ex in get_executions(name):
        if ex.final or (execution_id and ex.execution_id != execution_id):
            continue
        with _procs_lock:
            proc = _procs.get(f"{name}/{ex.execution_id}")
        killed = False
        if proc is not None and proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
            killed = True
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
        # Only overwrite the record when we actually signaled it — the
        # process may have exited on its own between the listing and the
        # signal, in which case _reap's FINISHED/FAILED verdict stands.
        if killed:
            with _procs_lock:
                cur = get_execution(name, ex.execution_id)
                cur.state = "KILLED"
                cur.finished_at = cur.finished_at or time.time()
                cur.save()


def wait_for_completion(name: str, execution_id: str, timeout_s: float = 600.0) -> Execution:
    """Poll an execution to a final state (the Flink client's 90 s poll
    loop, jobs_flink_client.py:55-61, with a configurable budget)."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        ex = get_execution(name, execution_id)
        if ex.final:
            return ex
        time.sleep(0.1)
    raise TimeoutError(f"execution {name}/{execution_id} not done after {timeout_s}s")
