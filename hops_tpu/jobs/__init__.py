"""Jobs / orchestration layer (SURVEY.md §2.7, L6).

The reference's control plane is the Hopsworks Jobs REST API driven by
thin clients (``jobs-client/spark/jobs_spark_client.py:28-54``,
``jobs-client/flink/jobs_flink_client.py``) plus Airflow operators
(``airflow/launch_jobs.py:79-130``). Here the "cluster" is the TPU
slice itself, so the control plane is local-first: jobs are registered
in the project's ``Jobs`` dataset, executed as supervised OS processes
on the host (each owning the slice or a sub-slice via
``JAX_PLATFORMS``/visible-device env), and polled through the same
create/start/poll/stop verbs the REST clients used. The DAG module
gives the Airflow-operator surface without an Airflow install.
"""

from hops_tpu.jobs import dag, dataset, streaming  # noqa: F401
from hops_tpu.jobs.api import (  # noqa: F401
    Execution,
    Job,
    JobConfig,
    create_job,
    delete_job,
    get_executions,
    get_job,
    get_jobs,
    start_job,
    stop_job,
    wait_for_completion,
)
