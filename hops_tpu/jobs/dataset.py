"""Dataset staging — the reference's ``hops.dataset.upload`` hop.

The Spark jobs client zipped a local workspace and uploaded it before
job submission (jobs_spark_client.py:44-50, README workflow steps 1-3).
Staging here is a copy into the project tree, plus the same
zip-a-workspace convenience for shipping a code directory with its
dependencies.
"""

from __future__ import annotations

import shutil
import zipfile
from pathlib import Path

from hops_tpu.runtime import fs


def upload(local_path: str | Path, remote_dir: str) -> str:
    """Copy a local file/dir into ``<project>/<remote_dir>/``; returns
    the project-tree destination path."""
    src = Path(local_path)
    dst_dir = Path(fs.project_path(remote_dir))
    dst_dir.mkdir(parents=True, exist_ok=True)
    dst = dst_dir / src.name
    if src.is_dir():
        shutil.copytree(src, dst, dirs_exist_ok=True)
    else:
        shutil.copy2(src, dst)
    return str(dst)


def download(remote_path: str, local_dir: str | Path = ".") -> str:
    return fs.copy_to_local(remote_path, local_dir)


def upload_workspace(workspace_dir: str | Path, remote_dir: str, name: str | None = None) -> str:
    """Zip a code workspace and stage it (the client's zip+upload step)."""
    src = Path(workspace_dir)
    name = name or f"{src.name}.zip"
    dst_dir = Path(fs.project_path(remote_dir))
    dst_dir.mkdir(parents=True, exist_ok=True)
    dst = dst_dir / name
    with zipfile.ZipFile(dst, "w", zipfile.ZIP_DEFLATED) as zf:
        for p in sorted(src.rglob("*")):
            if p.is_file():
                zf.write(p, p.relative_to(src))
    return str(dst)


def extract(archive_path: str | Path, dest_dir: str | Path) -> str:
    dest = Path(dest_dir)
    dest.mkdir(parents=True, exist_ok=True)
    with zipfile.ZipFile(archive_path) as zf:
        zf.extractall(dest)
    return str(dest)
