"""Per-host launcher CLI — ``python -m hops_tpu.launch [opts] script.py``.

The reference's launcher was a Spark driver scheduling wrapper functions
onto executors (SURVEY.md §3.1-3.2); on TPU every host must run the
same SPMD program, so the launcher becomes this thin per-host agent
(SURVEY.md §7 build stage 3 "launcher-owns-the-mesh"): it joins the
multi-host runtime (coordination service on host 0), pins the shared
run-session id, then hands the host to the user's script/module, whose
``experiment.*`` calls now see the full slice.

Usage (one invocation per host, e.g. via your pod scheduler):

    python -m hops_tpu.launch \
        --coordinator 10.0.0.2:1234 --num-processes 4 --process-id $IDX \
        train.py --epochs 10

Single-host runs need no flags: ``python -m hops_tpu.launch train.py``.
Flags may also come from JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES /
JAX_PROCESS_ID env vars (the GKE path auto-discovers and needs none).
"""

from __future__ import annotations

import argparse
import os
import runpy
import sys


def main(argv: list[str] | None = None) -> None:
    parser = argparse.ArgumentParser(
        prog="python -m hops_tpu.launch", description=__doc__.split("\n")[0]
    )
    parser.add_argument("--coordinator", default=os.environ.get("JAX_COORDINATOR_ADDRESS"))
    parser.add_argument(
        "--num-processes",
        type=int,
        default=int(os.environ["JAX_NUM_PROCESSES"]) if "JAX_NUM_PROCESSES" in os.environ else None,
    )
    parser.add_argument(
        "--process-id",
        type=int,
        default=int(os.environ["JAX_PROCESS_ID"]) if "JAX_PROCESS_ID" in os.environ else None,
    )
    parser.add_argument(
        "--platform",
        default=os.environ.get("HOPS_TPU_PLATFORM"),
        help="force the JAX platform (e.g. cpu) — applied via jax.config "
        "before backend init, so it wins even when a sitecustomize has "
        "already imported jax and snapshotted JAX_PLATFORMS",
    )
    parser.add_argument("-m", "--module", help="run a module instead of a script file")
    parser.add_argument("script", nargs="?", help="Python file to run on this host")
    parser.add_argument("script_args", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)

    if not args.module and not args.script:
        parser.error("provide a script file or -m module")

    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)

    # Join the slice BEFORE the user code can touch the XLA backend.
    from hops_tpu.parallel import multihost

    multihost.initialize(
        coordinator_address=args.coordinator,
        num_processes=args.num_processes,
        process_id=args.process_id,
    )

    if args.module:
        sys.argv = [args.module, *([args.script] if args.script else []), *args.script_args]
        runpy.run_module(args.module, run_name="__main__", alter_sys=True)
    else:
        sys.argv = [args.script, *args.script_args]
        runpy.run_path(args.script, run_name="__main__")


if __name__ == "__main__":
    main()
