"""SQL execution over feature-store tables via in-process sqlite3.

Table resolution: identifiers in FROM/JOIN clauses are matched against
feature groups — ``name_<version>`` pins a version, a bare ``name``
reads the latest. Matched tables are loaded into a temporary sqlite
database and the query runs there (the same pattern as the reference's
server-side "query constructor → spark.sql", SURVEY.md §3.5, minus the
cluster).
"""

from __future__ import annotations

import re
import sqlite3

import pandas as pd

_FROM_RE = re.compile(r"\b(?:from|join)\s+([A-Za-z_][A-Za-z0-9_.]*)", re.IGNORECASE)


def _resolve_tables(sql: str, feature_store) -> dict[str, pd.DataFrame]:
    tables: dict[str, pd.DataFrame] = {}
    for ident in _FROM_RE.findall(sql):
        name = ident.split(".")[-1]
        if name in tables:
            continue
        df = _lookup(feature_store, name)
        if df is not None:
            tables[name] = df
    return tables


def _lookup(feature_store, ident: str) -> pd.DataFrame | None:
    if feature_store is None:
        return None
    stem, _, ver = ident.rpartition("_")
    candidates = [(stem, int(ver))] if (stem and ver.isdigit()) else []
    candidates.append((ident, None))
    for name, version in candidates:
        try:
            return feature_store.get_feature_group(name, version).read()
        except KeyError:
            continue
    return None


def execute(sql: str, feature_store=None, connector=None,
            tables: dict[str, pd.DataFrame] | None = None) -> pd.DataFrame:
    """Run ``sql`` and return a DataFrame. Tables come from (in order)
    the explicit ``tables`` dict, the feature store, or ``connector.read()``
    registered under the connector's name."""
    resolved = dict(tables or {})
    for name, df in _resolve_tables(sql, feature_store).items():
        resolved.setdefault(name, df)
    if connector is not None and getattr(connector, "name", None):
        try:
            resolved.setdefault(connector.name, connector.read())
        except (RuntimeError, NotImplementedError, FileNotFoundError):
            pass
    db = sqlite3.connect(":memory:")
    try:
        for name, df in resolved.items():
            df.to_sql(name, db, index=False)
        return pd.read_sql_query(sql, db)
    finally:
        db.close()


class _Cursor:
    """Minimal DB-API cursor (the PyHive shape the reference exercised)."""

    def __init__(self, feature_store):
        self._fs = feature_store
        self._result: pd.DataFrame | None = None

    def execute(self, sql: str) -> None:
        self._result = execute(sql, feature_store=self._fs)

    @property
    def description(self):
        if self._result is None:
            return None
        return [(c, None, None, None, None, None, None) for c in self._result.columns]

    def fetchall(self) -> list[tuple]:
        return [tuple(r) for r in self._result.itertuples(index=False)]

    def fetchone(self):
        rows = self.fetchall()
        return rows[0] if rows else None

    def close(self) -> None:
        pass


class _Connection:
    def __init__(self, feature_store):
        self._fs = feature_store

    def cursor(self) -> _Cursor:
        return _Cursor(self._fs)

    def close(self) -> None:
        pass


def connection(feature_store=None) -> _Connection:
    """Reference: ``hive.setup_hive_connection()`` (PyHive.ipynb:46)."""
    if feature_store is None:
        from hops_tpu import featurestore as hsfs

        feature_store = hsfs.connection().get_feature_store()
    return _Connection(feature_store)
