"""Analytic SQL gateway (the reference's Hive surface, re-based on sqlite).

Reference: ``hops.hive.setup_hive_connection()`` + PyHive
(notebooks/hive/PyHive.ipynb:46) and the two-way-TLS Hive JDBC client
(hive/src/.../HiveJDBCClient.java — SURVEY.md §2.8). The TPU build has
no Hive; SQL over feature-store tables runs in-process on sqlite3
(stdlib), with a DB-API-shaped connection for PyHive-style callers.
"""

from hops_tpu.sql.gateway import connection, execute  # noqa: F401

__all__ = ["connection", "execute"]
