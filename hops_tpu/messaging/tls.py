"""Per-project TLS material — the ``hops.tls`` surface.

Reference functions (KafkaPython.ipynb:155-157, KafkaSparkPython.ipynb:
165-169, SURVEY.md §2.2): locate the project CA chain, client cert/key
and trust/key stores provisioned by the platform. Here the material
lives under ``<project>/.tls`` and is generated on demand with the
system ``openssl`` (self-signed project CA + client cert). Store
passwords follow the reference's file-based delivery.
"""

from __future__ import annotations

import os
import secrets
import shutil
import subprocess
from pathlib import Path

from hops_tpu.runtime import fs


def _tls_dir() -> Path:
    d = Path(fs.project_path(".tls"))
    d.mkdir(parents=True, exist_ok=True)
    return d


def _ensure_material() -> Path:
    """Generate-or-return the material directory.

    Generation happens in a private temp dir that is atomically renamed
    into place once complete (marker file written last), so concurrent
    callers never observe partially-written material and a crash mid-
    generation leaves no poisoned sentinel.
    """
    base = _tls_dir()
    final = base / "material"
    if (final / ".complete").exists():
        return final
    tmp = base / f".material-tmp-{os.getpid()}-{secrets.token_hex(4)}"
    tmp.mkdir(parents=True, exist_ok=True)
    _generate_into(tmp)
    (tmp / ".complete").write_text("")
    if final.exists() and not (final / ".complete").exists():
        shutil.rmtree(final, ignore_errors=True)  # stale partial from a crash
    try:
        os.rename(tmp, final)
    except OSError:
        shutil.rmtree(tmp, ignore_errors=True)  # another caller won the race
    return final


def _generate_into(d: Path) -> None:
    ca = d / "ca_chain.pem"
    project = fs.project_name()
    try:
        subprocess.run(
            ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
             "-keyout", str(d / "ca_key.pem"), "-out", str(ca),
             "-days", "365", "-subj", f"/CN={project}-ca"],
            check=True, capture_output=True,
        )
        subprocess.run(
            ["openssl", "req", "-newkey", "rsa:2048", "-nodes",
             "-keyout", str(d / "client_key.pem"), "-out", str(d / "client.csr"),
             "-subj", f"/CN={fs.project_user()}"],
            check=True, capture_output=True,
        )
        subprocess.run(
            ["openssl", "x509", "-req", "-in", str(d / "client.csr"),
             "-CA", str(ca), "-CAkey", str(d / "ca_key.pem"),
             "-CAcreateserial", "-out", str(d / "client_cert.pem"), "-days", "365"],
            check=True, capture_output=True,
        )
    except (OSError, subprocess.CalledProcessError):
        # No openssl: write clearly-marked placeholder material so the
        # path contract still holds for tooling/tests.
        for name in ("ca_chain.pem", "client_cert.pem", "client_key.pem"):
            (d / name).write_text(f"# placeholder {name}; openssl unavailable\n")
    (d / "trust_store.jks").write_bytes(ca.read_bytes())
    (d / "key_store.jks").write_bytes(
        (d / "client_cert.pem").read_bytes() + (d / "client_key.pem").read_bytes()
    )
    (d / "material_passwd").write_text(secrets.token_hex(16))


def get_ca_chain_location() -> str:
    return str(_ensure_material() / "ca_chain.pem")


def get_client_certificate_location() -> str:
    return str(_ensure_material() / "client_cert.pem")


def get_client_key_location() -> str:
    return str(_ensure_material() / "client_key.pem")


def get_trust_store() -> str:
    return str(_ensure_material() / "trust_store.jks")


def get_key_store() -> str:
    return str(_ensure_material() / "key_store.jks")


def _get_password() -> str:
    return (_ensure_material() / "material_passwd").read_text()


def get_trust_store_pwd() -> str:
    return _get_password()


def get_key_store_pwd() -> str:
    return _get_password()
