"""Search index — the `hops.elasticsearch` twin.

The reference exposes per-project Elasticsearch connection config for
Spark↔ES pipelines (``get_elasticsearch_config(index)``, reference:
notebooks/spark/Elasticsearch-python.ipynb:72,123; SURVEY.md §2.2).
The TPU build keeps the config-provider surface for external clusters
and adds what the platform actually used ES for — searching runs, logs
and metadata — as an embedded inverted index over JSON documents in the
project tree, so `index → document → search` works with zero external
services.
"""

from __future__ import annotations

import json
import re
import threading
from collections import defaultdict
from pathlib import Path
from typing import Any

from hops_tpu.runtime import fs

_TOKEN = re.compile(r"[a-z0-9_]+")
_lock = threading.Lock()


def get_elasticsearch_config(index: str) -> dict[str, str]:
    """Connector config for an external ES cluster (reference shape:
    host/port/auth keys consumed by the Spark connector). Point at a
    real cluster via ``HOPS_TPU_ES_HOST``/``HOPS_TPU_ES_PORT``; the
    embedded index below needs none of this."""
    import os

    return {
        "es.nodes": os.environ.get("HOPS_TPU_ES_HOST", "localhost"),
        "es.port": os.environ.get("HOPS_TPU_ES_PORT", "9200"),
        "es.resource": f"{fs.project_name()}_{index}/_doc",
        "es.net.http.auth.user": fs.project_user(),
        "es.index.auto.create": "true",
    }


class SearchIndex:
    """Embedded inverted index over JSON docs, persisted per project."""

    def __init__(self, name: str):
        self.name = name
        self.dir = Path(fs.project_path(f"SearchIndex/{name}"))
        self.dir.mkdir(parents=True, exist_ok=True)
        self._docs_file = self.dir / "docs.jsonl"

    @staticmethod
    def _tokens(value: Any) -> set[str]:
        return set(_TOKEN.findall(json.dumps(value, default=str).lower()))

    def index_document(self, doc_id: str, doc: dict[str, Any]) -> None:
        with _lock, self._docs_file.open("a") as f:
            f.write(json.dumps({"_id": doc_id, "_source": doc}, default=str) + "\n")

    def _scan(self) -> dict[str, dict[str, Any]]:
        docs: dict[str, dict[str, Any]] = {}
        if self._docs_file.exists():
            for line in self._docs_file.read_text().splitlines():
                rec = json.loads(line)
                docs[rec["_id"]] = rec["_source"]  # last write wins
        return docs

    def get(self, doc_id: str) -> dict[str, Any] | None:
        return self._scan().get(doc_id)

    def count(self) -> int:
        return len(self._scan())

    def search(self, query: str, limit: int = 10) -> list[dict[str, Any]]:
        """Rank docs by matched-term count (ES-style hit envelopes)."""
        terms = set(_TOKEN.findall(query.lower()))
        scores: dict[str, int] = defaultdict(int)
        docs = self._scan()
        for doc_id, src in docs.items():
            hit = len(terms & self._tokens(src))
            if hit:
                scores[doc_id] = hit
        ranked = sorted(scores.items(), key=lambda kv: (-kv[1], kv[0]))[:limit]
        return [
            {"_id": doc_id, "_score": score, "_source": docs[doc_id]}
            for doc_id, score in ranked
        ]

    def delete(self) -> None:
        fs.rmr(self.dir)


def index_run(run_meta: dict[str, Any]) -> None:
    """Index an experiment-run record for search (what the platform's
    Experiments UI used ES for)."""
    SearchIndex("experiments").index_document(str(run_meta.get("run_id")), run_meta)


def search_runs(query: str, limit: int = 10) -> list[dict[str, Any]]:
    return SearchIndex("experiments").search(query, limit)
