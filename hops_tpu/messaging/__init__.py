"""Host-side messaging: RPC control plane + pubsub + TLS material.

The reference used Spark driver<->executor RPC for trial control,
SSL-Kafka for logs/streams and per-project X.509 material (SURVEY.md
§2.2, §5 "Distributed communication backend"). Device-side collectives
are XLA's job (hops_tpu.parallel); this package is the host-side
control/data plane: a tiny JSON-line RPC layer (trial heartbeats, job
control) and a pubsub abstraction (inference logging, streaming ingest).
"""

from hops_tpu.messaging import rpc  # noqa: F401
