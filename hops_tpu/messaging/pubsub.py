"""Durable pubsub topics — the Kafka-surface equivalent.

The reference used per-project SSL Kafka for inference logging and
streaming ingest, with broker discovery and an Avro schema registry
(``hops.kafka``: get_broker_endpoints / get_schema — KafkaPython.ipynb:
134,155; SURVEY.md §2.2). Here a topic is an append-only JSONL log under
the project's ``Topics`` dataset: producers append, consumers tail with
durable per-group offsets — the same at-least-once, replayable contract,
with no broker to operate. The storage backend rides the fs façade, so a
shared filesystem gives cross-host pubsub; a real broker can slot in
behind the same API later.
"""

from __future__ import annotations

import json
import threading
import time
from pathlib import Path
from typing import Any, Iterator

from hops_tpu.runtime import fs
from hops_tpu.runtime.logging import get_logger
from hops_tpu.telemetry.metrics import REGISTRY

log = get_logger(__name__)

_lock = threading.Lock()

_m_consumer_lag = REGISTRY.gauge(
    "hops_tpu_pubsub_consumer_lag",
    "Bytes between a consumer group's offset and the topic end "
    "(0 = caught up), sampled at every poll",
    labels=("topic", "group"),
)
_m_poison = REGISTRY.counter(
    "hops_tpu_pubsub_poison_records_total",
    "Unparsable records skipped by consumers (corrupt on the wire or "
    "at rest); the offset keeps moving past them",
    labels=("topic",),
)
_m_replayed = REGISTRY.counter(
    "hops_tpu_pubsub_replayed_records_total",
    "Records re-delivered after a consumer restart because the previous "
    "incarnation died between delivery and its offset commit "
    "(at-least-once replay — downstream dedupe owns convergence)",
    labels=("topic", "group"),
)


def _topics_root() -> Path:
    p = Path(fs.project_path("Topics"))
    p.mkdir(parents=True, exist_ok=True)
    return p


def _topic_dir(name: str) -> Path:
    return _topics_root() / name


def create_topic(name: str, schema: dict[str, Any] | None = None) -> str:
    d = _topic_dir(name)
    d.mkdir(parents=True, exist_ok=True)
    (d / "log.jsonl").touch()
    if schema is not None:
        (d / "schema.json").write_text(json.dumps(schema, indent=2))
    return name


def topic_exists(name: str) -> bool:
    return (_topic_dir(name) / "log.jsonl").exists()


def list_topics() -> list[str]:
    return sorted(d.name for d in _topics_root().iterdir() if d.is_dir())


def get_schema(topic: str) -> dict[str, Any] | None:
    """Schema-registry lookup (reference: ``kafka.get_schema(topic)``)."""
    p = _topic_dir(topic) / "schema.json"
    return json.loads(p.read_text()) if p.exists() else None


def get_broker_endpoints() -> str:
    """Reference-parity discovery (``kafka.get_broker_endpoints``): the
    'broker' is the topics root on the shared filesystem."""
    return str(_topics_root())


def get_security_protocol() -> str:
    return "FS"  # filesystem-backed; TLS applies at the mount, not here


class Producer:
    def __init__(self, topic: str):
        if not topic_exists(topic):
            create_topic(topic)
        self._path = _topic_dir(topic) / "log.jsonl"

    def send(self, value: Any, key: str | None = None) -> None:
        from hops_tpu.runtime import faultinject

        rec = {"ts": time.time(), "key": key, "value": value}
        line = (json.dumps(rec, default=str) + "\n").encode()
        # Chaos point: raise/delay a publish, or corrupt the encoded
        # record (consumers must survive an unparsable line).
        line = faultinject.fire_data("pubsub.publish", line)
        with _lock:
            with self._path.open("ab") as f:
                f.write(line)

    def flush(self) -> None:
        pass  # every send is durable


class Consumer:
    """Tailing consumer with a durable per-group offset.

    A committed group offset always resumes (that is what makes the
    group durable); ``from_beginning`` only chooses where a group
    WITHOUT a commit starts — byte 0 (catch up on history) or the
    current end (new records only). Kafka's ``auto.offset.reset``
    contract: a restarted write-through materializer must not replay
    the whole topic just because it was constructed replay-capable.
    """

    def __init__(self, topic: str, group: str = "default", from_beginning: bool = False):
        if not topic_exists(topic):
            create_topic(topic)
        self._topic = topic
        self._group = group
        self._log = _topic_dir(topic) / "log.jsonl"
        self._offset_file = _topic_dir(topic) / f"offset.{group}"
        # Delivered watermark: the highest offset this group has ever
        # POLLED (vs committed). A restart whose committed offset sits
        # below it is about to replay a span the previous incarnation
        # consumed but never committed — at-least-once by design, but
        # it must be VISIBLE (a silent whole-batch replay after a
        # mid-batch crash is indistinguishable from fresh data to
        # anything downstream without its own dedupe).
        self._delivered_file = _topic_dir(topic) / f"delivered.{group}"
        if self._offset_file.exists():
            self._offset = int(self._offset_file.read_text() or 0)
        else:
            self._offset = 0 if from_beginning else self._current_end()
        self._delivered = self._read_delivered()
        self._replay_end = 0
        self._replay_logged = False
        if self._delivered > self._offset:
            self._replay_end = self._delivered
        self._m_lag = _m_consumer_lag.labels(topic=topic, group=group)
        self._m_poison = _m_poison.labels(topic=topic)
        self._m_replayed = _m_replayed.labels(topic=topic, group=group)

    def _read_delivered(self) -> int:
        try:
            return int(self._delivered_file.read_text() or 0)
        except (OSError, ValueError):
            return 0

    def _current_end(self) -> int:
        return self._log.stat().st_size

    @property
    def offset(self) -> int:
        """Byte offset into the topic log; settable for external
        checkpointing (the streaming runner's checkpointLocation)."""
        return self._offset

    @offset.setter
    def offset(self, value: int) -> None:
        self._offset = int(value)

    def end_offset(self) -> int:
        """Current end of the topic log (bytes)."""
        return self._current_end()

    def lag(self) -> int:
        """Bytes between this group's offset and the topic end — 0 when
        caught up. The watermark check write-through materializers and
        streaming runners gate their drain on."""
        return max(0, self._current_end() - self._offset)

    def poll(self, max_records: int | None = None) -> list[dict[str, Any]]:
        return [rec for _, rec in self.poll_records(max_records)]

    def poll_records(
        self, max_records: int | None = None
    ) -> list[tuple[int, dict[str, Any]]]:
        """Like :meth:`poll`, but each record arrives with its starting
        byte offset in the topic log — the handle span ledgers and
        replay dedupe key on. A raised fault restores the pre-poll
        offset first, so a retried poll re-delivers the whole batch
        (at-least-once) instead of silently skipping the partial one.
        """
        from hops_tpu.runtime import faultinject

        start = self._offset
        replayed_span: tuple[int, int] | None = None
        replayed = poisoned = 0
        out: list[tuple[int, dict[str, Any]]] = []
        try:
            with self._log.open("rb") as f:
                f.seek(self._offset)
                for line in f:
                    if not line.endswith(b"\n"):
                        break  # partial write in flight; retry next poll
                    at = self._offset
                    self._offset += len(line)
                    # Chaos point: per-record consumer-side faults —
                    # error/latency abort the poll (offset restored
                    # in the except arm, so a retried poll re-delivers
                    # the batch), corrupt mangles THIS record after the
                    # durable log, making a consumer-side poison record
                    # without damaging the topic.
                    line = faultinject.fire_data("pubsub.poll", line)
                    if at < self._replay_end:
                        replayed += 1
                        replayed_span = (
                            at if replayed_span is None else replayed_span[0],
                            self._offset,
                        )
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        # A corrupt record must not wedge the consumer
                        # at this offset forever: skip it, keep tailing.
                        poisoned += 1
                        log.warning("topic %s: skipping unparsable record "
                                    "at offset %d", self._topic, at)
                        continue
                    out.append((at, rec))
                    if max_records is not None and len(out) >= max_records:
                        break
        except Exception:
            # Counters stay untouched on the abort path: the retried
            # poll re-delivers (and re-counts) the same records.
            self._offset = start
            raise
        if replayed:
            self._m_replayed.inc(replayed)
        if poisoned:
            self._m_poison.inc(poisoned)
        if replayed_span is not None and not self._replay_logged:
            self._replay_logged = True
            log.warning(
                "topic %s group %s: replaying span [%d, %d) delivered "
                "before the last restart but never committed "
                "(at-least-once — downstream dedupe owns convergence)",
                self._topic, self._group, replayed_span[0], replayed_span[1],
            )
            from hops_tpu.runtime import flight

            flight.record("span_replayed", topic=self._topic,
                          group=self._group, first=replayed_span[0],
                          last=replayed_span[1])
        if self._offset > self._delivered:
            self._delivered = self._offset
            try:
                self._delivered_file.write_text(str(self._delivered))
            except OSError as e:
                # Watermark persistence is best-effort visibility: a
                # failed write only costs replay DETECTION, never data.
                log.warning("topic %s: could not persist delivered "
                            "watermark: %s", self._topic, e)
        self._m_lag.set(max(0, self._current_end() - self._offset))
        return out

    def commit(self) -> None:
        self._offset_file.write_text(str(self._offset))

    def __iter__(self) -> Iterator[dict[str, Any]]:
        while True:
            batch = self.poll()
            if not batch:
                return
            yield from batch
