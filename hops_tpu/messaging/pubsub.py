"""Durable pubsub topics — the Kafka-surface equivalent.

The reference used per-project SSL Kafka for inference logging and
streaming ingest, with broker discovery and an Avro schema registry
(``hops.kafka``: get_broker_endpoints / get_schema — KafkaPython.ipynb:
134,155; SURVEY.md §2.2). Here a topic is an append-only JSONL log under
the project's ``Topics`` dataset: producers append, consumers tail with
durable per-group offsets — the same at-least-once, replayable contract,
with no broker to operate. The storage backend rides the fs façade, so a
shared filesystem gives cross-host pubsub; a real broker can slot in
behind the same API later.
"""

from __future__ import annotations

import json
import threading
import time
from pathlib import Path
from typing import Any, Iterator

from hops_tpu.runtime import fs
from hops_tpu.runtime.logging import get_logger

log = get_logger(__name__)

_lock = threading.Lock()


def _topics_root() -> Path:
    p = Path(fs.project_path("Topics"))
    p.mkdir(parents=True, exist_ok=True)
    return p


def _topic_dir(name: str) -> Path:
    return _topics_root() / name


def create_topic(name: str, schema: dict[str, Any] | None = None) -> str:
    d = _topic_dir(name)
    d.mkdir(parents=True, exist_ok=True)
    (d / "log.jsonl").touch()
    if schema is not None:
        (d / "schema.json").write_text(json.dumps(schema, indent=2))
    return name


def topic_exists(name: str) -> bool:
    return (_topic_dir(name) / "log.jsonl").exists()


def list_topics() -> list[str]:
    return sorted(d.name for d in _topics_root().iterdir() if d.is_dir())


def get_schema(topic: str) -> dict[str, Any] | None:
    """Schema-registry lookup (reference: ``kafka.get_schema(topic)``)."""
    p = _topic_dir(topic) / "schema.json"
    return json.loads(p.read_text()) if p.exists() else None


def get_broker_endpoints() -> str:
    """Reference-parity discovery (``kafka.get_broker_endpoints``): the
    'broker' is the topics root on the shared filesystem."""
    return str(_topics_root())


def get_security_protocol() -> str:
    return "FS"  # filesystem-backed; TLS applies at the mount, not here


class Producer:
    def __init__(self, topic: str):
        if not topic_exists(topic):
            create_topic(topic)
        self._path = _topic_dir(topic) / "log.jsonl"

    def send(self, value: Any, key: str | None = None) -> None:
        from hops_tpu.runtime import faultinject

        rec = {"ts": time.time(), "key": key, "value": value}
        line = (json.dumps(rec, default=str) + "\n").encode()
        # Chaos point: raise/delay a publish, or corrupt the encoded
        # record (consumers must survive an unparsable line).
        line = faultinject.fire_data("pubsub.publish", line)
        with _lock:
            with self._path.open("ab") as f:
                f.write(line)

    def flush(self) -> None:
        pass  # every send is durable


class Consumer:
    """Tailing consumer with a durable per-group offset.

    A committed group offset always resumes (that is what makes the
    group durable); ``from_beginning`` only chooses where a group
    WITHOUT a commit starts — byte 0 (catch up on history) or the
    current end (new records only). Kafka's ``auto.offset.reset``
    contract: a restarted write-through materializer must not replay
    the whole topic just because it was constructed replay-capable.
    """

    def __init__(self, topic: str, group: str = "default", from_beginning: bool = False):
        if not topic_exists(topic):
            create_topic(topic)
        self._log = _topic_dir(topic) / "log.jsonl"
        self._offset_file = _topic_dir(topic) / f"offset.{group}"
        if self._offset_file.exists():
            self._offset = int(self._offset_file.read_text() or 0)
        else:
            self._offset = 0 if from_beginning else self._current_end()

    def _current_end(self) -> int:
        return self._log.stat().st_size

    @property
    def offset(self) -> int:
        """Byte offset into the topic log; settable for external
        checkpointing (the streaming runner's checkpointLocation)."""
        return self._offset

    @offset.setter
    def offset(self, value: int) -> None:
        self._offset = int(value)

    def end_offset(self) -> int:
        """Current end of the topic log (bytes)."""
        return self._current_end()

    def lag(self) -> int:
        """Bytes between this group's offset and the topic end — 0 when
        caught up. The watermark check write-through materializers and
        streaming runners gate their drain on."""
        return max(0, self._current_end() - self._offset)

    def poll(self, max_records: int | None = None) -> list[dict[str, Any]]:
        with self._log.open("rb") as f:
            f.seek(self._offset)
            out = []
            for line in f:
                if not line.endswith(b"\n"):
                    break  # partial write in flight; retry next poll
                self._offset += len(line)
                try:
                    out.append(json.loads(line))
                except ValueError:
                    # A corrupt record must not wedge the consumer at
                    # this offset forever: skip it, keep tailing.
                    log.warning("topic %s: skipping unparsable record at "
                                "offset %d", self._log.parent.name,
                                self._offset - len(line))
                    continue
                if max_records is not None and len(out) >= max_records:
                    break
        return out

    def commit(self) -> None:
        self._offset_file.write_text(str(self._offset))

    def __iter__(self) -> Iterator[dict[str, Any]]:
        while True:
            batch = self.poll()
            if not batch:
                return
            yield from batch
