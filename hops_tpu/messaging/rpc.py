"""Minimal JSON-line RPC over TCP — the framework's host control plane.

Replaces the Spark driver<->executor RPC channel the reference's maggy
driver used for trial dispatch/heartbeats (SURVEY.md §2.4, §3.3). One
driver-side :class:`RpcServer` with named handlers; executors (threads,
subprocesses, or other hosts) connect with :class:`RpcClient`. Wire
format: one JSON object per line, ``{"method": str, "kwargs": {...}}``
-> ``{"ok": bool, "result"|"error": ...}``.
"""

from __future__ import annotations

import json
import socket
import socketserver
import threading
from typing import Any, Callable

from hops_tpu.runtime.logging import get_logger

log = get_logger(__name__)


class RpcServer:
    """Threaded JSON-line RPC server bound to an ephemeral local port."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        handlers: dict[str, Callable[..., Any]] = {}
        self._handlers = handlers

        class Handler(socketserver.StreamRequestHandler):
            def handle(self) -> None:
                for line in self.rfile:
                    try:
                        msg = json.loads(line)
                        fn = handlers[msg["method"]]
                        result = fn(**msg.get("kwargs", {}))
                        reply = {"ok": True, "result": result}
                    except Exception as e:  # noqa: BLE001 — reply, don't kill the server
                        reply = {"ok": False, "error": f"{type(e).__name__}: {e}"}
                    self.wfile.write((json.dumps(reply) + "\n").encode())
                    self.wfile.flush()

        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = Server((host, port), Handler)
        self._thread = threading.Thread(target=self._server.serve_forever, daemon=True)

    def register(self, method: str, fn: Callable[..., Any]) -> None:
        self._handlers[method] = fn

    def start(self) -> "RpcServer":
        self._thread.start()
        return self

    @property
    def address(self) -> tuple[str, int]:
        return self._server.server_address

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()


class RpcClient:
    """Blocking JSON-line RPC client; one socket per client, thread-safe."""

    def __init__(self, address: tuple[str, int], timeout: float = 10.0):
        self._sock = socket.create_connection(address, timeout=timeout)
        self._file = self._sock.makefile("rwb")
        self._lock = threading.Lock()

    def call(self, method: str, **kwargs: Any) -> Any:
        payload = (json.dumps({"method": method, "kwargs": kwargs}) + "\n").encode()
        with self._lock:
            self._file.write(payload)
            self._file.flush()
            line = self._file.readline()
        if not line:
            raise ConnectionError("rpc server closed connection")
        reply = json.loads(line)
        if not reply["ok"]:
            raise RuntimeError(f"rpc {method} failed: {reply['error']}")
        return reply["result"]

    def close(self) -> None:
        try:
            self._file.close()
        finally:
            self._sock.close()
