"""Parameter-sharding rules: tensor-parallel / FSDP via GSPMD annotations.

The reference never sharded a model (SURVEY.md §2.9 row 5) — on TPU it
is nearly free: annotate parameter shardings over a ``model`` (TP) or
``fsdp`` axis and XLA GSPMD partitions the matmuls and inserts the
collectives. These helpers infer a reasonable sharding tree for any
flax param pytree, used by ``ShardedStrategy`` and the multichip dryrun.
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def infer_param_spec(
    params: Any,
    axis: str = "model",
    axis_size: int | None = None,
    min_size: int = 4096,
) -> Any:
    """PartitionSpec tree: shard each large >=2-D param on the dimension
    that (a) is divisible by the axis size and (b) is largest — the
    Megatron-style column/row split chosen mechanically. Small params
    (biases, norms) stay replicated: their AllReduce cost would dwarf
    the memory win."""

    def spec_for(p: Any) -> P:
        shape = np.shape(p)
        if len(shape) < 2 or np.prod(shape) < min_size:
            return P()
        if axis_size is not None:
            candidates = [d for d in range(len(shape)) if shape[d] % axis_size == 0]
        else:
            candidates = list(range(len(shape)))
        if not candidates:
            return P()
        dim = max(candidates, key=lambda d: shape[d])
        spec = [None] * len(shape)
        spec[dim] = axis
        return P(*spec)

    return jax.tree.map(spec_for, params)


def shard_params(mesh: Mesh, params: Any, axis: str = "model", min_size: int = 4096) -> Any:
    """Place ``params`` onto ``mesh`` with inferred TP shardings."""
    spec = infer_param_spec(params, axis, mesh.shape[axis], min_size)
    return jax.tree.map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)), params, spec
    )


def sharding_tree(mesh: Mesh, spec_tree: Any) -> Any:
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        spec_tree,
        is_leaf=lambda x: isinstance(x, P),
    )
