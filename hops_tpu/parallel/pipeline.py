"""Pipeline parallelism: GPipe-style microbatched stages over a ``stage``
mesh axis.

Rounds out the parallelism families (dp/tp/fsdp/sp/ep elsewhere; the
reference itself shards nothing — SURVEY.md §2.9 row 5). TPU-idiomatic
formulation: identical-shaped stages hold their params sharded
``P("stage")`` on the leading stack dim; inside one ``shard_map`` the
schedule is a single ``fori_loop`` where every device applies its stage
to the activation it currently holds and passes the result one hop down
the ring (``ppermute`` — neighbor traffic on ICI). With M microbatches
and S stages the loop runs M+S-1 ticks (the classic GPipe bubble);
gradients flow through ``ppermute``/``psum`` so ``jax.grad`` works
unchanged.

Best for models whose blocks repeat (TransformerLM's ``Block`` stack);
for a handful of chips prefer dp+tp — pp pays off when the param tree
exceeds per-chip HBM across many hosts.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def stack_stage_params(per_stage_params: list[Any]) -> Any:
    """Stack S same-structure param trees along a new leading stage dim."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stacked_params: Any,
    x: jax.Array,
    mesh: Mesh,
    *,
    axis: str = "stage",
    num_microbatches: int | None = None,
) -> jax.Array:
    """Run ``x`` through S pipelined stages; returns the final activations.

    ``stage_fn(params_s, h) -> h`` must preserve ``h``'s shape (a
    residual-block stack). ``stacked_params`` leaves have leading dim S
    and are consumed sharded ``P(axis)``; ``x`` is ``(batch, ...)``,
    replicated over the stage axis, split into ``num_microbatches``
    (default S) equal microbatches.
    """
    n_stages = mesh.shape[axis]
    m = num_microbatches or n_stages
    batch = x.shape[0]
    if batch % m:
        raise ValueError(f"batch {batch} not divisible by {m} microbatches")

    def local_fn(params, x):
        # params leaves arrive as (1, ...) slices of the stage stack.
        from hops_tpu.parallel.mesh import pvary as _pvary

        params = jax.tree.map(lambda p: p[0], params)
        s = jax.lax.axis_index(axis)
        micro = x.reshape(m, batch // m, *x.shape[1:])
        # Carries start as broadcast constants; mark them device-varying
        # on the stage axis so the fori_loop carry types stay stable.
        buf = _pvary(jnp.zeros_like(micro[0]), (axis,))
        outputs = _pvary(jnp.zeros_like(micro), (axis,))

        def tick(t, carry):
            buf, outputs = carry
            # Stage 0 ingests microbatch t (while t < m); later stages
            # consume what the previous tick's ppermute delivered.
            feed = micro[jnp.clip(t, 0, m - 1)]
            h_in = jnp.where(s == 0, feed, buf)
            h_out = stage_fn(params, h_in)
            # The last stage emits microbatch t-(S-1) once the pipe fills.
            out_idx = t - (n_stages - 1)
            emit = (s == n_stages - 1) & (out_idx >= 0)
            written = outputs.at[jnp.clip(out_idx, 0, m - 1)].set(h_out)
            outputs = jnp.where(emit, written, outputs)
            # Hand activations one stage down the ring.
            buf = jax.lax.ppermute(
                h_out, axis, [(i, i + 1) for i in range(n_stages - 1)]
            )
            return buf, outputs

        _, outputs = jax.lax.fori_loop(0, m + n_stages - 1, tick, (buf, outputs))
        # Only the last stage holds real outputs; broadcast to all so the
        # caller sees a replicated result (loss runs everywhere, SPMD).
        outputs = jax.lax.psum(
            jnp.where(s == n_stages - 1, outputs, jnp.zeros_like(outputs)), axis
        )
        return outputs.reshape(batch, *x.shape[1:])

    return shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(P(axis), P()),
        out_specs=P(),
    )(stacked_params, x)
