"""Pipeline parallelism: GPipe-style microbatched stages over a ``stage``
mesh axis.

Rounds out the parallelism families (dp/tp/fsdp/sp/ep elsewhere; the
reference itself shards nothing — SURVEY.md §2.9 row 5). TPU-idiomatic
formulation: identical-shaped stages hold their params sharded
``P("stage")`` on the leading stack dim; inside one ``shard_map`` the
schedule is a single ``fori_loop`` where every device applies its stage
to the activation it currently holds and passes the result one hop down
the ring (``ppermute`` — neighbor traffic on ICI). With M microbatches
and S stages the loop runs M+S-1 ticks (the classic GPipe bubble);
gradients flow through ``ppermute``/``psum`` so ``jax.grad`` works
unchanged.

Best for models whose blocks repeat (TransformerLM's ``Block`` stack);
for a handful of chips prefer dp+tp — pp pays off when the param tree
exceeds per-chip HBM across many hosts.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from hops_tpu.parallel.mesh import pvary as _pvary


def stack_stage_params(per_stage_params: list[Any]) -> Any:
    """Stack S same-structure param trees along a new leading stage dim."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)


def chunk_stage_params(per_layer_params: list[Any], n_stages: int) -> Any:
    """Split L same-structure layer trees into S stage chunks of K=L/S
    layers; leaves come out ``(S, K, ...)`` — stage-sharded outside,
    scanned inside the stage."""
    n_layers = len(per_layer_params)
    if n_layers % n_stages:
        raise ValueError(f"{n_layers} layers not divisible by {n_stages} stages")
    k = n_layers // n_stages
    return stack_stage_params(
        [
            jax.tree.map(lambda *xs: jnp.stack(xs), *per_layer_params[s * k : (s + 1) * k])
            for s in range(n_stages)
        ]
    )


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stacked_params: Any,
    x: jax.Array,
    mesh: Mesh,
    *,
    axis: str = "stage",
    num_microbatches: int | None = None,
    ingest_fn: Callable[[Any, jax.Array], jax.Array] | None = None,
    ingest_params: Any = None,
    emit_fn: Callable[[Any, jax.Array], jax.Array] | None = None,
    emit_params: Any = None,
    stage_aux: bool = False,
    x_spec: P | None = None,
    out_spec: P | None = None,
    param_specs: Any = None,
    extra_vary: tuple[str, ...] = (),
) -> jax.Array | tuple[jax.Array, jax.Array]:
    """Run ``x`` through S pipelined stages; returns the final outputs.

    ``stage_aux=True`` changes the stage contract to
    ``stage_fn(params_s, h) -> (h, aux_scalar)`` and returns
    ``(outputs, aux)`` where ``aux`` is the mean over microbatches of
    the per-stage scalars, summed across stages (``psum``) — how
    sown per-layer losses (MoE load balancing) ride the ring.
    Fill/drain ticks (where a stage holds no real microbatch) are
    masked out of the accumulation.

    ``stage_fn(params_s, h) -> h`` must preserve ``h``'s shape (a
    residual-block stack). ``stacked_params`` leaves have leading dim S
    and are consumed sharded ``P(axis)``; ``x`` is ``(batch, ...)``,
    replicated over the stage axis, split into ``num_microbatches``
    (default S) equal microbatches.

    Inner mesh axes compose through four knobs (used by
    ``pipelined_lm_apply`` for sp/ep inside pp): ``x_spec``/``out_spec``
    shard the input/output over an inner axis (e.g. ``P(None, "seq")``),
    ``param_specs`` optionally shards stage-param leaves beyond
    ``P(axis)`` (e.g. expert stacks over ``"expert"``), and
    ``extra_vary`` names inner axes the carried activations are
    device-varying over (sequence shards vary; an ep stage's psum'd
    activations do not). The stage_fn must then use named-axis
    collectives for the inner axis (``ring_attention_local``,
    ``MoEMLP(expert_axis=...)``).

    Heterogeneous models (embed → blocks → head) hang their non-shape-
    preserving ends on the ring boundary:

    - ``ingest_fn(ingest_params, micro) -> h`` maps a raw microbatch
      (any shape/dtype, e.g. int token ids) to the uniform carried
      activation before stage 0's body;
    - ``emit_fn(emit_params, outputs) -> y`` maps the collected
      activations to the final output (e.g. logits) after the loop.

    Both run replicated: ingest is cheap (an embed gather), and emit
    runs ONCE over the full batch after the loop rather than per tick —
    so the head matmul costs one replicated pass, not S copies. Their
    params replicate over ``axis`` (the memory that pp exists to shard —
    the L-block stack — stays stage-sharded; a vocab-huge embed/head
    should be Megatron-split on an orthogonal ``model`` axis instead).
    """
    n_stages = mesh.shape[axis]
    m = num_microbatches or n_stages
    batch = x.shape[0]
    # x_spec may shard the batch dim (dp outside pp): each data
    # coordinate runs its own m-microbatch ring over its local shard.
    batch_axes: tuple[str, ...] = ()
    if x_spec is not None and len(x_spec) and x_spec[0] is not None:
        batch_axes = x_spec[0] if isinstance(x_spec[0], tuple) else (x_spec[0],)
    n_data = 1
    for name in batch_axes:
        n_data *= mesh.shape[name]
    if batch % (m * n_data):
        raise ValueError(
            f"batch {batch} not divisible by {m} microbatches x {n_data} "
            f"batch shards"
        )
    ingest = ingest_fn or (lambda _, v: v)
    has_params = (ingest_params is not None, emit_params is not None)

    def local_fn(params, ingest_p, emit_p, x):
        # params leaves arrive as (1, ...) slices of the stage stack.
        params = jax.tree.map(lambda p: p[0], params)
        s = jax.lax.axis_index(axis)
        # Under a data-sharded x_spec this is the LOCAL batch shard;
        # each data coordinate runs its own m-microbatch ring.
        lb = x.shape[0]
        micro = x.reshape(m, lb // m, *x.shape[1:])
        # Carries start as broadcast constants; mark them device-varying
        # on the stage axis so the fori_loop carry types stay stable.
        h0 = ingest(ingest_p, micro[0])
        vary = (axis,) + extra_vary
        buf = _pvary(jnp.zeros_like(h0), vary)
        outputs = _pvary(jnp.zeros((m,) + h0.shape, h0.dtype), vary)
        # Per-stage aux derives from data-sharded activations under dp,
        # so its carry must vary over the batch axes too.
        aux_sum = _pvary(jnp.zeros((), jnp.float32), (axis,) + batch_axes)

        def tick(t, carry):
            buf, outputs, aux_sum = carry
            # Stage 0 ingests microbatch t (while t < m); later stages
            # consume what the previous tick's ppermute delivered.
            feed = ingest(ingest_p, micro[jnp.clip(t, 0, m - 1)])
            h_in = jnp.where(s == 0, feed, buf)
            if stage_aux:
                h_out, aux_t = stage_fn(params, h_in)
                # Stage s holds real microbatch t-s only for 0 <= t-s < m;
                # fill/drain ticks run on garbage and must not count.
                valid = (t - s >= 0) & (t - s < m)
                aux_sum = aux_sum + jnp.where(valid, aux_t.astype(jnp.float32), 0.0)
            else:
                h_out = stage_fn(params, h_in)
            # The last stage emits microbatch t-(S-1) once the pipe fills.
            out_idx = t - (n_stages - 1)
            emit = (s == n_stages - 1) & (out_idx >= 0)
            written = outputs.at[jnp.clip(out_idx, 0, m - 1)].set(h_out)
            outputs = jnp.where(emit, written, outputs)
            # Hand activations one stage down the ring.
            buf = jax.lax.ppermute(
                h_out, axis, [(i, i + 1) for i in range(n_stages - 1)]
            )
            return buf, outputs, aux_sum

        _, outputs, aux_sum = jax.lax.fori_loop(
            0, m + n_stages - 1, tick, (buf, outputs, aux_sum)
        )
        # Only the last stage holds real outputs; broadcast to all so the
        # caller sees a replicated result (loss runs everywhere, SPMD).
        outputs = jax.lax.psum(
            jnp.where(s == n_stages - 1, outputs, jnp.zeros_like(outputs)), axis
        )
        outputs = outputs.reshape(lb, *h0.shape[1:])
        out = emit_fn(emit_p, outputs) if emit_fn else outputs
        if stage_aux:
            # Sum over stages; under dp also average the per-data-shard
            # aux (it's a mean-style loss) so the scalar comes back
            # replicated everywhere.
            aux = jax.lax.psum(aux_sum, axis) / m
            if batch_axes:
                aux = jax.lax.psum(aux, batch_axes) / n_data
            return out, aux
        return out

    if param_specs is None:
        param_specs = jax.tree.map(lambda _: P(axis), stacked_params)
    main_out = out_spec if out_spec is not None else P()
    return shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(
            param_specs,
            P() if has_params[0] else None,
            P() if has_params[1] else None,
            x_spec if x_spec is not None else P(),
        ),
        out_specs=(main_out, P()) if stage_aux else main_out,
    )(stacked_params, ingest_params, emit_params, x)


def pipelined_lm_apply(
    model: Any,
    params: Any,
    tokens: jax.Array,
    mesh: Mesh,
    *,
    axis: str = "stage",
    num_microbatches: int | None = None,
    return_aux: bool = False,
    seq_axis: str | None = None,
    expert_axis: str | None = None,
    batch_axis: str | None = None,
    tp_axis: str | None = None,
) -> jax.Array | tuple[jax.Array, jax.Array]:
    """Run a ``TransformerLM`` forward through the GPipe ring.

    Heterogeneous stage signatures via the ring-boundary hooks: embed is
    the ingest transform, final-norm + unembed the emit transform, and
    the L blocks split into S stage chunks of K=L/S layers (leaves
    ``(S, K, ...)`` — stage-sharded outside, ``lax.scan`` inside).
    Logits match ``model.apply`` exactly (tests/test_pipeline.py).

    MoE models (``moe_every > 0``) pipeline too: layers chunk into
    uniform (moe_every-1 dense + 1 MoE) groups. Semantic notes: MoE
    routing (expert capacity, token drops) is computed per microbatch —
    the batch a stage sees IS the microbatch, as in any GPipe x MoE
    system — so whole-batch parity is exact only for drop-free routing.

    Inner parallelism composes (round 3):

    - ``seq_axis``: sequence parallelism INSIDE each pipeline stage —
      tokens/logits shard ``P(None, seq_axis)`` and attention runs the
      ring-attention body over that axis (``ring_attention_local``),
      so pp bounds layer memory while sp bounds activation memory for
      long sequences. Dense models only (MoE routing under a sharded
      sequence would change drop semantics — use ``expert_axis``).
    - ``expert_axis``: expert parallelism INSIDE each pipeline stage —
      ``w_in``/``w_out`` stacks shard over the axis, each device runs
      its local experts and a per-layer ``psum`` combines
      (``MoEMLP(expert_axis=...)``); routing/capacity math is
      unchanged, so logits still match the dense apply exactly.
    - ``tp_axis``: Megatron tensor parallelism INSIDE each pipeline
      stage — qkv/gate/up kernels column-shard (local heads / local
      hidden columns), out/down kernels row-shard, and one psum per
      projection combines the partials (``Attention``/``MLP``
      ``tp_axis``/``tp_shards``). Dense models only for now.
    - ``batch_axis``: data parallelism OUTSIDE the ring — tokens and
      logits shard ``P(batch_axis, ...)`` and every data coordinate
      runs its own microbatch ring; gradient summation over the data
      axis falls out of shard_map's transpose of the replicated
      params. Composes with either inner axis (dp x pp x sp/ep).

    ``return_aux=True`` returns ``(logits, aux)`` where ``aux`` is the
    sown load-balancing loss accumulated through the ring (mean over
    microbatches, summed over layers/stages) — feed it into the train
    loss exactly like ``make_lm_train_step`` does for the dense path.
    """
    from hops_tpu.models.moe import MoEBlock, sum_sown_losses
    from hops_tpu.models.transformer import Block, RMSNorm
    from flax import linen as nn

    if seq_axis and model.moe_every:
        raise NotImplementedError(
            "seq_axis inside pp is supported for dense LMs; MoE models "
            "compose pp with expert_axis instead (per-microbatch routing "
            "over a sharded sequence would change drop semantics)"
        )
    if expert_axis and not model.moe_every:
        raise ValueError("expert_axis requires a MoE model (moe_every > 0)")
    if tp_axis and model.moe_every:
        raise NotImplementedError(
            "tp_axis inside pp is supported for dense LMs; MoE models "
            "compose pp with expert_axis instead"
        )

    n_stages = mesh.shape[axis]
    block = Block(
        model.num_heads,
        dtype=model.dtype,
        attention_impl="ring_local" if seq_axis else model.attention_impl,
        mesh=mesh if seq_axis else None,
        seq_axis=seq_axis or "seq",
        batch_axis=batch_axis,
        dropout_rate=0.0,
        tp_axis=tp_axis,
        tp_shards=mesh.shape[tp_axis] if tp_axis else 1,
        num_kv_heads=model.num_kv_heads,
        kv_cache_dtype=model.kv_cache_dtype,
        window=model.window,
    )
    embed = nn.Embed(model.vocab_size, model.d_model, dtype=model.dtype)
    norm = RMSNorm(dtype=model.dtype)
    unembed = nn.Dense(model.vocab_size, dtype=model.dtype, use_bias=False)

    if model.moe_every:
        # MoE layers sit at positions g-1, 2g-1, ... (g = moe_every), so
        # g consecutive layers form a uniform group tree of (g-1 dense +
        # 1 MoE) params: groups stack/scan exactly like layers do in the
        # dense path. Router/expert shapes repeat per MoE layer, so the
        # group trees all share structure. Load-balancing aux losses are
        # collected per group via mutable apply and accumulated through
        # the ring (stage_aux); return_aux exposes them to the caller.
        g = model.moe_every
        if model.num_layers % g:
            raise ValueError(
                f"{model.num_layers} layers not divisible by moe_every={g}")
        moe_block = MoEBlock(
            model.num_heads,
            num_experts=model.num_experts,
            top_k=model.moe_top_k,
            dtype=model.dtype,
            attention_impl=model.attention_impl,
            mesh=None,
            dropout_rate=0.0,
            expert_axis=expert_axis,
            expert_shards=mesh.shape[expert_axis] if expert_axis else 1,
            num_kv_heads=model.num_kv_heads,
            kv_cache_dtype=model.kv_cache_dtype,
            window=model.window,
        )
        groups = []
        for start in range(0, model.num_layers, g):
            group = {"moe": params[f"block_{start + g - 1}"]}
            if g > 1:
                group["dense"] = jax.tree.map(
                    lambda *xs: jnp.stack(xs),
                    *[params[f"block_{i}"] for i in range(start, start + g - 1)],
                )
            groups.append(group)
        stacked = chunk_stage_params(groups, n_stages)

        def stage_fn(stage_params, h):
            def group_body(carry, gp):
                h, aux = carry
                if g > 1:
                    def dense_body(h, lp):
                        return block.apply({"params": lp}, h), None

                    h, _ = jax.lax.scan(dense_body, h, gp["dense"])
                h, mods = moe_block.apply(
                    {"params": gp["moe"]}, h, mutable=["losses"]
                )
                aux = aux + sum_sown_losses(mods)
                return (h, aux), None

            # Under dp the sown aux derives from data-sharded
            # activations — seed the scan carry varying over that axis
            # too or the carry types won't match.
            aux0 = _pvary(jnp.zeros((), jnp.float32), (axis, batch_axis))
            (h, aux), _ = jax.lax.scan(group_body, (h, aux0), stage_params)
            return h, aux

    else:
        stacked = chunk_stage_params(
            [params[f"block_{i}"] for i in range(model.num_layers)], n_stages
        )

        def stage_fn(stage_params, h):
            def body(h, layer_params):
                return block.apply({"params": layer_params}, h), None

            h, _ = jax.lax.scan(body, h, stage_params)
            return h, _pvary(jnp.zeros((), jnp.float32), (axis, batch_axis))

    def ingest_fn(p, micro_tokens):
        return embed.apply({"params": p}, micro_tokens)

    def emit_fn(p, h):
        logits = unembed.apply(
            {"params": p["unembed"]}, norm.apply({"params": p["final_norm"]}, h)
        )
        return logits.astype(jnp.float32)

    param_specs = None
    if tp_axis:
        # Megatron leaf shardings on top of the stage dim. Stacked
        # leaves are (S, K, *param.shape): qkv (S,K,dm,3,H,hd) shards
        # heads; attn-out (S,K,dm,dm) and mlp-down (S,K,hidden,dm)
        # shard input rows; gate/up (S,K,dm,hidden) shard output
        # columns. Everything else stays stage-sharded (replicated
        # over tp).
        from hops_tpu.parallel.tp_inference import tp_leaf_partition

        def tp_leaf_spec(path, _):
            names = [str(k.key) for k in path if hasattr(k, "key")]
            part = tp_leaf_partition(names, tp_axis)
            # Stacked leaves are (S, K, *param.shape): prepend the
            # stage and layer dims to the shared per-param partition.
            return P(axis, None, *part) if part else P(axis)

        param_specs = jax.tree_util.tree_map_with_path(tp_leaf_spec, stacked)
    if expert_axis:
        # Expert stacks shard over the inner axis on top of the stage
        # dim: (S, K, E, dm, hidden) -> P(stage, None, expert). All
        # other stage params stay stage-sharded only (replicated over
        # the expert axis).
        def leaf_spec(path, _):
            name = str(path[-1].key) if hasattr(path[-1], "key") else ""
            if name in ("w_in", "w_out"):
                return P(axis, None, expert_axis)
            return P(axis)

        param_specs = jax.tree_util.tree_map_with_path(leaf_spec, stacked)

    logits, aux = pipeline_apply(
        stage_fn,
        stacked,
        tokens,
        mesh,
        axis=axis,
        num_microbatches=num_microbatches,
        ingest_fn=ingest_fn,
        ingest_params=params["embed"],
        emit_fn=emit_fn,
        emit_params={"final_norm": params["final_norm"], "unembed": params["unembed"]},
        stage_aux=True,
        x_spec=P(batch_axis, seq_axis) if (seq_axis or batch_axis) else None,
        out_spec=P(batch_axis, seq_axis) if (seq_axis or batch_axis) else None,
        param_specs=param_specs,
        extra_vary=tuple(a for a in (batch_axis, seq_axis) if a),
    )
    return (logits, aux) if return_aux else logits


# -- explicit schedules: gpipe / 1F1B / interleaved ---------------------------


def _scheduled_lm_loss_and_grads(
    model: Any,
    mesh: Mesh,
    axis: str,
    sched: Any,
) -> Callable[[Any, jax.Array, jax.Array], tuple[jax.Array, Any]]:
    """Build the explicit tick-program forward/backward for a dense
    ``TransformerLM`` under a :class:`~hops_tpu.parallel.pp_schedule.
    PipelineSchedule`: per tick each device runs (at most) one stage
    forward and one stage backward-VJP, activations/cotangents hop the
    rotated ring, the last virtual stage computes the per-microbatch
    loss + cotangent seed the moment a microbatch's forward finishes,
    and per-chunk param grads accumulate microbatch-ascending — the
    accumulation-order invariant that makes every schedule's gradients
    bit-identical. Returns ``fn(params, inputs, targets) -> (loss,
    grads)`` with ``grads`` shaped like the dense param tree.
    """
    import optax
    from flax import linen as nn

    from hops_tpu.models.transformer import Block, RMSNorm

    S, v, V, m = sched.n_stages, sched.v, sched.n_virtual, sched.num_microbatches
    if model.moe_every:
        raise NotImplementedError(
            "explicit pipeline schedules support dense TransformerLMs; "
            "MoE pipelines use the autodiff ring (schedule=None)")
    if model.num_layers % V:
        raise ValueError(
            f"{model.num_layers} layers not divisible by {V} virtual "
            f"stages ({S} stages x {v} chunks)")
    K = model.num_layers // V

    block = Block(
        model.num_heads, dtype=model.dtype,
        attention_impl=model.attention_impl, dropout_rate=0.0,
        num_kv_heads=model.num_kv_heads,
        kv_cache_dtype=model.kv_cache_dtype, window=model.window,
    )
    embed = nn.Embed(model.vocab_size, model.d_model, dtype=model.dtype)
    norm = RMSNorm(dtype=model.dtype)
    unembed = nn.Dense(model.vocab_size, dtype=model.dtype, use_bias=False)

    def stage_fn(stage_params, h):
        def body(h, layer_params):
            return block.apply({"params": layer_params}, h), None

        h, _ = jax.lax.scan(body, h, stage_params)
        return h

    def emit_loss(emit_p, h, tgt):
        logits = unembed.apply(
            {"params": emit_p["unembed"]},
            norm.apply({"params": emit_p["final_norm"]}, h),
        ).astype(jnp.float32)
        return optax.softmax_cross_entropy_with_integer_labels(logits, tgt).mean()

    # Static per-tick tables, uploaded once.
    jf_c, jf_m = jnp.asarray(sched.f_chunk), jnp.asarray(sched.f_mb)
    jb_c, jb_m = jnp.asarray(sched.b_chunk), jnp.asarray(sched.b_mb)
    jif_c, jif_m = jnp.asarray(sched.in_f_chunk), jnp.asarray(sched.in_f_mb)
    jib_c, jib_m = jnp.asarray(sched.in_b_chunk), jnp.asarray(sched.in_b_mb)
    fwd_perm = [(i, (i + 1) % S) for i in range(S)]
    bwd_perm = [(i, (i - 1) % S) for i in range(S)]

    def local_fn(stacked, embed_p, emit_p, tokens, targets):
        params = jax.tree.map(lambda p: p[0], stacked)  # (v, K, ...)
        s = jax.lax.axis_index(axis)
        b, t_len = tokens.shape
        mb_b = b // m
        emb_all = embed.apply({"params": embed_p}, tokens)
        d_model = emb_all.shape[-1]
        micro_h = emb_all.reshape(m, mb_b, t_len, d_model)
        micro_tok = tokens.reshape(m, mb_b, t_len)
        micro_tgt = targets.reshape(m, mb_b, t_len)

        # Virtual stage 0's inputs are pre-seeded; everything else
        # arrives over the ring and is stored as it lands.
        base = jnp.zeros((v, m, mb_b, t_len, d_model), emb_all.dtype)
        acts = jnp.where(s == 0, base.at[0].set(micro_h), base)
        cts = _pvary(jnp.zeros_like(base), (axis,))
        gacc = jax.tree.map(
            lambda p: _pvary(jnp.zeros_like(p), (axis,)), params)
        emb_gacc = jax.tree.map(
            lambda p: _pvary(jnp.zeros_like(p), (axis,)), embed_p)
        emit_gacc = jax.tree.map(
            lambda p: _pvary(jnp.zeros_like(p), (axis,)), emit_p)
        loss_acc = _pvary(jnp.zeros((), jnp.float32), (axis,))
        fwd_in = bwd_in = None

        def put(buf, val, c, mb):
            return jax.lax.dynamic_update_slice(
                buf, val[None, None].astype(buf.dtype),
                (c, mb, 0, 0, 0))

        for t in range(sched.ticks):
            # 1. integrate what last tick's ring hop delivered
            if fwd_in is not None and (sched.in_f_chunk[t] >= 0).any():
                ic, im = jif_c[t][s], jif_m[t][s]
                stored = put(acts, fwd_in, jnp.clip(ic, 0, v - 1),
                             jnp.clip(im, 0, m - 1))
                acts = jnp.where(ic >= 0, stored, acts)
            if bwd_in is not None and (sched.in_b_chunk[t] >= 0).any():
                ic, im = jib_c[t][s], jib_m[t][s]
                stored = put(cts, bwd_in, jnp.clip(ic, 0, v - 1),
                             jnp.clip(im, 0, m - 1))
                cts = jnp.where(ic >= 0, stored, cts)

            # 2. forward slot
            if (sched.f_chunk[t] >= 0).any():
                fc = jnp.clip(jf_c[t][s], 0, v - 1)
                fm = jnp.clip(jf_m[t][s], 0, m - 1)
                fvalid = jf_c[t][s] >= 0
                h_in = acts[fc, fm]
                params_c = jax.tree.map(lambda p: p[fc], params)
                h_out = stage_fn(params_c, h_in)
                # Only the last virtual stage can emit this tick, and
                # that is statically known from the table.
                if sched.f_chunk[t][S - 1] == v - 1:
                    is_last = fvalid & (s == S - 1) & (jf_c[t][s] == v - 1)
                    tgt = micro_tgt[fm]
                    loss_mb, evjp = jax.vjp(
                        lambda ep, h: emit_loss(ep, h, tgt), emit_p, h_out)
                    d_ep, d_h = evjp(jnp.asarray(1.0 / m, jnp.float32))
                    loss_acc = loss_acc + jnp.where(
                        is_last, loss_mb / m, 0.0)
                    emit_gacc = jax.tree.map(
                        lambda a, d: a + jnp.where(is_last, d, 0.0),
                        emit_gacc, d_ep)
                    cts = jnp.where(is_last, put(cts, d_h, fc, fm), cts)
                fwd_msg = h_out
            else:
                fwd_msg = None

            # 3. backward slot
            if (sched.b_chunk[t] >= 0).any():
                bc = jnp.clip(jb_c[t][s], 0, v - 1)
                bm = jnp.clip(jb_m[t][s], 0, m - 1)
                bvalid = jb_c[t][s] >= 0
                g_in = cts[bc, bm]
                h_saved = acts[bc, bm]
                params_b = jax.tree.map(lambda p: p[bc], params)
                _, svjp = jax.vjp(stage_fn, params_b, h_saved)
                d_p, d_hin = svjp(g_in)
                gacc = jax.tree.map(
                    lambda a, d: a.at[bc].add(
                        jnp.where(bvalid, d, jnp.zeros_like(d))),
                    gacc, d_p)
                # Virtual stage 0's input cotangent feeds the embed.
                if sched.b_chunk[t][0] == 0:
                    is_first = bvalid & (s == 0) & (jb_c[t][s] == 0)
                    tok = micro_tok[bm]
                    _, ev = jax.vjp(
                        lambda ep: embed.apply({"params": ep}, tok), embed_p)
                    (d_emb,) = ev(d_hin.astype(emb_all.dtype))
                    emb_gacc = jax.tree.map(
                        lambda a, d: a + jnp.where(is_first, d, 0.0),
                        emb_gacc, d_emb)
                bwd_msg = d_hin
            else:
                bwd_msg = None

            # 4. one ring hop each way
            fwd_in = (
                jax.lax.ppermute(fwd_msg, axis, fwd_perm)
                if fwd_msg is not None else None
            )
            bwd_in = (
                jax.lax.ppermute(bwd_msg, axis, bwd_perm)
                if bwd_msg is not None else None
            )

        loss = jax.lax.psum(loss_acc, axis)
        emb_g = jax.tree.map(lambda g: jax.lax.psum(g, axis), emb_gacc)
        emit_g = jax.tree.map(lambda g: jax.lax.psum(g, axis), emit_gacc)
        gacc = jax.tree.map(lambda g: g[None], gacc)  # (1, v, K, ...)
        return loss, gacc, emb_g, emit_g

    shard_fn = shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(P(axis), P(), P(), P(), P()),
        out_specs=(P(), P(axis), P(), P()),
        check_rep=False,
    )

    def loss_and_grads(params, inputs, targets):
        per_vs = [
            jax.tree.map(
                lambda *xs: jnp.stack(xs),
                *[params[f"block_{vs * K + k}"] for k in range(K)],
            )
            for vs in range(V)
        ]
        # Device s holds chunks j = 0..v-1 as virtual stages j*S + s.
        dev_trees = [
            jax.tree.map(
                lambda *xs: jnp.stack(xs),
                *[per_vs[j * S + s] for j in range(v)],
            )
            for s in range(S)
        ]
        stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *dev_trees)
        emit_p = {
            "final_norm": params["final_norm"], "unembed": params["unembed"]
        }
        loss, g_st, g_emb, g_emit = shard_fn(
            stacked, params["embed"], emit_p, inputs, targets)
        grads = {"embed": g_emb, "final_norm": g_emit["final_norm"],
                 "unembed": g_emit["unembed"]}
        for vs in range(V):
            dev, chunk = vs % S, vs // S
            for k in range(K):
                grads[f"block_{vs * K + k}"] = jax.tree.map(
                    lambda g, d=dev, c=chunk, kk=k: g[d, c, kk], g_st
                )
        return loss, grads

    return loss_and_grads


def make_pp_lm_train_step(
    model: Any,
    mesh: Mesh,
    *,
    axis: str = "stage",
    seq_axis: str | None = None,
    expert_axis: str | None = None,
    batch_axis: str | None = None,
    tp_axis: str | None = None,
    num_microbatches: int | None = None,
    aux_loss_weight: float = 0.01,
    schedule: str | None = None,
    virtual_stages: int | None = None,
) -> Callable[[Any, dict[str, jax.Array]], tuple[Any, dict[str, jax.Array]]]:
    """Pipelined next-token-prediction train step for a ``TransformerLM``.

    Same ``step(state, batch) -> (state, metrics)`` contract as
    ``models.transformer.make_lm_train_step`` (so the experiment
    launchers accept it unchanged), but the forward/backward runs
    through the pipeline — optionally with sp (``seq_axis``) or ep
    (``expert_axis``) composed inside the stages. Gradients flow back
    to the caller's dense param tree; the optimizer update itself runs
    on that replicated tree (stage-sharded optimizer state — true
    ZeRO-style pp memory for the update — is flat-mesh
    ``ShardedStrategy`` territory).

    ``schedule=None`` (default) differentiates through the naive
    fill-drain GPipe ring (``pipeline_apply``). ``schedule="gpipe" |
    "1f1b" | "interleaved"`` switches to the explicit tick-program
    engine (:mod:`hops_tpu.parallel.pp_schedule`): warmup/steady/
    cooldown phases are explicit, ``interleaved`` runs
    ``virtual_stages`` (default 2) chunks per device, and all three
    produce bit-identical losses AND gradients to each other (backward
    accumulation is microbatch-ascending under every policy — see
    ``tests/test_pipeline_schedule.py``). Explicit schedules support
    dense models on a pure ``stage`` mesh; compositions (sp/ep/tp/dp,
    MoE) stay on the autodiff ring. The factory registers the
    schedule's bubble fraction on
    ``hops_tpu_pp_bubble_fraction{schedule=...}``; wrap the returned
    step with :func:`instrument_pp_step` for per-microbatch wall-time
    telemetry.
    """
    import optax

    if schedule is not None:
        if seq_axis or expert_axis or batch_axis or tp_axis:
            raise NotImplementedError(
                "explicit schedules (gpipe/1f1b/interleaved) run on a "
                "pure stage mesh; inner-axis compositions use the "
                "autodiff ring (schedule=None)")
        from hops_tpu.parallel.pp_schedule import build_pp_schedule

        m = num_microbatches or mesh.shape[axis]
        sched = build_pp_schedule(
            schedule, m, mesh.shape[axis], virtual_stages)
        _register_pp_schedule_telemetry(sched)
        loss_and_grads = _scheduled_lm_loss_and_grads(model, mesh, axis, sched)

        def scheduled_train_step(state, batch):
            tokens = batch["tokens"]
            inputs, targets = tokens[:, :-1], tokens[:, 1:]
            loss, grads = loss_and_grads(state.params, inputs, targets)
            state = state.apply_gradients(grads=grads)
            return state, {"loss": loss, "perplexity": jnp.exp(loss)}

        scheduled_train_step.pp_schedule = sched
        return scheduled_train_step

    def train_step(state, batch):
        tokens = batch["tokens"]
        inputs, targets = tokens[:, :-1], tokens[:, 1:]

        def compute_loss(params):
            logits, aux = pipelined_lm_apply(
                model, params, inputs, mesh,
                axis=axis,
                num_microbatches=num_microbatches,
                return_aux=True,
                seq_axis=seq_axis,
                expert_axis=expert_axis,
                batch_axis=batch_axis,
                tp_axis=tp_axis,
            )
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, targets
            ).mean()
            return loss + aux_loss_weight * aux, loss

        (_, loss), grads = jax.value_and_grad(compute_loss, has_aux=True)(state.params)
        state = state.apply_gradients(grads=grads)
        return state, {"loss": loss, "perplexity": jnp.exp(loss)}

    return train_step


def _register_pp_schedule_telemetry(sched: Any) -> None:
    """Publish the schedule's static bubble model (host-side, factory
    time — never inside a compiled step)."""
    from hops_tpu.telemetry import REGISTRY

    REGISTRY.gauge(
        "hops_tpu_pp_bubble_fraction",
        "Idle fraction of pipeline work slots for the built schedule",
        labels=("schedule",),
    ).set(sched.bubble_fraction, schedule=sched.kind)


def instrument_pp_step(
    step_fn: Callable[..., Any], sched: Any | None = None
) -> Callable[..., Any]:
    """Wrap a (compiled) scheduled pipeline step with host-side
    per-microbatch timing: each call's wall time divided by the
    schedule's microbatch count feeds
    ``hops_tpu_pp_microbatch_seconds{schedule=...}``. Wrap OUTSIDE any
    ``jax.jit`` — this mutates telemetry."""
    import time

    from hops_tpu.telemetry import REGISTRY

    sched = sched if sched is not None else getattr(step_fn, "pp_schedule", None)
    if sched is None:
        raise ValueError(
            "instrument_pp_step needs the step's PipelineSchedule "
            "(build the step with make_pp_lm_train_step(schedule=...))")
    hist = REGISTRY.histogram(
        "hops_tpu_pp_microbatch_seconds",
        "Wall time per microbatch of a scheduled pipeline train step",
        labels=("schedule",),
        buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                 0.25, 0.5, 1.0, 2.5),
    )

    def timed(state, batch):
        t0 = time.perf_counter()
        out = jax.block_until_ready(step_fn(state, batch))
        hist.observe(
            (time.perf_counter() - t0) / sched.num_microbatches,
            schedule=sched.kind,
        )
        return out

    timed.pp_schedule = sched
    return timed
