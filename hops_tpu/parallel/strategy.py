"""Distribution strategies — the user-facing API of the parallel layer.

Mirrors the ergonomics the reference exposed through
``tf.distribute.MirroredStrategy`` / ``MultiWorkerMirroredStrategy``
inside ``experiment.mirrored`` wrapper functions (reference:
mirroredstrategy_mnist_example.ipynb:125-131,
multiworkermirroredstrategy_mnist_example.ipynb:137-141; SURVEY.md
§2.9), but lowers to pjit-style sharded ``jax.jit`` over a Mesh: params
replicated, batch sharded on the ``data`` axis, gradient AllReduce
emitted by XLA over ICI — no NCCL, no TF_CONFIG, no cluster spec.

Typical wrapper-function use::

    def train_fn():
        strategy = distribute.MirroredStrategy()
        state = strategy.replicate(create_state(...))
        step = strategy.step(train_step)        # compiled SPMD step
        for batch in data:
            state, metrics = step(state, strategy.distribute_batch(batch))
        return {"accuracy": float(metrics["accuracy"])}
"""

from __future__ import annotations

import contextlib
import math
from typing import Any, Callable, Iterator

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hops_tpu.parallel import mesh as mesh_lib

_current: list["Strategy"] = []


class Strategy:
    """Base: data-parallel SPMD over an arbitrary mesh."""

    def __init__(
        self,
        mesh: Mesh | None = None,
        data_axis: str | tuple[str, ...] = "data",
        grad_comms: "Any | None" = None,
    ):
        self.mesh = mesh if mesh is not None else mesh_lib.global_mesh()
        self.data_axis = data_axis
        #: Default ``grad_comms.GradCommsConfig`` for :meth:`step` — None
        #: keeps XLA's implicit gradient AllReduce.
        self.grad_comms = grad_comms
        # Compiled steps memoized per (fn, donate_state, config): a fresh
        # ``jax.jit`` wrapper per call would recompile every time.
        self._step_cache: dict[Any, Callable[..., Any]] = {}

    # -- introspection (reference: strategy.num_replicas_in_sync) ------------

    @property
    def num_replicas_in_sync(self) -> int:
        axes = (
            self.data_axis
            if isinstance(self.data_axis, tuple)
            else (self.data_axis,)
        )
        return math.prod(self.mesh.shape[a] for a in axes)

    @property
    def num_hosts(self) -> int:
        return jax.process_count()

    def global_batch_size(self, per_replica: int) -> int:
        """Reference pattern: ``BATCH_SIZE_PER_REPLICA * num_replicas``."""
        return per_replica * self.num_replicas_in_sync

    # -- placement ------------------------------------------------------------

    def replicate(self, tree: Any) -> Any:
        return mesh_lib.replicate(self.mesh, tree)

    def distribute_batch(self, batch: Any) -> Any:
        return mesh_lib.shard_batch(self.mesh, batch, self.data_axis)

    # -- execution ------------------------------------------------------------

    def step(
        self,
        fn: Callable[..., Any],
        donate_state: bool = True,
        grad_comms: "Any | None" = None,
    ) -> Callable[..., Any]:
        """Compile ``fn(state, batch) -> (state, aux)`` as one SPMD step:
        state replicated, batch sharded.

        Default path: XLA inserts the gradient collectives. With a
        ``grad_comms.GradCommsConfig`` (argument here or on the
        strategy), ``fn`` instead runs inside ``shard_map`` over the
        data axis and must do its own cross-replica reduction — build it
        with ``models.common.make_train_step(grad_comms=cfg)``, which
        routes gradients through the bucketed/quantized/ZeRO-1
        collectives in :mod:`hops_tpu.parallel.grad_comms`. Compiled
        steps are memoized per ``(fn, donate_state, config)`` so
        repeated :meth:`step`/:meth:`run` calls reuse the executable.
        """
        cfg = grad_comms if grad_comms is not None else self.grad_comms
        key = (fn, donate_state, cfg)
        cached = self._step_cache.get(key)
        if cached is not None:
            return cached
        donate = (0,) if donate_state else ()
        # Inside shard_map nothing syncs gradients implicitly, so a step
        # fn that was not built for explicit comms would train WITHOUT
        # cross-replica reduction and silently diverge per device (and a
        # grad-comms fn under plain jit hits unbound psum axes). The
        # ``grad_comms`` marker that make_train_step stamps on its steps
        # (copy it onto wrappers that close over one) makes both
        # mismatches loud here instead.
        marker = getattr(fn, "grad_comms", None)
        if cfg is not None:
            if marker is None:
                raise ValueError(
                    "Strategy.step(grad_comms=...) runs fn inside shard_map "
                    "with NO implicit gradient AllReduce; fn must reduce its "
                    "own gradients. Build it with models.common."
                    "make_train_step(grad_comms=cfg) (or set fn.grad_comms = "
                    "cfg on a wrapper around such a step)."
                )
            if marker != cfg:
                raise ValueError(
                    f"fn was built for grad_comms config {marker}, but the "
                    f"step was asked to run {cfg}; pass the same config to "
                    "make_train_step and Strategy.step"
                )
            from hops_tpu.parallel import grad_comms as gc

            if getattr(cfg, "update_sharding", None) == "zero3":
                # ZeRO-3 states carry per-device DIFFERENT shard leaves,
                # so the shard_map specs depend on the state's structure
                # — derived lazily from the first state seen and
                # memoized per abstract signature.
                inner_jit = self._lazy_spec_step(
                    fn, donate,
                    lambda st: gc.zero3_state_specs(st, self.data_axis),
                )
            elif getattr(cfg, "update_sharding", None) in (
                "cross_replica", "zero2",
            ):
                # ZeRO-1/2: with the persistent-sharded-moments carrier
                # (grad_comms.zero12_init) the MomentShards buffers ride
                # P(data) and stay resident; a plain replicated state
                # degenerates to the all-replicated spec — same lazy
                # per-structure derivation either way.
                inner_jit = self._lazy_spec_step(
                    fn, donate,
                    lambda st: gc.zero12_state_specs(st, self.data_axis),
                )
            else:
                from jax.experimental.shard_map import shard_map

                inner = shard_map(
                    fn,
                    mesh=self.mesh,
                    in_specs=(P(), P(self.data_axis)),
                    out_specs=(P(), P()),
                    check_rep=False,
                )
                inner_jit = jax.jit(inner, donate_argnums=donate)
            stepped = gc.instrument_step(
                inner_jit,
                cfg,
                steps_per_call=getattr(fn, "grad_comms_steps", 1),
            )
        elif marker is not None:
            raise ValueError(
                "fn was built with an explicit grad_comms config "
                f"({marker}) and reduces its own gradients inside "
                "shard_map; run it via Strategy.step(fn, grad_comms=cfg)"
            )
        else:
            rep = mesh_lib.replicated(self.mesh)
            data = NamedSharding(self.mesh, P(self.data_axis))
            stepped = jax.jit(
                fn,
                in_shardings=(rep, data),
                out_shardings=(rep, rep),
                donate_argnums=donate,
            )
        self._step_cache[key] = stepped
        return stepped

    def _lazy_spec_step(
        self,
        fn: Callable[..., Any],
        donate: tuple,
        spec_fn: Callable[[Any], Any],
    ) -> Callable[..., Any]:
        """Lazy shard_map compile for steps whose state carries
        per-device shard leaves (ZeRO-3 flat param/moment shards,
        ZeRO-1/2 persistent MomentShards buffers): the specs come from
        ``spec_fn`` on the actual state at first call and re-derive per
        state structure/shape signature."""
        from jax.experimental.shard_map import shard_map

        compiled: dict[Any, Callable[..., Any]] = {}

        def run(state, batch):
            key = (
                jax.tree.structure(state),
                tuple(jax.numpy.shape(l) for l in jax.tree.leaves(state)),
            )
            exe = compiled.get(key)
            if exe is None:
                specs = spec_fn(state)
                inner = shard_map(
                    fn,
                    mesh=self.mesh,
                    in_specs=(specs, P(self.data_axis)),
                    out_specs=(specs, P()),
                    check_rep=False,
                )
                exe = compiled[key] = jax.jit(inner, donate_argnums=donate)
            return exe(state, batch)

        return run

    def run(self, fn: Callable[..., Any], state: Any, batch: Any) -> Any:
        return self.step(fn)(state, self.distribute_batch(batch))

    # -- scope (reference: ``with strategy.scope():``) ------------------------

    @contextlib.contextmanager
    def scope(self) -> Iterator["Strategy"]:
        _current.append(self)
        try:
            yield self
        finally:
            _current.pop()


class MirroredStrategy(Strategy):
    """Data parallelism over the chips of ONE host (reference:
    single-host ``tf.distribute.MirroredStrategy``)."""

    def __init__(self, data_axis: str = "data", grad_comms: Any | None = None):
        super().__init__(mesh_lib.local_mesh((data_axis,)), data_axis, grad_comms)


class CollectiveAllReduceStrategy(Strategy):
    """Data parallelism over the WHOLE slice; gradients AllReduce over
    ICI/DCN (reference: ``MultiWorkerMirroredStrategy`` with NCCL —
    SURVEY.md §2.9 row 2).

    ``update_sharding="cross_replica"`` switches the weight update to
    the ZeRO-1 reduce-scatter/sharded-update/all-gather schedule
    (:mod:`hops_tpu.parallel.grad_comms`); ``grad_comms`` takes a full
    ``GradCommsConfig`` (quantization, bucket size) and wins over the
    shorthand's defaults.
    """

    def __init__(
        self,
        data_axis: str = "data",
        update_sharding: str = "replicated",
        grad_comms: Any | None = None,
    ):
        if update_sharding != "replicated":
            import dataclasses

            from hops_tpu.parallel.grad_comms import GradCommsConfig

            base = grad_comms if grad_comms is not None else GradCommsConfig()
            grad_comms = dataclasses.replace(base, update_sharding=update_sharding)
        super().__init__(mesh_lib.global_mesh((data_axis,)), data_axis, grad_comms)


# The reference docs name ParameterServerStrategy as a supported mode but
# never call it (SURVEY.md §2.3 last row); parameter servers have no
# TPU-native analog, so it is a documented alias of collective allreduce.
ParameterServerStrategy = CollectiveAllReduceStrategy


class ShardedStrategy(Strategy):
    """Data + FSDP + tensor parallelism over one (data, fsdp, model) mesh.

    Beyond-reference capability (SURVEY.md §2.9 row 5 notes the
    reference shards nothing): large params are Megatron-split on
    ``model`` and ZeRO-style split on ``fsdp`` via GSPMD annotations —
    XLA inserts the gather/reduce-scatter collectives. The wrapper-fn
    contract is unchanged; call :meth:`shard_state` once after creating
    the train state.
    """

    def __init__(
        self,
        data: int = -1,
        fsdp: int = 1,
        model: int = 1,
        min_shard_size: int = 4096,
    ):
        mesh = mesh_lib.make_mesh({"data": data, "fsdp": fsdp, "model": model})
        # ZeRO semantics: the batch shards over data AND fsdp — each
        # fsdp group works on different samples (params are what fsdp
        # shards); only the model axis replicates the batch. The base
        # class derives replica count and batch sharding from the tuple.
        super().__init__(mesh, ("data", "fsdp"))
        self.min_shard_size = min_shard_size

    def _spec_for(self, leaf: Any) -> P:
        from hops_tpu.parallel import sharding as shard_lib

        sp = shard_lib.infer_param_spec(
            leaf, "model", self.mesh.shape["model"], self.min_shard_size
        )
        fsdp = self.mesh.shape["fsdp"]
        shape = jax.numpy.shape(leaf)
        if fsdp == 1 or len(shape) < 2 or math.prod(shape) < self.min_shard_size:
            return sp
        taken = {d for d, ax in enumerate(sp) if ax is not None}
        free = [d for d in range(len(shape)) if d not in taken and shape[d] % fsdp == 0]
        if not free:
            return sp
        dim = max(free, key=lambda d: shape[d])
        parts = list(sp) + [None] * (len(shape) - len(sp))
        parts[dim] = "fsdp"
        return P(*parts)

    def shard_state(self, state: Any) -> Any:
        """Place a train-state pytree: large >=2-D leaves (params AND
        their optimizer moments, which mirror param shapes) sharded on
        model/fsdp, everything else replicated."""

        def place(x):
            return jax.device_put(x, NamedSharding(self.mesh, self._spec_for(x)))

        return jax.tree.map(place, state)

    # FSDP/TP state is heterogeneous, so jit infers shardings from the
    # placed arguments instead of the base class's uniform in_shardings.
    def step(
        self,
        fn: Callable[..., Any],
        donate_state: bool = True,
        grad_comms: Any | None = None,
    ) -> Callable[..., Any]:
        if grad_comms is not None or self.grad_comms is not None:
            raise ValueError(
                "ShardedStrategy already owns its collectives via GSPMD "
                "annotations; grad_comms applies to the data-parallel "
                "strategies (Strategy/Mirrored/CollectiveAllReduce)"
            )
        key = (fn, donate_state, None)
        cached = self._step_cache.get(key)
        if cached is None:
            cached = self._step_cache[key] = jax.jit(
                fn, donate_argnums=(0,) if donate_state else ()
            )
        return cached


def current_strategy() -> "Strategy | None":
    """The innermost active ``strategy.scope()``, if any."""
    return _current[-1] if _current else None


def get_strategy() -> "Strategy":
    """Active strategy, or a default over all visible chips."""
    return _current[-1] if _current else Strategy()
