"""Distribution strategies — the user-facing API of the parallel layer.

Mirrors the ergonomics the reference exposed through
``tf.distribute.MirroredStrategy`` / ``MultiWorkerMirroredStrategy``
inside ``experiment.mirrored`` wrapper functions (reference:
mirroredstrategy_mnist_example.ipynb:125-131,
multiworkermirroredstrategy_mnist_example.ipynb:137-141; SURVEY.md
§2.9), but lowers to pjit-style sharded ``jax.jit`` over a Mesh: params
replicated, batch sharded on the ``data`` axis, gradient AllReduce
emitted by XLA over ICI — no NCCL, no TF_CONFIG, no cluster spec.

Typical wrapper-function use::

    def train_fn():
        strategy = distribute.MirroredStrategy()
        state = strategy.replicate(create_state(...))
        step = strategy.step(train_step)        # compiled SPMD step
        for batch in data:
            state, metrics = step(state, strategy.distribute_batch(batch))
        return {"accuracy": float(metrics["accuracy"])}
"""

from __future__ import annotations

import contextlib
import math
from typing import Any, Callable, Iterator

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hops_tpu.parallel import mesh as mesh_lib

_current: list["Strategy"] = []


class Strategy:
    """Base: data-parallel SPMD over an arbitrary mesh."""

    def __init__(
        self, mesh: Mesh | None = None, data_axis: str | tuple[str, ...] = "data"
    ):
        self.mesh = mesh if mesh is not None else mesh_lib.global_mesh()
        self.data_axis = data_axis

    # -- introspection (reference: strategy.num_replicas_in_sync) ------------

    @property
    def num_replicas_in_sync(self) -> int:
        axes = (
            self.data_axis
            if isinstance(self.data_axis, tuple)
            else (self.data_axis,)
        )
        return math.prod(self.mesh.shape[a] for a in axes)

    @property
    def num_hosts(self) -> int:
        return jax.process_count()

    def global_batch_size(self, per_replica: int) -> int:
        """Reference pattern: ``BATCH_SIZE_PER_REPLICA * num_replicas``."""
        return per_replica * self.num_replicas_in_sync

    # -- placement ------------------------------------------------------------

    def replicate(self, tree: Any) -> Any:
        return mesh_lib.replicate(self.mesh, tree)

    def distribute_batch(self, batch: Any) -> Any:
        return mesh_lib.shard_batch(self.mesh, batch, self.data_axis)

    # -- execution ------------------------------------------------------------

    def step(
        self,
        fn: Callable[..., Any],
        donate_state: bool = True,
    ) -> Callable[..., Any]:
        """Compile ``fn(state, batch, ...) -> (state, aux)`` as one SPMD
        step: state replicated, batch sharded, XLA inserts the gradient
        collectives. The compiled step is cached by jit."""
        rep = mesh_lib.replicated(self.mesh)
        data = NamedSharding(self.mesh, P(self.data_axis))
        return jax.jit(
            fn,
            in_shardings=(rep, data),
            out_shardings=(rep, rep),
            donate_argnums=(0,) if donate_state else (),
        )

    def run(self, fn: Callable[..., Any], state: Any, batch: Any) -> Any:
        return self.step(fn)(state, self.distribute_batch(batch))

    # -- scope (reference: ``with strategy.scope():``) ------------------------

    @contextlib.contextmanager
    def scope(self) -> Iterator["Strategy"]:
        _current.append(self)
        try:
            yield self
        finally:
            _current.pop()


class MirroredStrategy(Strategy):
    """Data parallelism over the chips of ONE host (reference:
    single-host ``tf.distribute.MirroredStrategy``)."""

    def __init__(self, data_axis: str = "data"):
        super().__init__(mesh_lib.local_mesh((data_axis,)), data_axis)


class CollectiveAllReduceStrategy(Strategy):
    """Data parallelism over the WHOLE slice; gradients AllReduce over
    ICI/DCN (reference: ``MultiWorkerMirroredStrategy`` with NCCL —
    SURVEY.md §2.9 row 2)."""

    def __init__(self, data_axis: str = "data"):
        super().__init__(mesh_lib.global_mesh((data_axis,)), data_axis)


# The reference docs name ParameterServerStrategy as a supported mode but
# never call it (SURVEY.md §2.3 last row); parameter servers have no
# TPU-native analog, so it is a documented alias of collective allreduce.
ParameterServerStrategy = CollectiveAllReduceStrategy


class ShardedStrategy(Strategy):
    """Data + FSDP + tensor parallelism over one (data, fsdp, model) mesh.

    Beyond-reference capability (SURVEY.md §2.9 row 5 notes the
    reference shards nothing): large params are Megatron-split on
    ``model`` and ZeRO-style split on ``fsdp`` via GSPMD annotations —
    XLA inserts the gather/reduce-scatter collectives. The wrapper-fn
    contract is unchanged; call :meth:`shard_state` once after creating
    the train state.
    """

    def __init__(
        self,
        data: int = -1,
        fsdp: int = 1,
        model: int = 1,
        min_shard_size: int = 4096,
    ):
        mesh = mesh_lib.make_mesh({"data": data, "fsdp": fsdp, "model": model})
        # ZeRO semantics: the batch shards over data AND fsdp — each
        # fsdp group works on different samples (params are what fsdp
        # shards); only the model axis replicates the batch. The base
        # class derives replica count and batch sharding from the tuple.
        super().__init__(mesh, ("data", "fsdp"))
        self.min_shard_size = min_shard_size

    def _spec_for(self, leaf: Any) -> P:
        from hops_tpu.parallel import sharding as shard_lib

        sp = shard_lib.infer_param_spec(
            leaf, "model", self.mesh.shape["model"], self.min_shard_size
        )
        fsdp = self.mesh.shape["fsdp"]
        shape = jax.numpy.shape(leaf)
        if fsdp == 1 or len(shape) < 2 or math.prod(shape) < self.min_shard_size:
            return sp
        taken = {d for d, ax in enumerate(sp) if ax is not None}
        free = [d for d in range(len(shape)) if d not in taken and shape[d] % fsdp == 0]
        if not free:
            return sp
        dim = max(free, key=lambda d: shape[d])
        parts = list(sp) + [None] * (len(shape) - len(sp))
        parts[dim] = "fsdp"
        return P(*parts)

    def shard_state(self, state: Any) -> Any:
        """Place a train-state pytree: large >=2-D leaves (params AND
        their optimizer moments, which mirror param shapes) sharded on
        model/fsdp, everything else replicated."""

        def place(x):
            return jax.device_put(x, NamedSharding(self.mesh, self._spec_for(x)))

        return jax.tree.map(place, state)

    # FSDP/TP state is heterogeneous, so jit infers shardings from the
    # placed arguments instead of the base class's uniform in_shardings.
    def step(self, fn: Callable[..., Any], donate_state: bool = True) -> Callable[..., Any]:
        return jax.jit(fn, donate_argnums=(0,) if donate_state else ())


def current_strategy() -> "Strategy | None":
    """The innermost active ``strategy.scope()``, if any."""
    return _current[-1] if _current else None


def get_strategy() -> "Strategy":
    """Active strategy, or a default over all visible chips."""
    return _current[-1] if _current else Strategy()
