"""Tensor-parallel inference: serve a dense-checkpoint TransformerLM
sharded over attention heads / MLP hidden columns.

Beyond-reference capability (the reference's serving is single-process
TF-Serving REST — SURVEY.md §2.5; nothing in it shards a model): a
model too big for one chip's HBM decodes across a ``tp_axis`` mesh
dimension the Megatron way — each device holds ``1/tp`` of every qkv /
out / gate / up / down kernel and its own head-shard of the KV cache,
and ONE psum per block (attention out + MLP down) combines the partial
sums over ICI. The TPU-shaped part: the whole ``generate()`` loop —
prefill, the ``lax.scan`` of decode steps, the Pallas decode kernel,
sampling — runs INSIDE a single ``shard_map``, so the only
cross-device traffic is those per-block psums; the cache lives
device-local for the entire generation.

No weight repacking: ``tp_param_specs`` slices the DENSE checkpoint's
existing head-major axes (qkv kernels are ``(dm, 3, H, hd)``, out is
head-major ``(dm, dm)``), so the shards a ``tp_shards``-configured
module expects are exactly what ``shard_map`` hands it. Output is
token-identical to single-device ``generate`` (tests/test_parallel.py).
"""

from __future__ import annotations

import functools
from typing import Any

import jax
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def tp_leaf_partition(names: list[str], tp_axis: str) -> tuple | None:
    """Which per-param axis Megatron-shards, by param path ``names``:
    the partition tuple for the UNSTACKED leaf shape, or None for
    replicated. The single source of truth for the leaf-role
    classification — ``parallel/pipeline.py`` prepends its (stage,
    layer) dims to these same tuples, so the two paths cannot
    disagree."""
    tail = names[-1] if names else ""
    if tail == "kernel":
        if "qkv" in names:  # (dm, 3, H, hd)
            return (None, None, tp_axis, None)
        if "q" in names:  # GQA q: (dm, H, hd)
            return (None, tp_axis, None)
        if "kv" in names:  # GQA kv: (dm, 2, Hkv, hd)
            return (None, None, tp_axis, None)
        if "out" in names:  # (dm, dm), rows head-major
            return (tp_axis, None)
        if "gate" in names or "up" in names:  # (dm, hidden)
            return (None, tp_axis)
        if "down" in names:  # (hidden, dm)
            return (tp_axis, None)
    return None


def tp_param_specs(params: Any, tp_axis: str) -> Any:
    """PartitionSpecs sharding a dense TransformerLM param tree the
    Megatron way over ``tp_axis``: qkv/q/kv kernels on their head axis,
    attention-out and mlp-down kernels on input rows (head-major, so
    row slices are head slices), gate/up on output columns; embeds,
    norms, and the unembed replicate."""

    def leaf_spec(path, leaf):
        names = [str(k.key) for k in path if hasattr(k, "key")]
        part = tp_leaf_partition(names, tp_axis)
        return P(*part) if part else P()

    return jax.tree_util.tree_map_with_path(leaf_spec, params)


def tp_cache_specs(cache: Any, tp_axis: str, paged: bool = False) -> Any:
    """PartitionSpecs sharding a TransformerLM decode cache over
    ``tp_axis`` — the single definition for BOTH cache layouts, so the
    dense and paged engines cannot drift:

    * dense ragged leaves ``(slots, heads, capacity, d)`` (and int8
      scale leaves ``(slots, heads, capacity)``) shard on the head
      axis, dim 1;
    * paged pool leaves ``(kv_heads, pool_blocks, page, d)`` shard on
      the head axis, dim 0 — each device owns its head-shard of every
      physical block, and the (replicated) page table indexes the same
      logical blocks on every shard;
    * the ``(slots,)`` cache index and the ``(slots, max_blocks)`` page
      table replicate (host-maintained scheduling state).
    """

    def leaf_spec(path, leaf):
        name = str(path[-1].key) if hasattr(path[-1], "key") else ""
        if name in ("idx", "pages"):
            return P()
        return P(tp_axis) if paged else P(None, tp_axis)

    return jax.tree_util.tree_map_with_path(leaf_spec, cache)


def tp_generate(
    model: Any,
    params: Any,
    prompt: jax.Array,
    rng: jax.Array,
    mesh: Mesh,
    tp_axis: str = "model",
    batch_axis: str | None = None,
    **generate_kwargs: Any,
) -> jax.Array:
    """:func:`hops_tpu.models.generation.generate` over a tensor-
    parallel mesh: same signature plus ``mesh``/``tp_axis``, same
    token-identical output. ``model`` is the DENSE module (its
    ``num_heads``, and ``num_kv_heads`` if set, must be divisible by
    the tp degree); ``params`` a dense checkpoint, resident sharded or
    not — jit moves it to the ``tp_param_specs`` layout. With
    ``batch_axis``, prompt rows additionally shard over that mesh axis
    (dp x tp serving on one mesh).
    """
    fn = _compiled(
        model, mesh, tp_axis, batch_axis,
        tuple(sorted(generate_kwargs.items())),
    )
    return fn(params, prompt, rng)


def tp_generate_speculative(
    model: Any,
    params: Any,
    draft_model: Any,
    draft_params: Any,
    prompt: jax.Array,
    mesh: Mesh,
    tp_axis: str = "model",
    batch_axis: str | None = None,
    **spec_kwargs: Any,
) -> jax.Array:
    """:func:`hops_tpu.models.generation.generate_speculative` over a
    tensor-parallel mesh: BOTH checkpoints slice in place
    (``tp_param_specs``) and both models' whole propose/score/accept
    loop runs inside one shard_map. Greedy output matches the
    single-device call (up to argmax flips at exact float ties —
    tp psums sum in a different order). Sampled runs are deterministic
    and keyed by global row ids, but acceptance compares ``u*q < p``
    on those reduction-order-sensitive logits, so cross-layout
    agreement is distributional (lossless wrt the tp-computed target),
    not bitwise. The draft's ``num_heads`` (and ``num_kv_heads``) must
    divide the tp degree too."""
    if spec_kwargs.get("temperature", 0.0) > 0 and spec_kwargs.get("rng") is None:
        # Mirror generate_speculative's validation here: inside the
        # traced wrapper rng is never None, so its own guard can't fire
        # — silently substituting a fixed key would make every
        # "random" call identical.
        raise ValueError("sampled speculative decoding requires rng")
    # rng is an ARRAY: it rides as a traced argument, not a cache key.
    rng = spec_kwargs.pop("rng", None)
    fn = _compiled(
        model, mesh, tp_axis, batch_axis,
        tuple(sorted(spec_kwargs.items())), draft_model=draft_model,
    )
    return fn(
        params, draft_params, prompt,
        jax.random.PRNGKey(0) if rng is None else rng,
    )


@functools.lru_cache(maxsize=64)
def _compiled(model, mesh, tp_axis, batch_axis, kw_items, draft_model=None):
    """The jitted shard_mapped decode loop (plain generate, or
    speculative when ``draft_model`` is given), cached on everything
    static — a per-call ``jax.jit(closure)`` would be a fresh callable
    every time and re-trace/recompile the whole decode program on
    every request batch."""
    from hops_tpu.models.generation import generate, generate_speculative

    kwargs = dict(kw_items)
    if "row_offset" in kwargs:
        raise ValueError(
            "the tp wrapper owns row_offset (it derives it from the "
            "dp shard index) — shard the batch via batch_axis instead"
        )
    shards = mesh.shape[tp_axis]
    local = model.clone(tp_axis=tp_axis, tp_shards=shards)
    dlocal = (
        draft_model.clone(tp_axis=tp_axis, tp_shards=shards)
        if draft_model is not None else None
    )
    data_spec = P(batch_axis) if batch_axis else P()

    def offset(prompt):
        # Global row id of this shard's row 0, so sampled rollouts key
        # their draws identically to the unsharded call.
        if not batch_axis:
            return 0
        return jax.lax.axis_index(batch_axis) * prompt.shape[0]

    if draft_model is None:

        def run(p, prompt, rng):
            return generate(
                local, p, prompt, rng, row_offset=offset(prompt), **kwargs
            )

        def mapped(params, prompt, rng):
            return shard_map(
                run, mesh=mesh,
                in_specs=(tp_param_specs(params, tp_axis), data_spec, P()),
                out_specs=data_spec, check_rep=False,
            )(params, prompt, rng)

    else:

        def run(p, dp, prompt, rng):
            return generate_speculative(
                local, p, dlocal, dp, prompt, rng=rng,
                row_offset=offset(prompt), **kwargs,
            )

        def mapped(params, draft_params, prompt, rng):
            return shard_map(
                run, mesh=mesh,
                in_specs=(
                    tp_param_specs(params, tp_axis),
                    tp_param_specs(draft_params, tp_axis),
                    data_spec,
                    P(),
                ),
                out_specs=data_spec, check_rep=False,
            )(params, draft_params, prompt, rng)

    return jax.jit(mapped)
