"""Tensor-parallel inference: serve a dense-checkpoint TransformerLM
sharded over attention heads / MLP hidden columns.

Beyond-reference capability (the reference's serving is single-process
TF-Serving REST — SURVEY.md §2.5; nothing in it shards a model): a
model too big for one chip's HBM decodes across a ``tp_axis`` mesh
dimension the Megatron way — each device holds ``1/tp`` of every qkv /
out / gate / up / down kernel and its own head-shard of the KV cache,
and ONE psum per block (attention out + MLP down) combines the partial
sums over ICI. The TPU-shaped part: the whole ``generate()`` loop —
prefill, the ``lax.scan`` of decode steps, the Pallas decode kernel,
sampling — runs INSIDE a single ``shard_map``, so the only
cross-device traffic is those per-block psums; the cache lives
device-local for the entire generation.

No weight repacking: ``tp_param_specs`` slices the DENSE checkpoint's
existing head-major axes (qkv kernels are ``(dm, 3, H, hd)``, out is
head-major ``(dm, dm)``), so the shards a ``tp_shards``-configured
module expects are exactly what ``shard_map`` hands it. Output is
token-identical to single-device ``generate`` (tests/test_parallel.py).
"""

from __future__ import annotations

import functools
from typing import Any

import jax
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def tp_leaf_partition(names: list[str], tp_axis: str) -> tuple | None:
    """Which per-param axis Megatron-shards, by param path ``names``:
    the partition tuple for the UNSTACKED leaf shape, or None for
    replicated. The single source of truth for the leaf-role
    classification — ``parallel/pipeline.py`` prepends its (stage,
    layer) dims to these same tuples, so the two paths cannot
    disagree."""
    tail = names[-1] if names else ""
    if tail == "kernel":
        if "qkv" in names:  # (dm, 3, H, hd)
            return (None, None, tp_axis, None)
        if "q" in names:  # GQA q: (dm, H, hd)
            return (None, tp_axis, None)
        if "kv" in names:  # GQA kv: (dm, 2, Hkv, hd)
            return (None, None, tp_axis, None)
        if "out" in names:  # (dm, dm), rows head-major
            return (tp_axis, None)
        if "gate" in names or "up" in names:  # (dm, hidden)
            return (None, tp_axis)
        if "down" in names:  # (hidden, dm)
            return (tp_axis, None)
    return None


def tp_param_specs(params: Any, tp_axis: str) -> Any:
    """PartitionSpecs sharding a dense TransformerLM param tree the
    Megatron way over ``tp_axis``: qkv/q/kv kernels on their head axis,
    attention-out and mlp-down kernels on input rows (head-major, so
    row slices are head slices), gate/up on output columns; embeds,
    norms, and the unembed replicate."""

    def leaf_spec(path, leaf):
        names = [str(k.key) for k in path if hasattr(k, "key")]
        part = tp_leaf_partition(names, tp_axis)
        return P(*part) if part else P()

    return jax.tree_util.tree_map_with_path(leaf_spec, params)


def tp_generate(
    model: Any,
    params: Any,
    prompt: jax.Array,
    rng: jax.Array,
    mesh: Mesh,
    tp_axis: str = "model",
    batch_axis: str | None = None,
    **generate_kwargs: Any,
) -> jax.Array:
    """:func:`hops_tpu.models.generation.generate` over a tensor-
    parallel mesh: same signature plus ``mesh``/``tp_axis``, same
    token-identical output. ``model`` is the DENSE module (its
    ``num_heads``, and ``num_kv_heads`` if set, must be divisible by
    the tp degree); ``params`` a dense checkpoint, resident sharded or
    not — jit moves it to the ``tp_param_specs`` layout. With
    ``batch_axis``, prompt rows additionally shard over that mesh axis
    (dp x tp serving on one mesh).
    """
    fn = _compiled(
        model, mesh, tp_axis, batch_axis,
        tuple(sorted(generate_kwargs.items())),
    )
    return fn(params, prompt, rng)


@functools.lru_cache(maxsize=64)
def _compiled(model, mesh, tp_axis, batch_axis, kw_items):
    """The jitted shard_mapped generate loop, cached on everything
    static — a per-call ``jax.jit(closure)`` would be a fresh callable
    every time and re-trace/recompile the whole decode program on
    every request batch."""
    from hops_tpu.models.generation import generate

    generate_kwargs = dict(kw_items)
    local = model.clone(tp_axis=tp_axis, tp_shards=mesh.shape[tp_axis])
    data_spec = P(batch_axis) if batch_axis else P()

    def run(p, prompt, rng):
        # Global row id of this shard's row 0, so sampled rollouts are
        # bit-identical to the unsharded call (generate folds global
        # row ids into its per-row sampling keys).
        row_offset = (
            jax.lax.axis_index(batch_axis) * prompt.shape[0]
            if batch_axis else 0
        )
        return generate(
            local, p, prompt, rng, row_offset=row_offset, **generate_kwargs
        )

    def mapped(params, prompt, rng):
        specs = tp_param_specs(params, tp_axis)
        return shard_map(
            run, mesh=mesh, in_specs=(specs, data_spec, P()),
            out_specs=data_spec, check_rep=False,
        )(params, prompt, rng)

    return jax.jit(mapped)
