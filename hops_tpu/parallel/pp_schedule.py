"""Pipeline schedule tables: gpipe / 1F1B / interleaved.

The scheduled pipeline engine (:func:`hops_tpu.parallel.pipeline.
make_pp_lm_train_step` with ``schedule=...``) runs an explicit
forward/backward tick program instead of differentiating through the
fill-drain ring. This module builds the *static* per-tick action tables
that program follows, entirely host-side:

- a **virtual stage** ``vs`` lives on device ``vs % S`` as chunk
  ``vs // S`` (Megatron interleaved placement; ``v=1`` makes chunk 0 the
  only chunk and reduces to plain stage order);
- each tick every device executes at most one forward and one backward
  *work slot* (masked no-ops when its table entry is ``-1``);
- activations/cotangents hop one device down/up the rotated ring per
  tick, so an action's products are consumable from the next tick on.

Three policies (arXiv:1909.09756's pipelining recipe; 1F1B/interleaved
per Megatron-LM):

- ``gpipe`` — *sequential*: a device starts backward work only after
  ALL its forward microbatches are done (fill, then drain). This is the
  bit-exact reference schedule the others are tested against.
- ``1f1b`` — backward as early as possible, forwards throttled to keep
  at most ``S - s`` microbatches in flight on device ``s`` (the classic
  warmup/steady/cooldown shape, bounding live activations).
- ``interleaved`` — ``v`` chunks per device (default 2): forwards
  proceed chunk-major over groups of ``S`` microbatches, shrinking the
  fill/drain bubble by ~``1/v`` at the price of ``v``× ring traffic.

Backward order is microbatch-ascending per (device, chunk) under every
policy — the property that makes gradients bit-identical across
schedules (float accumulation order never changes, only *when* the work
happens).

The tables double as the bubble model: :attr:`PipelineSchedule.
bubble_fraction` is the fraction of work slots that are idle, exported
as ``hops_tpu_pp_bubble_fraction{schedule=...}``.
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass(frozen=True)
class PipelineSchedule:
    """Static tick program for the scheduled pipeline engine.

    All tables have shape ``(ticks, n_stages)`` with ``-1`` meaning "no
    action in this slot this tick". ``f_*`` are the forward slot's
    chunk/microbatch, ``b_*`` the backward slot's; ``in_f_*`` /
    ``in_b_*`` describe what the incoming ring message (sent at the
    previous tick) contains, so the engine knows where to store it.
    """

    kind: str
    num_microbatches: int
    n_stages: int
    v: int
    f_chunk: np.ndarray
    f_mb: np.ndarray
    b_chunk: np.ndarray
    b_mb: np.ndarray
    in_f_chunk: np.ndarray
    in_f_mb: np.ndarray
    in_b_chunk: np.ndarray
    in_b_mb: np.ndarray

    @property
    def n_virtual(self) -> int:
        return self.n_stages * self.v

    @property
    def ticks(self) -> int:
        return int(self.f_chunk.shape[0])

    @property
    def bubble_fraction(self) -> float:
        """Idle fraction of work slots: each device offers 2 slots per
        tick (one F, one B) and owes ``2 * m * v`` units of work."""
        total = 2 * self.ticks * self.n_stages
        useful = 2 * self.num_microbatches * self.v * self.n_stages
        return 1.0 - useful / total

    def microbatch_work_units(self) -> int:
        """Useful work units per device (F+B per microbatch per chunk) —
        the denominator for per-microbatch step-time attribution."""
        return 2 * self.num_microbatches * self.v

    @property
    def peak_in_flight(self) -> int:
        """Max microbatches any device holds forward-done-backward-
        pending at once — the live-activation high-water mark. 1F1B's
        win over gpipe at equal bubble: O(S) instead of O(m)."""
        peak = 0
        for dev in range(self.n_stages):
            live = 0
            for t in range(self.ticks):
                if self.f_chunk[t, dev] >= 0:
                    live += 1
                if self.b_chunk[t, dev] >= 0:
                    live -= 1
                peak = max(peak, live)
        return peak


def build_pp_schedule(
    kind: str, num_microbatches: int, n_stages: int, v: int | None = None
) -> PipelineSchedule:
    """Simulate the policy into per-tick tables (see module docstring).

    The simulator is dependency-exact: ``F(vs, mb)`` needs ``F(vs-1,
    mb)`` to have completed on an earlier tick (one ring hop), ``B(vs,
    mb)`` needs its own ``F`` (stored activation + loss seed on the
    last virtual stage) and ``B(vs+1, mb)`` from an earlier tick. A
    policy violating its own dependencies would deadlock; the builder
    asserts termination.
    """
    m, s_n = num_microbatches, n_stages
    if kind not in ("gpipe", "1f1b", "interleaved"):
        raise ValueError(
            f"schedule must be gpipe|1f1b|interleaved, got {kind!r}")
    v = v if v is not None else (2 if kind == "interleaved" else 1)
    if v < 1:
        raise ValueError(f"virtual stages must be >= 1, got {v}")
    V = s_n * v

    done_f: dict[tuple[int, int], int] = {}  # (vs, mb) -> tick completed
    done_b: dict[tuple[int, int], int] = {}

    def f_ready(vs: int, mb: int, t: int) -> bool:
        return vs == 0 or done_f.get((vs - 1, mb), t) < t

    def b_ready(vs: int, mb: int, t: int) -> bool:
        if done_f.get((vs, mb), t) >= t:
            return False  # activation (and, on the last vs, the seed)
        if vs == V - 1:
            return True
        return done_b.get((vs + 1, mb), t) < t

    def f_order_key(chunk: int, mb: int) -> tuple:
        if kind == "interleaved":
            # Chunk-major over groups of S microbatches (Megatron).
            return (mb // s_n, chunk, mb)
        return (mb, chunk)

    def inflight_cap(dev: int) -> int:
        if kind == "gpipe":
            return m * v
        if kind == "1f1b":
            return s_n - dev
        return (s_n - dev) + (v - 1) * s_n  # interleaved warmup depth

    rows_f, rows_b = [], []
    t = 0
    limit = 8 * (m * v + V) + 16
    while len(done_b) < m * V:
        assert t < limit, f"{kind} schedule did not converge (deadlock?)"
        row_f = [(-1, -1)] * s_n
        row_b = [(-1, -1)] * s_n
        for dev in range(s_n):
            chunks = [j * s_n + dev for j in range(v)]
            # Backward slot: smallest microbatch first, deepest chunk on
            # ties — keeps per-(device, chunk) backward order
            # microbatch-ascending (the bit-identity invariant).
            b_cands = sorted(
                (
                    (mb, -(vs // s_n))
                    for vs in chunks
                    for mb in range(m)
                    if (vs, mb) not in done_b and b_ready(vs, mb, t)
                ),
            )
            if b_cands and (
                kind != "gpipe"
                or all((vs, mb) in done_f for vs in chunks for mb in range(m))
            ):
                mb, negc = b_cands[0]
                row_b[dev] = (-negc, mb)
            # Forward slot, policy-ordered and throttled.
            in_flight = sum(
                1
                for vs in chunks
                for mb in range(m)
                if (vs, mb) in done_f and (vs, mb) not in done_b
            )
            if in_flight < inflight_cap(dev):
                f_cands = sorted(
                    (
                        (f_order_key(vs // s_n, mb), vs // s_n, mb)
                        for vs in chunks
                        for mb in range(m)
                        if (vs, mb) not in done_f and f_ready(vs, mb, t)
                    ),
                )
                if f_cands:
                    _, chunk, mb = f_cands[0]
                    row_f[dev] = (chunk, mb)
        for dev in range(s_n):
            if row_f[dev][0] >= 0:
                c, mb = row_f[dev]
                done_f[(c * s_n + dev, mb)] = t
            if row_b[dev][0] >= 0:
                c, mb = row_b[dev]
                done_b[(c * s_n + dev, mb)] = t
        rows_f.append(row_f)
        rows_b.append(row_b)
        t += 1

    T = len(rows_f)
    f_chunk = np.array([[a for a, _ in r] for r in rows_f], np.int32)
    f_mb = np.array([[b for _, b in r] for r in rows_f], np.int32)
    b_chunk = np.array([[a for a, _ in r] for r in rows_b], np.int32)
    b_mb = np.array([[b for _, b in r] for r in rows_b], np.int32)

    # Incoming-message tables: what the ring delivers at tick t is what
    # the neighbor produced at t-1, retargeted one virtual stage on.
    in_f_chunk = np.full((T, s_n), -1, np.int32)
    in_f_mb = np.full((T, s_n), -1, np.int32)
    in_b_chunk = np.full((T, s_n), -1, np.int32)
    in_b_mb = np.full((T, s_n), -1, np.int32)
    for t in range(1, T):
        for dev in range(s_n):
            src = (dev - 1) % s_n
            c, mb = f_chunk[t - 1, src], f_mb[t - 1, src]
            if c >= 0:
                vs = c * s_n + src
                if vs + 1 <= V - 1:  # the last vs consumes its own output
                    tc = c + 1 if dev == 0 else c
                    if 0 <= tc < v and (tc * s_n + dev) == vs + 1:
                        in_f_chunk[t, dev] = tc
                        in_f_mb[t, dev] = mb
            src = (dev + 1) % s_n
            c, mb = b_chunk[t - 1, src], b_mb[t - 1, src]
            if c >= 0:
                vs = c * s_n + src
                if vs - 1 >= 0:  # vs 0's input cotangent feeds the embed
                    tc = c - 1 if dev == s_n - 1 else c
                    if 0 <= tc < v and (tc * s_n + dev) == vs - 1:
                        in_b_chunk[t, dev] = tc
                        in_b_mb[t, dev] = mb

    return PipelineSchedule(
        kind=kind, num_microbatches=m, n_stages=s_n, v=v,
        f_chunk=f_chunk, f_mb=f_mb, b_chunk=b_chunk, b_mb=b_mb,
        in_f_chunk=in_f_chunk, in_f_mb=in_f_mb,
        in_b_chunk=in_b_chunk, in_b_mb=in_b_mb,
    )
