"""Multi-host runtime initialization and cross-host coordination.

The reference's multi-worker story was Spark allocating executors and
the launcher templating ``TF_CONFIG`` per worker (SURVEY.md §3.2). The
TPU-native story: every host runs the SAME program; ``initialize()``
wires them into one JAX runtime (coordination service on host 0), after
which ``jax.devices()`` spans the slice and a global mesh covers all
chips. Control-plane barriers/broadcasts ride the same coordination
service so no side channel (Spark RPC) is needed.
"""

from __future__ import annotations

import os
from typing import Any

import jax
import numpy as np

from hops_tpu.runtime.logging import get_logger

log = get_logger(__name__)


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Join the multi-host runtime. No-ops on single-process runs and on
    TPU pods where the platform auto-discovers (GKE/GCE metadata).

    Must run before anything initializes the local XLA backend — so this
    function never touches ``jax.process_count()`` etc. until after the
    distributed client is up.
    """
    if _distributed_initialized():
        return  # already joined
    want_multi = (
        coordinator_address is not None
        or "JAX_COORDINATOR_ADDRESS" in os.environ
        or num_processes not in (None, 1)
    )
    if not want_multi:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    _sync_session_id()
    log.info(
        "joined multihost runtime: host %d/%d, %d global chips",
        jax.process_index(),
        jax.process_count(),
        jax.device_count(),
    )


def _distributed_initialized() -> bool:
    """``jax.distributed.is_initialized()`` with a fallback for JAX
    versions that predate it (0.4.x): the distributed client lives in
    ``jax._src.distributed.global_state``. Must not touch the local XLA
    backend (see :func:`initialize`)."""
    is_init = getattr(jax.distributed, "is_initialized", None)
    if is_init is not None:
        return bool(is_init())
    try:
        from jax._src import distributed as _dist

        return _dist.global_state.client is not None
    except Exception:  # pragma: no cover — private-API drift
        return False


def _sync_session_id(max_len: int = 64) -> None:
    """Adopt the chief's run-session id on every host so a run's
    artifacts land in ONE ``Experiments/<session>_<n>`` directory."""
    from hops_tpu.runtime import rundir

    sid = rundir.session_id() if is_chief() else ""
    raw = np.zeros(max_len, np.uint8)
    enc = sid.encode()[:max_len]
    raw[: len(enc)] = np.frombuffer(enc, np.uint8)
    agreed = broadcast_from_chief(raw)
    rundir.set_session_id(bytes(np.asarray(agreed)).rstrip(b"\x00").decode())


def is_chief() -> bool:
    """Host 0 — the reference's "chief worker"/driver role."""
    return jax.process_index() == 0


def broadcast_from_chief(value: Any) -> Any:
    """Broadcast a small host-level pytree from host 0 to all hosts via a
    device collective (control-plane use only — config, run ids)."""
    from jax.experimental import multihost_utils

    return multihost_utils.broadcast_one_to_all(value)


def barrier(name: str = "barrier") -> None:
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)


def all_hosts_agree(value: Any) -> bool:
    """Check a scalar is identical on every host (guards against
    divergent control flow, the classic SPMD deadlock)."""
    from jax.experimental import multihost_utils

    arr = np.asarray(value, dtype=np.float32).reshape(-1)
    gathered = multihost_utils.process_allgather(arr)
    return bool(np.all(gathered == gathered[0]))
