"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

The reference has no long-context support at all (SURVEY.md §5
"Long-context / sequence parallelism — Absent"); this framework makes it
first-class. Two TPU-native schemes, both expressed as ``shard_map``
programs over a ``seq`` mesh axis so XLA lowers the communication onto
the ICI ring:

- **Ring attention** (`ring_attention`): Q stays put; K/V chunks rotate
  around the ring via ``lax.ppermute`` while each device folds the
  incoming chunk into online-softmax accumulators (running max/sum).
  Memory per device is O(seq/n · d); the (seq, seq) score matrix never
  exists. Communication overlaps compute step-for-step — the pattern
  the scaling book calls "ring attention on the ICI torus".

- **Ulysses** (`ulysses_attention`): two ``all_to_all`` collectives
  reshard (seq-sharded, all heads) → (head-sharded, full seq), run
  ordinary (flash) attention locally, and reshard back. Cheaper at
  moderate sequence lengths when heads ≥ ring size.

Both give bitwise-identical math to full attention (up to fp summation
order) and are verified against the XLA reference on the fake 8-device
mesh (tests/test_ringattention.py).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from hops_tpu.ops.attention import NEG_INF, flash_attention, repeat_kv


from hops_tpu.parallel.mesh import pvary as _pvary


def _local_scores(q, k, sm_scale, q_offset, k_offset, causal, window=None,
                  s_q: int | None = None):
    """(bh, rows, sk) masked scores for one ring step, fp32.

    ``s_q``: the true per-device query length when GQA query-head
    groups are folded into the row dim (rows = g * s_q; row r holds
    chunk position r % s_q). Defaults to the row count (no folding).
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32)
    s = s * sm_scale
    if causal:
        s_q = s_q or q.shape[2]
        q_pos = q_offset + jnp.arange(q.shape[2])[:, None] % s_q
        k_pos = k_offset + jnp.arange(k.shape[2])[None, :]
        visible = q_pos >= k_pos
        if window is not None:
            visible &= q_pos - k_pos < window
        s = jnp.where(visible, s, NEG_INF)
    return s


def _fold(carry, s, v):
    """Fold one chunk's scores/values into online-softmax accumulators."""
    m, l, acc = carry
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    m_safe = jnp.where(m_new == NEG_INF, 0.0, m_new)
    p = jnp.exp(s - m_safe[..., None])
    alpha = jnp.exp(jnp.where(m == NEG_INF, NEG_INF, m - m_safe))
    l = l * alpha + jnp.sum(p, axis=-1)
    pv = jnp.einsum(
        "bhqk,bhkd->bhqd", p.astype(v.dtype), v, preferred_element_type=jnp.float32
    )
    acc = acc * alpha[..., None] + pv
    return m_new, l, acc


def ring_attention_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis: str = "seq",
    batch_axis: str | None = None,
    causal: bool = False,
    sm_scale: float | None = None,
    window: int | None = None,
    ring_size: int,
) -> jax.Array:
    """The per-device body of ring attention, for use under an
    ENCLOSING ``shard_map`` that carries a ``axis``-named mesh axis
    (e.g. sequence parallelism inside a pipeline stage —
    ``pipeline.pipelined_lm_apply(seq_axis=...)``). ``q``/``k``/``v``
    are the local ``(batch, heads, seq/ring_size, d)`` shards; only
    named-axis collectives (``ppermute``/``axis_index``) are used, so
    it composes with any outer axes.

    GQA: ``k``/``v`` may carry fewer heads than ``q`` — the UN-repeated
    kv heads are what rotates the ring, so a GQA model moves
    ``num_kv_heads/num_heads`` of the MHA ICI bytes. Locally the
    query-head groups fold into the row dim (as the decode kernel
    does), so no repeat is ever materialized.
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    n = ring_size
    b, h, seq_local, d = q.shape
    hkv = k.shape[1]
    if h % hkv:
        raise ValueError(f"{h} query heads not divisible by {hkv} kv heads")
    g = h // hkv
    if g > 1:
        # (b, h, s, d) -> (b, hkv, g*s, d): row r = group * s + pos.
        q = q.reshape(b, hkv, g * seq_local, d)
    my_idx = jax.lax.axis_index(axis)
    q32 = q.astype(jnp.float32)
    bh_shape = q.shape[:2] + (q.shape[2],)
    # The accumulators start as broadcast constants; mark them as
    # device-varying on the ring (and data, if combined) axes so the
    # fori_loop carry types match its (varying) outputs under
    # shard_map. Under an ENCLOSING shard_map (sp inside pp) q also
    # varies over ambient axes (e.g. "stage") which the step outputs
    # inherit — the carries must start varying over those too.
    try:
        ambient = tuple(jax.typeof(q).vma)
    except (AttributeError, TypeError):
        ambient = ()
    vary = (axis, batch_axis) + ambient
    m0 = _pvary(jnp.full(bh_shape, NEG_INF, jnp.float32), vary)
    l0 = _pvary(jnp.zeros(bh_shape, jnp.float32), vary)
    acc0 = _pvary(jnp.zeros(q.shape, jnp.float32), vary)
    q_offset = my_idx * seq_local

    def step(t, carry):
        m, l, acc, k_cur, v_cur = carry
        src_idx = (my_idx - t) % n
        k_start = src_idx * seq_local

        def fold_chunk(carry):
            s = _local_scores(
                q32, k_cur, sm_scale, q_offset, k_start, causal, window,
                s_q=seq_local,
            )
            return _fold(carry, s, v_cur)

        if causal and window is not None:
            # Sliding window: skip the fold (scores + exp + two
            # einsums) for chunks entirely outside this device's
            # visible band [q_start - window + 1, q_end]. The chunk
            # must still ROTATE — downstream devices may need it — so
            # only compute is conditional (no collectives inside cond).
            relevant = jnp.logical_and(
                k_start <= q_offset + seq_local - 1,
                k_start + seq_local - 1 >= q_offset - (window - 1),
            )
            m, l, acc = jax.lax.cond(
                relevant, fold_chunk, lambda c: c, (m, l, acc)
            )
        else:
            m, l, acc = fold_chunk((m, l, acc))
        # Rotate K/V one hop (device i sends to i+1) so that at
        # step t every device holds the chunk that originated at
        # (my_idx - t) mod n. The permute overlaps the next step's
        # compute under XLA's async collectives.
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_nxt = jax.lax.ppermute(k_cur, axis, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis, perm)
        return m, l, acc, k_nxt, v_nxt

    m, l, acc, _, _ = jax.lax.fori_loop(0, n, step, (m0, l0, acc0, k, v))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    out = (acc / l_safe[..., None]).astype(q.dtype)
    if g > 1:
        out = out.reshape(b, h, seq_local, d)
    return out


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    axis: str = "seq",
    batch_axis: str | None = None,
    causal: bool = False,
    sm_scale: float | None = None,
    window: int | None = None,
) -> jax.Array:
    """Ring attention over globally-shaped ``(batch, heads, seq, d)``.

    Inputs/outputs are sharded ``P(batch_axis, None, axis, None)`` on
    ``mesh`` (``batch_axis`` combines data parallelism with the ring);
    internally K/V rotate via ``ppermute`` so every device sees every
    chunk with only neighbor-to-neighbor ICI traffic. The per-device
    body is :func:`ring_attention_local`, reusable under an enclosing
    ``shard_map``.
    """
    n = mesh.shape[axis]
    local = functools.partial(
        ring_attention_local,
        axis=axis, batch_axis=batch_axis, causal=causal,
        sm_scale=sm_scale, window=window, ring_size=n,
    )
    spec = P(batch_axis, None, axis, None)
    return shard_map(
        local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
    )(q, k, v)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    axis: str = "seq",
    batch_axis: str | None = None,
    causal: bool = False,
    sm_scale: float | None = None,
    window: int | None = None,
    use_flash: bool = True,
) -> jax.Array:
    """DeepSpeed-Ulysses-style sequence parallelism via two all-to-alls.

    Requires ``heads % mesh.shape[axis] == 0``. Locally each device runs
    full-sequence attention over its head subset (flash kernel when
    shapes allow), so quality-of-fusion matches the single-chip path.

    GQA: when ``num_kv_heads % ring == 0`` too, K/V ride the
    all-to-alls UN-repeated (``Hkv/H`` of the MHA bytes) and the
    repeat to the local query-head count happens after the reshard —
    a local copy, not ICI traffic. An indivisible kv head count
    repeats before the all-to-all instead (correct, MHA-cost).
    """
    n = mesh.shape[axis]
    if q.shape[1] % n:
        raise ValueError(f"heads {q.shape[1]} not divisible by {axis}={n}")
    if q.shape[1] % k.shape[1]:
        raise ValueError(
            f"{q.shape[1]} query heads not divisible by {k.shape[1]} kv heads"
        )
    if k.shape[1] % n:
        k, v = repeat_kv(q, k, v)

    attn = functools.partial(
        flash_attention if use_flash else _reference_local,
        causal=causal,
        sm_scale=sm_scale,
        window=window,
    )

    def local_fn(q, k, v):
        # (b, H, s/n, d) → (b, H/n, s, d): gather seq, scatter heads.
        def fwd(x):
            return jax.lax.all_to_all(x, axis, split_axis=1, concat_axis=2, tiled=True)

        def rev(x):
            return jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1, tiled=True)

        q, k, v = fwd(q), fwd(k), fwd(v)
        k, v = repeat_kv(q, k, v)  # no-op unless GQA kv heads crossed
        return rev(attn(q, k, v))

    spec = P(batch_axis, None, axis, None)
    return shard_map(
        local_fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
    )(q, k, v)


def _reference_local(q, k, v, causal, sm_scale, window=None):
    from hops_tpu.ops.attention import attention_reference

    return attention_reference(
        q, k, v, causal=causal, sm_scale=sm_scale, window=window
    )
