"""Distribution layer: meshes, shardings, strategies, collectives.

Replaces the reference's distribution substrate (tf.distribute strategies
over Spark executors with NCCL allreduce — SURVEY.md §2.9) with SPMD over
``jax.sharding.Mesh``: shardings are annotated, XLA inserts the
collectives (AllReduce/AllGather/ReduceScatter) over ICI within a slice
and DCN across slices.
"""

from hops_tpu.parallel import grad_comms, mesh, multihost, strategy  # noqa: F401
from hops_tpu.parallel.grad_comms import (  # noqa: F401
    GradCommsConfig,
    all_reduce_grads,
    hier_all_gather,
    hier_reduce_scatter,
    psum_hierarchical,
    psum_quantized,
    sharded_apply_gradients,
    tag_backward_comms,
    zero2_apply_gradients,
    zero3_init,
    zero3_unshard,
)
from hops_tpu.parallel.tp_inference import (  # noqa: F401
    tp_generate,
    tp_generate_speculative,
    tp_param_specs,
)
from hops_tpu.parallel.strategy import (  # noqa: F401
    CollectiveAllReduceStrategy,
    MirroredStrategy,
    ParameterServerStrategy,
    ShardedStrategy,
    Strategy,
    current_strategy,
    get_strategy,
)
