"""Mesh construction and sharding helpers.

The mesh is the TPU-native unit of distribution: what the reference
modeled as "Spark executors each holding GPUs" (SURVEY.md §1 L1) becomes
axes of a ``jax.sharding.Mesh`` laid out over the slice's ICI fabric.
Axis conventions used across the framework:

- ``data``    — batch (data-parallel) axis
- ``fsdp``    — parameter-sharding axis (ZeRO-style, optional)
- ``model``   — tensor-parallel axis
- ``seq``     — sequence/context-parallel axis (ring attention)

Meshes are built host-major so that the innermost axes map onto
intra-host ICI links and collectives ride ICI, not DCN.
"""

from __future__ import annotations

import contextlib
import math
import threading
from typing import Any, Mapping, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hops_tpu.runtime import devices as rt_devices

# Sub-slice scoping: the trial driver partitions the slice into disjoint
# device groups (1 chip, 2 chips, 2x2, ...) and enters a device_scope
# per trial thread, so framework code that builds meshes inside the
# trial sees only its group — SURVEY.md §7 hard part #2 (trials on
# sub-slices of a bigger slice).
_scope = threading.local()


@contextlib.contextmanager
def device_scope(devices: Sequence[Any]):
    """Limit default mesh construction on this thread to ``devices``."""
    prev = getattr(_scope, "devices", None)
    _scope.devices = list(devices)
    try:
        yield
    finally:
        _scope.devices = prev


def scoped_devices() -> list[Any] | None:
    """Devices of the enclosing :func:`device_scope`, or None."""
    devs = getattr(_scope, "devices", None)
    return list(devs) if devs is not None else None


def _resolve_devices(devices: Sequence[Any] | None) -> list[Any]:
    """Device list for mesh construction: the explicit argument, else the
    enclosing :func:`device_scope`'s group, else all chips — host-major
    sorted so intra-host neighbors stay adjacent on inner mesh axes."""
    if devices is None:
        devices = scoped_devices()
    devs = list(devices) if devices is not None else list(jax.devices())
    return sorted(devs, key=lambda d: (d.process_index, d.id))


def make_mesh(
    shape: Sequence[int] | Mapping[str, int] | None = None,
    axis_names: Sequence[str] = ("data",),
    devices: Sequence[Any] | None = None,
) -> Mesh:
    """Build a mesh over ``devices`` (default: the enclosing
    :func:`device_scope`'s group, else all chips).

    ``shape`` may be a dict ``{"data": 4, "model": 2}``, a tuple matching
    ``axis_names``, or ``None`` (all devices on the first axis). ``-1``
    in one position means "whatever is left".
    """
    devs = _resolve_devices(devices)
    if isinstance(shape, Mapping):
        axis_names = tuple(shape.keys())
        shape = tuple(shape.values())
    if shape is None:
        shape = (len(devs),) + (1,) * (len(axis_names) - 1)
    shape = list(shape)
    if -1 in shape:
        known = math.prod(s for s in shape if s != -1)
        shape[shape.index(-1)] = len(devs) // known
    if math.prod(shape) != len(devs):
        raise ValueError(f"mesh shape {tuple(shape)} != {len(devs)} devices")
    arr = np.array(devs).reshape(shape)
    return Mesh(arr, tuple(axis_names))


def hybrid_mesh(
    ici: Mapping[str, int],
    dcn: Mapping[str, int],
    devices: Sequence[Any] | None = None,
    slice_id=None,
) -> Mesh:
    """Multi-slice mesh: DCN axes outermost, ICI axes innermost.

    A TPU pod job can span several slices; links WITHIN a slice (ICI)
    are an order of magnitude faster than the data-center network
    BETWEEN slices (DCN). The scaling-book recipe: put pure
    data-parallelism on the DCN axes (one gradient all-reduce per step
    amortizes fine over DCN) and keep every bandwidth-hungry axis —
    tensor/sequence/expert — on ICI axes inside one slice. This helper
    encodes that layout: ``dcn`` axes index whole slices, ``ici`` axes
    tile the chips of each slice, so XLA's collectives over an ``ici``
    axis never cross DCN.

    ``slice_id`` maps a device to its slice (default: the TPU runtime's
    ``device.slice_index``, falling back to ``process_index`` for
    non-TPU multi-process backends; single-process fake CPU meshes must
    pass an explicit ``slice_id`` — e.g. ``lambda d: d.id // 4`` —
    to emulate slices). Every slice must hold ``prod(ici)`` devices and
    ``prod(dcn)`` must equal the slice count.

        mesh = hybrid_mesh(ici={"data": 4, "model": 2}, dcn={"replica": 2})
        # axes ("replica", "data", "model"); psum over "model" rides ICI

    Feed to ``Strategy(mesh, data_axis=("replica", "data"))`` (batch
    shards over both) or use directly with shard_map/pjit.
    """
    devs = _resolve_devices(devices)
    if slice_id is None:
        def slice_id(d):
            return getattr(d, "slice_index", d.process_index)

    groups: dict[Any, list[Any]] = {}
    for d in devs:
        groups.setdefault(slice_id(d), []).append(d)
    slices = [groups[k] for k in sorted(groups)]
    n_dcn, n_ici = math.prod(dcn.values()), math.prod(ici.values())
    if len(slices) != n_dcn:
        raise ValueError(
            f"dcn axes {dict(dcn)} want {n_dcn} slices, found {len(slices)} "
            f"(slice ids {sorted(groups)})")
    sizes = {len(s) for s in slices}
    if sizes != {n_ici}:
        raise ValueError(
            f"ici axes {dict(ici)} want {n_ici} chips per slice, "
            f"found sizes {sorted(sizes)}")
    arr = np.array(slices).reshape(tuple(dcn.values()) + tuple(ici.values()))
    return Mesh(arr, tuple(dcn) + tuple(ici))


def local_mesh(axis_names: Sequence[str] = ("data",)) -> Mesh:
    """Mesh over this host's chips only (the reference's single-host
    MirroredStrategy domain, SURVEY.md §2.9 row 1) — or the enclosing
    trial's device group inside a :func:`device_scope`."""
    devs = scoped_devices() or jax.local_devices()
    return make_mesh(axis_names=axis_names, devices=devs)


def global_mesh(axis_names: Sequence[str] = ("data",)) -> Mesh:
    """Mesh over every chip in the slice (MultiWorkerMirrored domain)."""
    return make_mesh(axis_names=axis_names)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def pvary(x: Any, axes: Sequence[str | None]) -> Any:
    """Mark a broadcast constant as device-varying on ``axes`` (shard_map
    loop-carry typing); shared by the ring-attention and pipeline
    collectives. Axes the value already varies over are skipped —
    ``pcast`` rejects mixed invarying/varying requests (e.g. zeros_like
    of a seq-sharded activation is already seq-varying and only needs
    the stage axis added)."""
    axes = tuple(a for a in axes if a is not None)
    try:
        current = jax.typeof(x).vma
    except (AttributeError, TypeError):
        current = frozenset()
    axes = tuple(a for a in axes if a not in current)
    if not axes:
        return x
    if hasattr(jax.lax, "pcast"):  # current API; pvary is its deprecated alias
        return jax.lax.pcast(x, axes, to="varying")
    if hasattr(jax.lax, "pvary"):
        return jax.lax.pvary(x, axes)
    # Neither exists: this JAX predates varying-manual-axes typing
    # (<= 0.4.x), where shard_map carries broadcast constants without
    # any vma marking — nothing to do.
    return x


def batch_sharding(mesh: Mesh, axis: str | tuple[str, ...] = "data") -> NamedSharding:
    """Leading-dim sharding for batches along the data axis (or several
    combined axes, e.g. ``("data", "fsdp")`` for ZeRO semantics)."""
    return NamedSharding(mesh, P(axis))


def shard_batch(mesh: Mesh, batch: Any, axis: str | tuple[str, ...] = "data") -> Any:
    """Place a host-local batch tree onto the mesh, sharded on ``axis``.

    Multi-host: each process contributes its local shard and the result
    is a global array (the TPU answer to the reference's
    ``AutoShardPolicy.OFF`` + per-worker dataset slicing, SURVEY.md §2.9
    row 2).
    """
    sharding = batch_sharding(mesh, axis)

    def _place(x: Any) -> jax.Array:
        x = np.asarray(x)
        if jax.process_count() > 1:
            return jax.make_array_from_process_local_data(sharding, x)
        return jax.device_put(x, sharding)

    return jax.tree.map(_place, batch)


def replicate(mesh: Mesh, tree: Any) -> Any:
    """Replicate a pytree (params/opt state) across the mesh."""
    return jax.device_put(tree, replicated(mesh))
