"""Gradient-communication optimization layer.

The data-parallel hot path is bounded by ICI/DCN bytes, not MXU FLOPs:
the default ``Strategy.step`` replicates state and leaves gradient
synchronization to XLA's fp32 AllReduce. This module takes explicit
control of that traffic with three composable optimizations:

1. **Block-scaled quantized all-reduce** (EQuARX, arXiv:2506.17615):
   gradients are quantized to int8 (or cast to bf16) with one fp32
   scale per ``block_size`` elements before each wire hop of the
   reduce-scatter + all-gather decomposition; the reduction itself
   accumulates in full precision. Exposed leaf-level as
   :func:`psum_quantized` (a drop-in ``lax.psum`` usable inside any
   ``shard_map``) and tree-level as :func:`all_reduce_grads`. On CPU
   emulation the quantize→dequantize round-trip models the numerics;
   on TPU the same schedule keeps int8 on the wire, halving (bf16) or
   quartering (int8) gradient bytes.

2. **Cross-replica sharded weight update** (ZeRO-1 shape; "Automatic
   Cross-Replica Sharding of Weight Update in Data-Parallel Training",
   arXiv:2004.13336): gradients are reduce-scattered instead of
   all-reduced, each replica runs the optimizer update on its 1/N slice
   of the (flattened) parameters and optimizer moments, and updated
   params are all-gathered — the redundant replicated update work drops
   by N×. Exposed as :func:`sharded_apply_gradients` and wired in via
   ``CollectiveAllReduceStrategy(update_sharding="cross_replica")``.
   The state contract stays replicated-in/replicated-out (moments are
   re-gathered), so it is a drop-in for existing loops; the
   persistent-sharded-moments variant that also banks the ZeRO-1
   memory win needs a sharded state carrier and is future work.

3. **Gradient bucketing** (:func:`flatten_buckets` /
   :func:`unflatten_buckets`): small leaves concatenate into a few
   large per-dtype buffers so per-collective launch overhead is
   amortized and block quantization sees long runs.

Everything here runs inside ``shard_map`` over the strategy's data
axis — ``Strategy.step(fn, grad_comms=cfg)`` does the wrapping, and
``models.common.make_train_step(grad_comms=cfg)`` builds a step that
calls :func:`apply_gradients` instead of relying on XLA's implicit
psum. The whole layer is testable on the fake 8-device CPU mesh
(``XLA_FLAGS=--xla_force_host_platform_device_count=8``), see
``tests/test_grad_comms.py``.

Telemetry (see docs/operations.md): counters
``hops_tpu_grad_comms_bytes_pre_total`` /
``hops_tpu_grad_comms_bytes_post_total`` (wire bytes per step before /
after compression, labelled ``mode``), gauge
``hops_tpu_grad_comms_compression_ratio``, and a
``span("grad_comms.all_reduce")`` timing each step dispatch into
``grad_comms_all_reduce_seconds``.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

#: Default bucket target: 4 MiB of gradient bytes per collective — big
#: enough to amortize launch overhead, small enough to overlap.
DEFAULT_BUCKET_BYTES = 4 << 20


@dataclasses.dataclass(frozen=True)
class GradCommsConfig:
    """Configuration for explicit gradient communication.

    Passing any config (even the default) to ``Strategy.step`` /
    ``make_train_step`` switches the step from XLA's implicit gradient
    AllReduce to the explicit bucketed collectives in this module;
    ``quantize`` and ``update_sharding`` then select the optimizations.
    Hashable (frozen) so compiled steps memoize per config.
    """

    quantize: bool = False
    update_sharding: str = "replicated"  # "replicated" | "cross_replica"
    qdtype: Any = jnp.int8  # int8 (block-scaled) or bfloat16 (cast-only)
    block_size: int = 256
    bucket_bytes: int = DEFAULT_BUCKET_BYTES

    def __post_init__(self):
        if self.update_sharding not in ("replicated", "cross_replica"):
            raise ValueError(
                f"update_sharding must be 'replicated' or 'cross_replica', "
                f"got {self.update_sharding!r}"
            )

    @property
    def mode(self) -> str:
        """Human/flag name: allreduce | quantized | zero1 | quantized+zero1."""
        parts = []
        if self.quantize:
            parts.append("quantized")
        if self.update_sharding == "cross_replica":
            parts.append("zero1")
        return "+".join(parts) or "allreduce"

    @classmethod
    def parse(cls, mode: str | None) -> "GradCommsConfig | None":
        """Parse the ``--grad-comms`` flag: ``none`` (or None) means the
        default XLA-implicit path and returns None; the other modes
        return a config for the explicit path."""
        if mode is None or mode == "none":
            return None
        known = {
            "allreduce": cls(),
            "quantized": cls(quantize=True),
            "zero1": cls(update_sharding="cross_replica"),
            "quantized+zero1": cls(quantize=True, update_sharding="cross_replica"),
        }
        if mode not in known:
            raise ValueError(
                f"unknown grad-comms mode {mode!r}; pick one of "
                f"none|{'|'.join(known)}"
            )
        return known[mode]


# -- block-scaled quantization ------------------------------------------------


def quantize_blockwise(
    x: jax.Array, block_size: int = 256, qdtype: Any = jnp.int8
) -> tuple[jax.Array, jax.Array | None]:
    """Quantize to ``(blocks, scales)``: the wire format of the quantized
    collectives. ``x`` is flattened, zero-padded to a block multiple and
    reshaped ``(n_blocks, block_size)``; int dtypes get one fp32 scale
    per block (``amax / qmax`` symmetric), float dtypes (bf16) are a
    plain cast with ``scales=None``."""
    flat = x.reshape(-1)
    pad = (-flat.shape[0]) % block_size
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    blocks = flat.reshape(-1, block_size)
    if not jnp.issubdtype(jnp.dtype(qdtype), jnp.integer):
        return blocks.astype(qdtype), None
    info = jnp.iinfo(qdtype)
    qmax = float(info.max)
    amax = jnp.max(jnp.abs(blocks.astype(jnp.float32)), axis=1, keepdims=True)
    scales = jnp.where(amax > 0, amax / qmax, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(blocks / scales), -qmax, qmax).astype(qdtype)
    return q, scales


def dequantize_blockwise(
    q: jax.Array,
    scales: jax.Array | None,
    size: int,
    shape: tuple[int, ...],
    dtype: Any,
) -> jax.Array:
    """Inverse of :func:`quantize_blockwise` (drops the block padding)."""
    blocks = q.astype(jnp.float32)
    if scales is not None:
        blocks = blocks * scales
    return blocks.reshape(-1)[:size].reshape(shape).astype(dtype)


def _wire(x: jax.Array, block_size: int, qdtype: Any) -> jax.Array:
    """One wire hop: quantize → dequantize. On TPU the quantized blocks
    are what travels; this round-trip is the numerics-faithful emulation
    that also runs on the CPU tier-1 mesh."""
    q, scales = quantize_blockwise(x, block_size, qdtype)
    return dequantize_blockwise(q, scales, x.size, x.shape, x.dtype)


def psum_quantized(
    x: jax.Array,
    axis_name: Any,
    *,
    block_size: int = 256,
    qdtype: Any = jnp.int8,
    mean: bool = False,
) -> jax.Array:
    """Drop-in ``lax.psum`` with block-scaled quantization on the wire.

    Decomposes the all-reduce into reduce-scatter + all-gather and
    quantizes the operand before each hop (local gradients going in,
    partial sums coming out) — the EQuARX schedule: accumulation stays
    full-precision, only wire bytes shrink. Must run inside a
    ``shard_map`` carrying ``axis_name``. With one replica there is no
    wire, so the input is returned unquantized.
    """
    n = lax.psum(1, axis_name)
    if n == 1:
        return x
    orig_dtype, shape, size = x.dtype, x.shape, x.size
    flat = x.astype(jnp.float32).reshape(-1)
    # Pad so every scatter shard is whole blocks of the scatter dim.
    pad = (-size) % (n * block_size)
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    flat = _wire(flat, block_size, qdtype)  # hop 1: local grads
    part = lax.psum_scatter(flat, axis_name, scatter_dimension=0, tiled=True)
    part = _wire(part, block_size, qdtype)  # hop 2: partial sums
    out = lax.all_gather(part, axis_name, tiled=True)
    out = out.reshape(-1)[:size].reshape(shape)
    if mean:
        out = out / n
    return out.astype(orig_dtype)


# -- bucketing ----------------------------------------------------------------


@dataclasses.dataclass
class BucketLayout:
    """Recipe to rebuild a pytree from its flat buckets."""

    treedef: Any
    #: per bucket: (leaf_indices, shapes, sizes, dtype, pad)
    buckets: list[tuple[list[int], list[tuple[int, ...]], list[int], Any, int]]


def flatten_buckets(
    tree: Any,
    bucket_bytes: int = DEFAULT_BUCKET_BYTES,
    pad_multiple: int = 1,
) -> tuple[list[jax.Array], BucketLayout]:
    """Concatenate pytree leaves into a few large 1-D buffers.

    Leaves group by dtype in tree order; a bucket closes once it holds
    ``bucket_bytes``. Each buffer is zero-padded to a multiple of
    ``pad_multiple`` (the replica count, for reduce-scatter). One
    collective per buffer instead of one per leaf amortizes dispatch
    overhead — the classic gradient-bucketing trick.
    """
    leaves, treedef = jax.tree.flatten(tree)
    open_bucket: dict[Any, int] = {}  # dtype -> index into groups
    groups: list[tuple[Any, list[int], int]] = []  # (dtype, leaf idxs, bytes)
    for i, leaf in enumerate(leaves):
        dt = jnp.dtype(leaf.dtype)
        nbytes = leaf.size * dt.itemsize
        j = open_bucket.get(dt)
        if j is None:
            open_bucket[dt] = len(groups)
            groups.append((dt, [i], nbytes))
        else:
            dtype, idxs, total = groups[j]
            idxs.append(i)
            groups[j] = (dtype, idxs, total + nbytes)
        if groups[open_bucket[dt]][2] >= bucket_bytes:
            del open_bucket[dt]  # bucket full: next same-dtype leaf opens a new one
    buffers, meta = [], []
    for dtype, idxs, _ in groups:
        parts = [leaves[i].reshape(-1) for i in idxs]
        buf = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
        pad = (-buf.shape[0]) % pad_multiple
        if pad:
            buf = jnp.concatenate([buf, jnp.zeros((pad,), buf.dtype)])
        buffers.append(buf)
        meta.append(
            (idxs, [leaves[i].shape for i in idxs], [leaves[i].size for i in idxs], dtype, pad)
        )
    return buffers, BucketLayout(treedef, meta)


def unflatten_buckets(buffers: list[jax.Array], layout: BucketLayout) -> Any:
    """Inverse of :func:`flatten_buckets`: split, reshape, re-tree."""
    n_leaves = sum(len(idxs) for idxs, *_ in layout.buckets)
    leaves: list[Any] = [None] * n_leaves
    for buf, (idxs, shapes, sizes, dtype, pad) in zip(buffers, layout.buckets):
        if pad:
            buf = buf[: buf.shape[0] - pad]
        offsets = np.cumsum(sizes)[:-1].tolist()
        parts = jnp.split(buf, offsets) if offsets else [buf]
        for i, shape, part in zip(idxs, shapes, parts):
            leaves[i] = part.reshape(shape).astype(dtype)
    return jax.tree.unflatten(layout.treedef, leaves)


# -- tree-level collectives ---------------------------------------------------


def all_reduce_grads(
    grads: Any,
    axis_name: Any = "data",
    config: GradCommsConfig | None = None,
    *,
    mean: bool = True,
) -> Any:
    """Bucketed (optionally quantized) all-reduce of a gradient pytree.

    The explicit replacement for the psum XLA would have inserted:
    flatten into per-dtype buffers, one collective per buffer, restore
    the tree. ``mean=True`` (the default) divides by the replica count,
    matching the global-mean-loss gradients of the implicit path.
    """
    cfg = config or GradCommsConfig()
    n = lax.psum(1, axis_name)
    buffers, layout = flatten_buckets(grads, cfg.bucket_bytes)
    out = []
    for buf in buffers:
        floating = jnp.issubdtype(buf.dtype, jnp.floating)
        if cfg.quantize and floating and n > 1:
            r = psum_quantized(
                buf, axis_name, block_size=cfg.block_size, qdtype=cfg.qdtype
            )
        else:
            r = lax.psum(buf, axis_name)
        if mean and floating:
            r = r / n
        out.append(r)
    return unflatten_buckets(out, layout)


# -- ZeRO-1 cross-replica sharded update --------------------------------------


def _shard_slice(buf: jax.Array, n: int, idx: jax.Array) -> jax.Array:
    m = buf.shape[0] // n
    return lax.dynamic_slice_in_dim(buf, idx * m, m)


def _param_subtree_pred(params: Any) -> Callable[[Any], bool]:
    """Predicate matching subtrees shaped exactly like ``params`` —
    optimizer moments (Adam mu/nu, SGD momentum trace) mirror the param
    tree; scalars like Adam's step count do not."""
    p_def = jax.tree.structure(params)
    p_shapes = [tuple(l.shape) for l in jax.tree.leaves(params)]

    def pred(x: Any) -> bool:
        if jax.tree.structure(x) != p_def:
            return False
        lv = jax.tree.leaves(x)
        return all(tuple(a.shape) == s for a, s in zip(lv, p_shapes))

    return pred


def sharded_apply_gradients(
    state: Any,
    grads: Any,
    axis_name: Any = "data",
    config: GradCommsConfig | None = None,
    extra_updates: dict[str, Any] | None = None,
) -> Any:
    """ZeRO-1-shaped train-state update inside ``shard_map``.

    Instead of all-reducing gradients and running the optimizer
    identically on every replica, this reduce-scatters the (bucketed,
    optionally quantized) gradients, updates only the local 1/N slice
    of the flattened params and optimizer moments, and all-gathers the
    updated params — eliminating the N-fold redundant update FLOPs
    (arXiv:2004.13336). Exact for elementwise optimizers (SGD,
    momentum, Adam, ...): slicing commutes with elementwise updates, so
    the result matches the replicated update bit-for-bit up to
    collective reduction order.

    ``extra_updates`` passes through to ``state.replace`` (e.g. pmean'd
    ``batch_stats``). The moments are re-gathered so the returned state
    keeps the replicated contract (see module docstring).
    """
    cfg = config or GradCommsConfig(update_sharding="cross_replica")
    extra = extra_updates or {}
    n = lax.psum(1, axis_name)
    if n == 1:  # no wire, no redundant work: plain update
        return state.apply_gradients(grads=grads, **extra)
    idx = lax.axis_index(axis_name)

    # 1. Bucket + pad the gradients and reduce-scatter each buffer;
    #    every replica ends up with the mean-gradient slice it owns.
    gbufs, _ = flatten_buckets(grads, cfg.bucket_bytes, pad_multiple=n)
    gshards = []
    for buf in gbufs:
        if cfg.quantize and jnp.issubdtype(buf.dtype, jnp.floating):
            buf = _wire(buf, cfg.block_size, cfg.qdtype)
        shard = lax.psum_scatter(buf, axis_name, scatter_dimension=0, tiled=True)
        gshards.append(shard / n)

    # 2. Slice the same flat layout out of params and the param-shaped
    #    optimizer-state subtrees (no communication: state is replicated).
    #    The params layout is kept for the unflatten in step 4: grads
    #    may arrive in a different dtype (bf16 comms casts), and the
    #    grads layout's dtypes would silently downcast the params.
    pbufs, playout = flatten_buckets(state.params, cfg.bucket_bytes, pad_multiple=n)
    pshards = [_shard_slice(b, n, idx) for b in pbufs]
    is_param_like = _param_subtree_pred(state.params)
    opt_vals, opt_def = jax.tree.flatten(state.opt_state, is_leaf=is_param_like)
    opt_flags = [is_param_like(v) for v in opt_vals]
    opt_shards, opt_layouts = [], []
    for val, flag in zip(opt_vals, opt_flags):
        if flag:
            bufs, vlayout = flatten_buckets(val, cfg.bucket_bytes, pad_multiple=n)
            opt_shards.append([_shard_slice(b, n, idx) for b in bufs])
            opt_layouts.append(vlayout)
        else:
            opt_shards.append(val)
            opt_layouts.append(None)
    opt_state_shard = jax.tree.unflatten(opt_def, opt_shards)

    # 3. Optimizer update on the shard only — 1/N of the math.
    updates, new_opt_shard = state.tx.update(gshards, opt_state_shard, pshards)
    new_pshards = jax.tree.map(lambda p, u: p + u.astype(p.dtype), pshards, updates)

    # 4. All-gather updated params (and moments, to keep the state
    #    contract replicated) and restore the original tree layout.
    new_params = unflatten_buckets(
        [lax.all_gather(s, axis_name, tiled=True) for s in new_pshards], playout
    )
    new_opt_vals = []
    # flatten_up_to keeps each leaf slot's value intact (a param-shaped
    # slot holds its list of shard buffers).
    for flag, vlayout, new_val in zip(
        opt_flags, opt_layouts, opt_def.flatten_up_to(new_opt_shard)
    ):
        if flag:
            gathered = [lax.all_gather(s, axis_name, tiled=True) for s in new_val]
            new_opt_vals.append(unflatten_buckets(gathered, vlayout))
        else:
            new_opt_vals.append(new_val)
    new_opt_state = jax.tree.unflatten(opt_def, new_opt_vals)

    return state.replace(
        step=state.step + 1, params=new_params, opt_state=new_opt_state, **extra
    )


def apply_gradients(
    state: Any,
    grads: Any,
    config: GradCommsConfig,
    axis_name: Any = "data",
    extra_updates: dict[str, Any] | None = None,
) -> Any:
    """Explicit-comms replacement for ``TrainState.apply_gradients``:
    dispatches to the ZeRO-1 sharded update or to bucketed (quantized)
    all-reduce + replicated update, per ``config``."""
    extra = extra_updates or {}
    if config.update_sharding == "cross_replica":
        return sharded_apply_gradients(
            state, grads, axis_name, config, extra_updates=extra
        )
    grads = all_reduce_grads(grads, axis_name, config, mean=True)
    return state.apply_gradients(grads=grads, **extra)


# -- telemetry ----------------------------------------------------------------


def wire_bytes(tree: Any, config: GradCommsConfig) -> tuple[int, int]:
    """(pre, post) gradient wire bytes for one reduction pass over
    ``tree``: pre is the uncompressed payload, post the quantized blocks
    plus per-block fp32 scales (equal when not quantizing). Static
    host-side arithmetic — safe to call on shapes every step."""
    pre = post = 0
    q_int = jnp.issubdtype(jnp.dtype(config.qdtype), jnp.integer)
    q_item = jnp.dtype(config.qdtype).itemsize
    for leaf in jax.tree.leaves(tree):
        nbytes = leaf.size * jnp.dtype(leaf.dtype).itemsize
        pre += nbytes
        if config.quantize and jnp.issubdtype(leaf.dtype, jnp.floating):
            n_blocks = math.ceil(leaf.size / config.block_size)
            post += leaf.size * q_item + (4 * n_blocks if q_int else 0)
        else:
            post += nbytes
    return pre, post


def instrument_step(
    step_fn: Callable[..., Any],
    config: GradCommsConfig,
    steps_per_call: int = 1,
) -> Callable[..., Any]:
    """Wrap a compiled grad-comms step with telemetry: per-call pre/post
    byte counters, the compression-ratio gauge, and a
    ``span("grad_comms.all_reduce")`` around the dispatch (async
    dispatch time, not device time — device time is the bench's job).
    ``steps_per_call`` scales the byte counters for steps that fuse
    several optimizer updates per dispatch (``lax.scan`` loops — the
    ``grad_comms_steps`` attribute Strategy.step reads off the fn)."""
    from hops_tpu.telemetry import REGISTRY, span

    mode = config.mode
    pre_c = REGISTRY.counter(
        "hops_tpu_grad_comms_bytes_pre_total",
        "Gradient wire bytes per step before compression",
        labels=("mode",),
    )
    post_c = REGISTRY.counter(
        "hops_tpu_grad_comms_bytes_post_total",
        "Gradient wire bytes per step after compression",
        labels=("mode",),
    )
    ratio_g = REGISTRY.gauge(
        "hops_tpu_grad_comms_compression_ratio",
        "Gradient compression ratio (pre / post wire bytes)",
        labels=("mode",),
    )

    @functools.wraps(step_fn)
    def wrapped(state, *args, **kwargs):
        params = getattr(state, "params", state)
        pre, post = wire_bytes(params, config)
        pre_c.inc(pre * steps_per_call, mode=mode)
        post_c.inc(post * steps_per_call, mode=mode)
        ratio_g.set(pre / post if post else 1.0, mode=mode)
        with span("grad_comms.all_reduce", mode=mode):
            return step_fn(state, *args, **kwargs)

    return wrapped
