"""Gradient-communication optimization layer.

The data-parallel hot path is bounded by ICI/DCN bytes, not MXU FLOPs:
the default ``Strategy.step`` replicates state and leaves gradient
synchronization to XLA's fp32 AllReduce. This module takes explicit
control of that traffic with three composable optimizations:

1. **Block-scaled quantized all-reduce** (EQuARX, arXiv:2506.17615):
   gradients are quantized to int8 (or cast to bf16) with one fp32
   scale per ``block_size`` elements before each wire hop of the
   reduce-scatter + all-gather decomposition; the reduction itself
   accumulates in full precision. Exposed leaf-level as
   :func:`psum_quantized` (a drop-in ``lax.psum`` usable inside any
   ``shard_map``) and tree-level as :func:`all_reduce_grads`. On CPU
   emulation the quantize→dequantize round-trip models the numerics;
   on TPU the same schedule keeps int8 on the wire, halving (bf16) or
   quartering (int8) gradient bytes.

2. **Cross-replica sharded weight update** (ZeRO-1 shape; "Automatic
   Cross-Replica Sharding of Weight Update in Data-Parallel Training",
   arXiv:2004.13336): gradients are reduce-scattered instead of
   all-reduced, each replica runs the optimizer update on its 1/N slice
   of the (flattened) parameters and optimizer moments, and updated
   params are all-gathered — the redundant replicated update work drops
   by N×. Exposed as :func:`sharded_apply_gradients` and wired in via
   ``CollectiveAllReduceStrategy(update_sharding="cross_replica")``.
   The state contract stays replicated-in/replicated-out (moments are
   re-gathered), so it is a drop-in for existing loops; the
   persistent-sharded-moments variant that also banks the ZeRO-1
   memory win needs a sharded state carrier and is future work.

3. **Gradient bucketing** (:func:`flatten_buckets` /
   :func:`unflatten_buckets`): small leaves concatenate into a few
   large per-dtype buffers so per-collective launch overhead is
   amortized and block quantization sees long runs.

4. **Overlap scheduling + ZeRO-2/3** (arXiv:1909.09756's
   comms-under-backward recipe): per-leaf ``custom_vjp`` hooks
   (:func:`tag_backward_comms`) launch each gradient's collective the
   moment backward produces it — ``overlap`` all-reduces (bit-identical
   to the sequential path), ``zero2`` reduce-scatters so gradients stay
   sharded from birth and the optimizer runs on shards
   (:func:`zero2_apply_gradients`), and ``zero3``
   (:func:`zero3_init` / :func:`zero3_unshard`) keeps parameters and
   moments 1/N-sharded at rest with on-demand per-leaf all-gather whose
   autodiff transpose IS the as-ready reduce-scatter. All exact for
   elementwise optimizers; all composing with the quantized wire.

5. **Hierarchy-aware collectives** (``hierarchy=H``): on a multi-host
   mesh the flat ring all-reduce crosses the slow inter-host fabric
   (DCN) once per hop — N-1 crossings per byte. Setting ``hierarchy``
   to the host count reschedules every gradient reduction as
   intra-host all-to-all (ICI) → inter-host all-to-all (one DCN
   crossing per byte) → local fold in **global rank order** →
   intra-host then inter-host all-gather. Because the schedule moves
   addends instead of summing partial results per phase, the fold
   reproduces XLA's flat rank-order accumulation exactly: the
   hierarchical path is **bit-identical** to the flat one, composes
   with the quantized wire (the two ``_wire`` hops sit at the same
   points) and with the overlap hooks, and its reduce-scatter half
   (:func:`hier_reduce_scatter`) drops into the ZeRO-1/2 update.
   Leaf-level entry point: :func:`psum_hierarchical`.

Everything here runs inside ``shard_map`` over the strategy's data
axis — ``Strategy.step(fn, grad_comms=cfg)`` does the wrapping, and
``models.common.make_train_step(grad_comms=cfg)`` builds a step that
calls :func:`apply_gradients` instead of relying on XLA's implicit
psum. The whole layer is testable on the fake 8-device CPU mesh
(``XLA_FLAGS=--xla_force_host_platform_device_count=8``), see
``tests/test_grad_comms.py``.

Telemetry (see docs/operations.md): counters
``hops_tpu_grad_comms_bytes_pre_total`` /
``hops_tpu_grad_comms_bytes_post_total`` (wire bytes per step before /
after compression, labelled ``mode``), gauge
``hops_tpu_grad_comms_compression_ratio``, and a
``span("grad_comms.all_reduce")`` timing each step dispatch into
``grad_comms_all_reduce_seconds``.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

#: Default bucket target: 4 MiB of gradient bytes per collective — big
#: enough to amortize launch overhead, small enough to overlap.
DEFAULT_BUCKET_BYTES = 4 << 20


@dataclasses.dataclass(frozen=True)
class GradCommsConfig:
    """Configuration for explicit gradient communication.

    Passing any config (even the default) to ``Strategy.step`` /
    ``make_train_step`` switches the step from XLA's implicit gradient
    AllReduce to the explicit bucketed collectives in this module;
    ``quantize``, ``overlap`` and ``update_sharding`` then select the
    optimizations. Hashable (frozen) so compiled steps memoize per
    config.

    ``update_sharding`` picks the ZeRO stage of the weight update:

    - ``"replicated"``   — every replica runs the full update (stage 0);
    - ``"cross_replica"``— ZeRO-1: reduce-scatter grads at update time,
      optimizer on each replica's 1/N bucket slice, all-gather params;
    - ``"zero2"``        — gradients are reduce-scattered *during
      backward* by per-leaf VJP hooks (never materialized reduced in
      full), optimizer runs on the shards;
    - ``"zero3"``        — parameters live sharded at rest
      (:func:`zero3_init`); the step all-gathers them per leaf before
      the forward and autodiff transposes that gather into the
      bucket-as-ready reduce-scatter during backward.

    ``overlap=True`` (stage-0 only) swaps the post-backward bucketed
    all-reduce for per-leaf VJP hooks, so each gradient's collective is
    launched the moment backward produces it and XLA's latency-hiding
    scheduler can run it under the remaining backward compute.
    ``zero2``/``zero3`` overlap by construction.

    ``local_only=True`` is the bench's timing reference: the step runs
    the explicit-path machinery but skips every cross-replica
    reduction (training diverges per device — measurement only).
    """

    quantize: bool = False
    update_sharding: str = "replicated"  # replicated|cross_replica|zero2|zero3
    qdtype: Any = jnp.int8  # int8 (block-scaled) or bfloat16 (cast-only)
    block_size: int = 256
    bucket_bytes: int = DEFAULT_BUCKET_BYTES
    overlap: bool = False
    local_only: bool = False  # bench-only: no reduction (compute-time probe)
    #: Host count for hierarchy-aware collectives: 0 = flat (single
    #: fabric), >= 2 = intra-host reduce then one inter-host exchange
    #: per byte. Bit-identical to flat; requires replica count % hosts == 0.
    hierarchy: int = 0

    def __post_init__(self):
        if self.update_sharding not in (
            "replicated", "cross_replica", "zero2", "zero3"
        ):
            raise ValueError(
                f"update_sharding must be one of 'replicated', "
                f"'cross_replica', 'zero2', 'zero3', got "
                f"{self.update_sharding!r}"
            )
        if self.overlap and self.update_sharding != "replicated":
            raise ValueError(
                "overlap=True applies to the replicated update only; "
                "zero2/zero3 overlap by construction and zero1 "
                "(cross_replica) reduce-scatters at update time"
            )
        if self.local_only and (self.overlap or self.hierarchy
                                or self.update_sharding != "replicated"):
            raise ValueError("local_only is a bench timing reference; "
                             "combine it with nothing")
        if self.hierarchy:
            if self.hierarchy < 2:
                raise ValueError(
                    "hierarchy counts hosts: 0 (flat) or >= 2, got "
                    f"{self.hierarchy}"
                )
            if self.update_sharding == "zero3":
                raise ValueError(
                    "hierarchy composes with the replicated/zero1/zero2 "
                    "updates; zero3's reduce-scatter is autodiff's "
                    "transpose of the param gather and cannot be "
                    "rescheduled"
                )

    @property
    def zero_stage(self) -> int:
        """0 (replicated) / 1 (cross_replica) / 2 / 3."""
        return {"replicated": 0, "cross_replica": 1,
                "zero2": 2, "zero3": 3}[self.update_sharding]

    @property
    def mode(self) -> str:
        """Human/flag name, e.g. allreduce | quantized+overlap | zero3."""
        if self.local_only:
            return "local"
        parts = []
        if self.quantize:
            parts.append("quantized")
        if self.hierarchy:
            parts.append("hier")
        if self.overlap:
            parts.append("overlap")
        if self.zero_stage:
            parts.append(f"zero{self.zero_stage}")
        return "+".join(parts) or "allreduce"

    @classmethod
    def parse(cls, mode: str | None) -> "GradCommsConfig | None":
        """Parse the ``--grad-comms`` flag: ``none`` (or None) means the
        default XLA-implicit path and returns None; the other modes
        return a config for the explicit path."""
        if mode is None or mode == "none":
            return None
        known = {
            "allreduce": cls(),
            "quantized": cls(quantize=True),
            "overlap": cls(overlap=True),
            "quantized+overlap": cls(quantize=True, overlap=True),
            "zero1": cls(update_sharding="cross_replica"),
            "quantized+zero1": cls(quantize=True, update_sharding="cross_replica"),
            "zero2": cls(update_sharding="zero2"),
            "quantized+zero2": cls(quantize=True, update_sharding="zero2"),
            "zero3": cls(update_sharding="zero3"),
            "quantized+zero3": cls(quantize=True, update_sharding="zero3"),
            "hier": cls(hierarchy=2),
            "quantized+hier": cls(quantize=True, hierarchy=2),
            "hier+overlap": cls(hierarchy=2, overlap=True),
            "quantized+hier+overlap": cls(
                quantize=True, hierarchy=2, overlap=True),
            "hier+zero1": cls(hierarchy=2, update_sharding="cross_replica"),
        }
        if mode not in known:
            raise ValueError(
                f"unknown grad-comms mode {mode!r}; pick one of "
                f"none|{'|'.join(known)}"
            )
        return known[mode]


# -- block-scaled quantization ------------------------------------------------


def quantize_blockwise(
    x: jax.Array, block_size: int = 256, qdtype: Any = jnp.int8
) -> tuple[jax.Array, jax.Array | None]:
    """Quantize to ``(blocks, scales)``: the wire format of the quantized
    collectives. ``x`` is flattened, zero-padded to a block multiple and
    reshaped ``(n_blocks, block_size)``; int dtypes get one fp32 scale
    per block (``amax / qmax`` symmetric), float dtypes (bf16) are a
    plain cast with ``scales=None``."""
    flat = x.reshape(-1)
    pad = (-flat.shape[0]) % block_size
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    blocks = flat.reshape(-1, block_size)
    if not jnp.issubdtype(jnp.dtype(qdtype), jnp.integer):
        return blocks.astype(qdtype), None
    info = jnp.iinfo(qdtype)
    qmax = float(info.max)
    amax = jnp.max(jnp.abs(blocks.astype(jnp.float32)), axis=1, keepdims=True)
    scales = jnp.where(amax > 0, amax / qmax, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(blocks / scales), -qmax, qmax).astype(qdtype)
    return q, scales


def dequantize_blockwise(
    q: jax.Array,
    scales: jax.Array | None,
    size: int,
    shape: tuple[int, ...],
    dtype: Any,
) -> jax.Array:
    """Inverse of :func:`quantize_blockwise` (drops the block padding)."""
    blocks = q.astype(jnp.float32)
    if scales is not None:
        blocks = blocks * scales
    return blocks.reshape(-1)[:size].reshape(shape).astype(dtype)


def _wire(x: jax.Array, block_size: int, qdtype: Any) -> jax.Array:
    """One wire hop: quantize → dequantize. On TPU the quantized blocks
    are what travels; this round-trip is the numerics-faithful emulation
    that also runs on the CPU tier-1 mesh."""
    q, scales = quantize_blockwise(x, block_size, qdtype)
    return dequantize_blockwise(q, scales, x.size, x.shape, x.dtype)


# -- hierarchy-aware collectives ----------------------------------------------
#
# The flat reduce-scatter ring crosses the inter-host fabric (DCN) on
# N-1 of its N hops — every byte pays the slow link N-1 times. The
# hierarchical schedule below pays it once: tiles first shuffle inside
# each host over ICI (all-to-all within the intra groups), then exactly
# one tile-sized exchange crosses hosts (all-to-all within the inter
# groups), and the reduction itself is a LOCAL fold over the collected
# addends. Folding in global rank order is what buys bit-identity: XLA's
# flat psum/psum_scatter accumulates contributions sequentially in rank
# order, and a movement-only schedule that delivers every rank's addend
# can reproduce that order exactly — whereas summing per phase (the
# textbook two-level all-reduce) reassociates the sum and drifts ~1 ulp.
# Mesh ranks are host-major: rank = host * local + device_on_host, the
# order `parallel.mesh.make_mesh` lays devices out in.


def hier_groups(
    n: int, hosts: int
) -> tuple[list[list[int]], list[list[int]]]:
    """(intra, inter) ``axis_index_groups`` for ``n`` host-major ranks on
    ``hosts`` hosts: intra groups are the ranks sharing a host, inter
    groups link the k-th device of every host."""
    if hosts < 2:
        raise ValueError(f"hierarchy needs >= 2 hosts, got {hosts}")
    if n % hosts:
        raise ValueError(
            f"replica count {n} not divisible by hierarchy={hosts} hosts"
        )
    local = n // hosts
    intra = [[h * local + i for i in range(local)] for h in range(hosts)]
    inter = [[h * local + i for h in range(hosts)] for i in range(local)]
    return intra, inter


def hier_reduce_scatter(
    flat: jax.Array, axis_name: Any, hosts: int
) -> jax.Array:
    """Hierarchical tiled reduce-scatter of a flat buffer (length a
    multiple of the replica count): intra-host all-to-all, one
    inter-host all-to-all, local fold in global rank order. Returns this
    rank's ``len(flat)/N`` tile — **bit-identical** to
    ``lax.psum_scatter(flat, axis_name, scatter_dimension=0,
    tiled=True)``, so it drops into any flat schedule (the quantized
    wire, the ZeRO-1/2 updates) without changing a single bit."""
    n = lax.psum(1, axis_name)
    intra, inter = hier_groups(n, hosts)
    local = n // hosts
    if flat.shape[0] % n:
        raise ValueError(
            f"buffer length {flat.shape[0]} not divisible by {n} replicas"
        )
    t = flat.reshape(hosts, local, -1)
    # Phase 1 (ICI): within each host, devices swap tile rows so device
    # k holds every host-mate's addends for the tiles k will own.
    p1 = lax.all_to_all(
        t, axis_name, split_axis=1, concat_axis=1, tiled=True,
        axis_index_groups=intra,
    )
    # Phase 2 (DCN): the single inter-host exchange — host rows swap so
    # each rank now holds ALL N addends for its own tile.
    p2 = lax.all_to_all(
        p1, axis_name, split_axis=0, concat_axis=0, tiled=True,
        axis_index_groups=inter,
    )
    contrib = p2.reshape(n, -1)  # row s = rank s's addend for my tile
    acc = contrib[0]
    for s in range(1, n):  # fold-left in rank order = flat psum order
        acc = acc + contrib[s]
    return acc


def hier_all_gather(
    shard: jax.Array, axis_name: Any, hosts: int
) -> jax.Array:
    """Hierarchical tiled all-gather of per-rank tiles back to the full
    buffer: intra-host gather FIRST (each host assembles its contiguous
    tile block over ICI), then one inter-host gather concatenates the
    host blocks. Pure movement — output equals the flat tiled
    ``all_gather`` element for element. Gathering inter-first would
    interleave tiles from different hosts and scramble the order."""
    g1 = lax.all_gather(
        shard, axis_name, tiled=True,
        axis_index_groups=hier_groups(lax.psum(1, axis_name), hosts)[0],
    )
    return lax.all_gather(
        g1, axis_name, tiled=True,
        axis_index_groups=hier_groups(lax.psum(1, axis_name), hosts)[1],
    )


def psum_hierarchical(
    x: jax.Array,
    axis_name: Any,
    *,
    hosts: int = 2,
    mean: bool = False,
) -> jax.Array:
    """Drop-in ``lax.psum`` with the hierarchical wire schedule —
    bit-identical output (the local fold reproduces the flat rank-order
    accumulation), one DCN crossing per byte instead of N-1. Must run
    inside a ``shard_map`` carrying ``axis_name``; the replica count
    must divide by ``hosts``. With one replica there is no wire and the
    input comes straight back."""
    n = lax.psum(1, axis_name)
    if n == 1:
        return x
    shape, size = x.shape, x.size
    flat = x.reshape(-1)
    pad = (-size) % n  # zero padding is sum-neutral
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    part = hier_reduce_scatter(flat, axis_name, hosts)
    out = hier_all_gather(part, axis_name, hosts)
    out = out.reshape(-1)[:size].reshape(shape)
    if mean:
        out = out / n
    return out


def psum_quantized(
    x: jax.Array,
    axis_name: Any,
    *,
    block_size: int = 256,
    qdtype: Any = jnp.int8,
    mean: bool = False,
    hierarchy: int = 0,
) -> jax.Array:
    """Drop-in ``lax.psum`` with block-scaled quantization on the wire.

    Decomposes the all-reduce into reduce-scatter + all-gather and
    quantizes the operand before each hop (local gradients going in,
    partial sums coming out) — the EQuARX schedule: accumulation stays
    full-precision, only wire bytes shrink. Must run inside a
    ``shard_map`` carrying ``axis_name``. With one replica there is no
    wire, so the input is returned unquantized. ``hierarchy`` >= 2
    swaps the flat reduce-scatter / all-gather for the hierarchical
    schedule — the ``_wire`` hops sit at the same two points, so the
    composition is bit-identical to the flat quantized path.
    """
    n = lax.psum(1, axis_name)
    if n == 1:
        return x
    orig_dtype, shape, size = x.dtype, x.shape, x.size
    flat = x.astype(jnp.float32).reshape(-1)
    # Pad so every scatter shard is whole blocks of the scatter dim.
    pad = (-size) % (n * block_size)
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    flat = _wire(flat, block_size, qdtype)  # hop 1: local grads
    if hierarchy:
        part = hier_reduce_scatter(flat, axis_name, hierarchy)
    else:
        part = lax.psum_scatter(
            flat, axis_name, scatter_dimension=0, tiled=True)
    part = _wire(part, block_size, qdtype)  # hop 2: partial sums
    if hierarchy:
        out = hier_all_gather(part, axis_name, hierarchy)
    else:
        out = lax.all_gather(part, axis_name, tiled=True)
    out = out.reshape(-1)[:size].reshape(shape)
    if mean:
        out = out / n
    return out.astype(orig_dtype)


# -- bucketing ----------------------------------------------------------------


@dataclasses.dataclass
class BucketLayout:
    """Recipe to rebuild a pytree from its flat buckets."""

    treedef: Any
    #: per bucket: (leaf_indices, shapes, sizes, dtype, pad)
    buckets: list[tuple[list[int], list[tuple[int, ...]], list[int], Any, int]]


def flatten_buckets(
    tree: Any,
    bucket_bytes: int = DEFAULT_BUCKET_BYTES,
    pad_multiple: int = 1,
) -> tuple[list[jax.Array], BucketLayout]:
    """Concatenate pytree leaves into a few large 1-D buffers.

    Leaves group by dtype in tree order; a bucket closes once it holds
    ``bucket_bytes``. Each buffer is zero-padded to a multiple of
    ``pad_multiple`` (the replica count, for reduce-scatter). One
    collective per buffer instead of one per leaf amortizes dispatch
    overhead — the classic gradient-bucketing trick.
    """
    leaves, treedef = jax.tree.flatten(tree)
    open_bucket: dict[Any, int] = {}  # dtype -> index into groups
    groups: list[tuple[Any, list[int], int]] = []  # (dtype, leaf idxs, bytes)
    for i, leaf in enumerate(leaves):
        dt = jnp.dtype(leaf.dtype)
        nbytes = leaf.size * dt.itemsize
        j = open_bucket.get(dt)
        if j is None:
            open_bucket[dt] = len(groups)
            groups.append((dt, [i], nbytes))
        else:
            dtype, idxs, total = groups[j]
            idxs.append(i)
            groups[j] = (dtype, idxs, total + nbytes)
        if groups[open_bucket[dt]][2] >= bucket_bytes:
            del open_bucket[dt]  # bucket full: next same-dtype leaf opens a new one
    buffers, meta = [], []
    for dtype, idxs, _ in groups:
        parts = [leaves[i].reshape(-1) for i in idxs]
        buf = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
        pad = (-buf.shape[0]) % pad_multiple
        if pad:
            buf = jnp.concatenate([buf, jnp.zeros((pad,), buf.dtype)])
        buffers.append(buf)
        meta.append(
            (idxs, [leaves[i].shape for i in idxs], [leaves[i].size for i in idxs], dtype, pad)
        )
    return buffers, BucketLayout(treedef, meta)


def unflatten_buckets(buffers: list[jax.Array], layout: BucketLayout) -> Any:
    """Inverse of :func:`flatten_buckets`: split, reshape, re-tree."""
    n_leaves = sum(len(idxs) for idxs, *_ in layout.buckets)
    leaves: list[Any] = [None] * n_leaves
    for buf, (idxs, shapes, sizes, dtype, pad) in zip(buffers, layout.buckets):
        if pad:
            buf = buf[: buf.shape[0] - pad]
        offsets = np.cumsum(sizes)[:-1].tolist()
        parts = jnp.split(buf, offsets) if offsets else [buf]
        for i, shape, part in zip(idxs, shapes, parts):
            leaves[i] = part.reshape(shape).astype(dtype)
    return jax.tree.unflatten(layout.treedef, leaves)


# -- tree-level collectives ---------------------------------------------------


def all_reduce_grads(
    grads: Any,
    axis_name: Any = "data",
    config: GradCommsConfig | None = None,
    *,
    mean: bool = True,
) -> Any:
    """Bucketed (optionally quantized) all-reduce of a gradient pytree.

    The explicit replacement for the psum XLA would have inserted:
    flatten into per-dtype buffers, one collective per buffer, restore
    the tree. ``mean=True`` (the default) divides by the replica count,
    matching the global-mean-loss gradients of the implicit path.
    """
    cfg = config or GradCommsConfig()
    n = lax.psum(1, axis_name)
    buffers, layout = flatten_buckets(grads, cfg.bucket_bytes)
    out = []
    for buf in buffers:
        floating = jnp.issubdtype(buf.dtype, jnp.floating)
        if cfg.quantize and floating and n > 1:
            r = psum_quantized(
                buf, axis_name, block_size=cfg.block_size,
                qdtype=cfg.qdtype, hierarchy=cfg.hierarchy,
            )
        elif cfg.hierarchy and floating and n > 1:
            r = psum_hierarchical(buf, axis_name, hosts=cfg.hierarchy)
        else:
            r = lax.psum(buf, axis_name)
        if mean and floating:
            r = r / n
        out.append(r)
    return unflatten_buckets(out, layout)


# -- ZeRO-1 cross-replica sharded update --------------------------------------


def _shard_slice(buf: jax.Array, n: int, idx: jax.Array) -> jax.Array:
    m = buf.shape[0] // n
    return lax.dynamic_slice_in_dim(buf, idx * m, m)


def _param_subtree_pred(params: Any) -> Callable[[Any], bool]:
    """Predicate matching subtrees shaped exactly like ``params`` —
    optimizer moments (Adam mu/nu, SGD momentum trace) mirror the param
    tree; scalars like Adam's step count do not."""
    p_def = jax.tree.structure(params)
    p_shapes = [tuple(l.shape) for l in jax.tree.leaves(params)]

    def pred(x: Any) -> bool:
        if jax.tree.structure(x) != p_def:
            return False
        lv = jax.tree.leaves(x)
        return all(tuple(a.shape) == s for a, s in zip(lv, p_shapes))

    return pred


def sharded_apply_gradients(
    state: Any,
    grads: Any,
    axis_name: Any = "data",
    config: GradCommsConfig | None = None,
    extra_updates: dict[str, Any] | None = None,
) -> Any:
    """ZeRO-1-shaped train-state update inside ``shard_map``.

    Instead of all-reducing gradients and running the optimizer
    identically on every replica, this reduce-scatters the (bucketed,
    optionally quantized) gradients, updates only the local 1/N slice
    of the flattened params and optimizer moments, and all-gathers the
    updated params — eliminating the N-fold redundant update FLOPs
    (arXiv:2004.13336). Exact for elementwise optimizers (SGD,
    momentum, Adam, ...): slicing commutes with elementwise updates, so
    the result matches the replicated update bit-for-bit up to
    collective reduction order.

    ``extra_updates`` passes through to ``state.replace`` (e.g. pmean'd
    ``batch_stats``). The moments are re-gathered so the returned state
    keeps the replicated contract (see module docstring).
    """
    cfg = config or GradCommsConfig(update_sharding="cross_replica")
    extra = extra_updates or {}
    n = lax.psum(1, axis_name)
    if n == 1:  # no wire, no redundant work: plain update
        return state.apply_gradients(grads=grads, **extra)
    idx = lax.axis_index(axis_name)

    # 1. Bucket + pad the gradients and reduce-scatter each buffer;
    #    every replica ends up with the mean-gradient slice it owns.
    gbufs, _ = flatten_buckets(grads, cfg.bucket_bytes, pad_multiple=n)
    gshards = []
    for buf in gbufs:
        if cfg.quantize and jnp.issubdtype(buf.dtype, jnp.floating):
            buf = _wire(buf, cfg.block_size, cfg.qdtype)
        if cfg.hierarchy:
            shard = hier_reduce_scatter(buf, axis_name, cfg.hierarchy)
        else:
            shard = lax.psum_scatter(
                buf, axis_name, scatter_dimension=0, tiled=True)
        gshards.append(shard / n)

    # 2-4. Sharded optimizer tail on the same per-dtype bucket layout.
    #    The params layout drives the unflatten: grads may arrive in a
    #    different dtype (bf16 comms casts), and the grads layout's
    #    dtypes would silently downcast the params.
    return _sharded_state_update(
        state, gshards,
        lambda t: flatten_buckets(t, cfg.bucket_bytes, pad_multiple=n),
        axis_name, n, idx, extra,
    )


# -- overlap hooks: collectives launched during backward ----------------------
#
# The compute-then-communicate paths above fence every collective behind
# the full backward pass. The hooks here restore the TPU-v3 pods
# overlap recipe (arXiv:1909.09756 §3): each parameter leaf is wrapped
# in an identity ``custom_vjp`` whose backward rule runs that leaf's
# collective, so the reduce lands in the backward graph exactly where
# autodiff produces the gradient. Each leaf is its own ready-bucket and
# the bucket-ready schedule IS the gradient production order (reverse
# forward order) — XLA's latency-hiding scheduler interleaves the
# collectives with the remaining backward compute instead of running
# them all after it. Values are bit-identical to the post-backward
# reduction: psum is elementwise, so per-leaf vs per-dtype-bucket
# grouping cannot change a single bit.


def _overlap_psum_hook(axis_name: Any, cfg: GradCommsConfig) -> Callable[[Any], Any]:
    """Identity whose VJP all-reduces (optionally quantized) and means
    the cotangent — the bucket-as-ready replacement for
    :func:`all_reduce_grads`."""

    @jax.custom_vjp
    def tag(x):
        return x

    def fwd(x):
        return x, None

    def bwd(_, g):
        n = lax.psum(1, axis_name)
        if n == 1:
            return (g,)
        if not jnp.issubdtype(g.dtype, jnp.floating):
            return (lax.psum(g, axis_name),)
        if cfg.quantize:
            r = psum_quantized(
                g, axis_name, block_size=cfg.block_size,
                qdtype=cfg.qdtype, hierarchy=cfg.hierarchy,
            )
        elif cfg.hierarchy:
            r = psum_hierarchical(g, axis_name, hosts=cfg.hierarchy)
        else:
            r = lax.psum(g, axis_name)
        return (r / n,)

    tag.defvjp(fwd, bwd)
    return tag


def _scatter_shard_hook(axis_name: Any, cfg: GradCommsConfig) -> Callable[[Any], Any]:
    """Identity whose VJP reduce-scatters the cotangent as soon as it is
    produced (ZeRO-2/3 wire schedule): each replica keeps only its own
    1/N mean-gradient slice, returned embedded at its flat offset in an
    otherwise-zero leaf-shaped buffer (the cotangent must match the
    primal shape). :func:`extract_grad_shards` recovers the slices; the
    off-shard zeros are never read. Only the reduce-scatter touches the
    wire — the gradient is never all-gathered."""

    @jax.custom_vjp
    def tag(x):
        return x

    def fwd(x):
        return x, None

    def bwd(_, g):
        n = lax.psum(1, axis_name)
        if n == 1:
            return (g,)
        idx = lax.axis_index(axis_name)
        shape, size, dtype = g.shape, g.size, g.dtype
        flat = g.reshape(-1)
        pad = (-size) % n
        if pad:
            flat = jnp.concatenate([flat, jnp.zeros((pad,), dtype)])
        if cfg.quantize and jnp.issubdtype(dtype, jnp.floating):
            flat = _wire(flat, cfg.block_size, cfg.qdtype)
        if cfg.hierarchy:
            shard = hier_reduce_scatter(flat, axis_name, cfg.hierarchy)
        else:
            shard = lax.psum_scatter(
                flat, axis_name, scatter_dimension=0, tiled=True)
        if jnp.issubdtype(dtype, jnp.floating):
            shard = shard / n
        m = flat.shape[0] // n
        out = lax.dynamic_update_slice(jnp.zeros_like(flat), shard, (idx * m,))
        # Positions >= size are block padding whose reduced value is 0,
        # so truncating back to the leaf shape loses nothing — the
        # extractor re-pads with the same zeros.
        return (out[:size].reshape(shape),)

    tag.defvjp(fwd, bwd)
    return tag


def _wire_cotangent_hook(cfg: GradCommsConfig) -> Callable[[Any], Any]:
    """Identity whose VJP quantize→dequantizes the cotangent — the
    EQuARX hop-1 wire format for the ZeRO-3 path, where the
    reduce-scatter itself is autodiff's transpose of the parameter
    all-gather and can't be swapped out."""

    @jax.custom_vjp
    def tag(x):
        return x

    def fwd(x):
        return x, None

    def bwd(_, g):
        if jnp.issubdtype(g.dtype, jnp.floating):
            return (_wire(g, cfg.block_size, cfg.qdtype),)
        return (g,)

    tag.defvjp(fwd, bwd)
    return tag


def tag_backward_comms(params: Any, axis_name: Any, cfg: GradCommsConfig) -> Any:
    """Wrap every param leaf so its gradient collective launches during
    backward (``overlap`` → all-reduce hooks, ``zero2`` →
    reduce-scatter hooks). Call INSIDE the differentiated function on
    the argument being differentiated."""
    if cfg.local_only:
        return params
    hook = (
        _scatter_shard_hook(axis_name, cfg)
        if cfg.update_sharding in ("zero2", "zero3")
        else _overlap_psum_hook(axis_name, cfg)
    )
    return jax.tree.map(hook, params)


# -- ZeRO-2: sharded gradients + sharded update --------------------------------


def _per_leaf_buffers(tree: Any, n: int) -> tuple[list[jax.Array], BucketLayout]:
    """Per-leaf flat buffers padded to the replica count — the shared
    layout of the scatter hooks, the ZeRO-2 update, and the ZeRO-3
    state (bucket_bytes=1 closes every bucket after one leaf)."""
    return flatten_buckets(tree, bucket_bytes=1, pad_multiple=n)


def extract_grad_shards(grads: Any, n: int, idx: jax.Array) -> list[jax.Array]:
    """Recover each replica's owned slices from scatter-hook cotangents
    (shard values at the flat offset, zeros elsewhere). A local slice —
    no communication."""
    bufs, _ = _per_leaf_buffers(grads, n)
    return [_shard_slice(b, n, idx) for b in bufs]


def _sharded_state_update(
    state: Any,
    gshards: list[jax.Array],
    flatten_fn: Callable[[Any], tuple[list[jax.Array], BucketLayout]],
    axis_name: Any,
    n: int,
    idx: jax.Array,
    extra: dict[str, Any],
) -> Any:
    """Shared ZeRO-1/2 tail: optimizer on the 1/N flat shards of params
    and param-shaped optimizer state, params all-gathered back.
    ``flatten_fn`` fixes the flat layout — per-dtype buckets for
    ZeRO-1, per-leaf buffers for ZeRO-2 (must match how ``gshards``
    was produced).

    Moments come in two carriages: replicated param-shaped subtrees
    (the legacy contract) are sliced here and all-gathered back after
    the update; :class:`MomentShards` subtrees (the persistent carrier
    from :func:`zero12_init`) arrive as the resident local shards —
    they update in place and are NEVER gathered, which is both the
    1/N-at-rest memory win and one less all-gather per step."""
    pbufs, playout = flatten_fn(state.params)
    pshards = [_shard_slice(b, n, idx) for b in pbufs]
    is_param_like = _param_subtree_pred(state.params)
    opt_vals, opt_def = jax.tree.flatten(
        state.opt_state,
        is_leaf=lambda x: _is_moment_shards(x) or is_param_like(x),
    )
    # Per entry: "persistent" (MomentShards), "replicated" (param-like,
    # slice + gather), or passthrough (scalars like Adam's count).
    opt_kind, opt_shards, opt_layouts = [], [], []
    for val in opt_vals:
        if _is_moment_shards(val):
            opt_kind.append("persistent")
            opt_shards.append(list(val.buffers))  # already the local shards
            opt_layouts.append(None)
        elif is_param_like(val):
            opt_kind.append("replicated")
            bufs, vlayout = flatten_fn(val)
            opt_shards.append([_shard_slice(b, n, idx) for b in bufs])
            opt_layouts.append(vlayout)
        else:
            opt_kind.append("scalar")
            opt_shards.append(val)
            opt_layouts.append(None)
    opt_state_shard = jax.tree.unflatten(opt_def, opt_shards)

    updates, new_opt_shard = state.tx.update(gshards, opt_state_shard, pshards)
    new_pshards = jax.tree.map(lambda p, u: p + u.astype(p.dtype), pshards, updates)

    new_params = unflatten_buckets(
        [lax.all_gather(s, axis_name, tiled=True) for s in new_pshards], playout
    )
    new_opt_vals = []
    for kind, vlayout, new_val in zip(
        opt_kind, opt_layouts, opt_def.flatten_up_to(new_opt_shard)
    ):
        if kind == "persistent":
            new_opt_vals.append(MomentShards(new_val))
        elif kind == "replicated":
            gathered = [lax.all_gather(s, axis_name, tiled=True) for s in new_val]
            new_opt_vals.append(unflatten_buckets(gathered, vlayout))
        else:
            new_opt_vals.append(new_val)
    new_opt_state = jax.tree.unflatten(opt_def, new_opt_vals)

    return state.replace(
        step=state.step + 1, params=new_params, opt_state=new_opt_state, **extra
    )


def zero2_apply_gradients(
    state: Any,
    grads: Any,
    axis_name: Any = "data",
    config: GradCommsConfig | None = None,
    extra_updates: dict[str, Any] | None = None,
) -> Any:
    """ZeRO-2 train-state update: ``grads`` arrived from the scatter
    hooks already reduce-scattered during backward (shard-in-zeros
    leaves), so this slices the owned shards locally and runs the
    ZeRO-1-style sharded optimizer tail — no gradient collective here
    at all. Exact vs the replicated update for elementwise optimizers,
    same replicated-in/out state contract as ZeRO-1."""
    extra = extra_updates or {}
    n = lax.psum(1, axis_name)
    if n == 1:
        return state.apply_gradients(grads=grads, **extra)
    idx = lax.axis_index(axis_name)
    gshards = extract_grad_shards(grads, n, idx)
    return _sharded_state_update(
        state, gshards, lambda t: _per_leaf_buffers(t, n), axis_name, n, idx, extra
    )


# -- ZeRO-1/2 persistent-sharded moments ---------------------------------------
#
# The updates above keep the replicated state contract: moments are
# all-gathered back after every step, paying N x the optimizer-state
# memory at rest PLUS a per-step gather of bytes nobody reads between
# steps (only the owning shard's slice is consumed next step). The
# carrier below banks the ZeRO-1/2 memory win ZeRO-3 already proved —
# moments stay 1/N-sharded between steps, params stay dense/replicated
# (no resharding of the forward path) — exact for elementwise
# optimizers: the moment shard each replica keeps is byte-identical to
# the slice it would have re-sliced out of the gathered tree.


@jax.tree_util.register_pytree_node_class
class MomentShards:
    """A param-like optimizer-state subtree held as flat 1/N shards.

    ``buffers`` mirrors the flat-buffer layout of the matching
    gradient shards (per-dtype buckets for ZeRO-1, per-leaf buffers
    for ZeRO-2); at rest each buffer is a global array sharded
    ``P(axis)`` across the data mesh, inside ``shard_map`` it is the
    replica's local ``(m,)`` slice. The wrapper is how the sharded
    update tells "already-sharded moments" apart from the replicated
    param-shaped subtrees it would otherwise slice."""

    def __init__(self, buffers):
        self.buffers = list(buffers)

    def tree_flatten(self):
        return self.buffers, len(self.buffers)

    @classmethod
    def tree_unflatten(cls, _n, children):
        return cls(children)

    def __repr__(self):
        return f"MomentShards({len(self.buffers)} buffers)"


def _is_moment_shards(x: Any) -> bool:
    return isinstance(x, MomentShards)


def _zero12_flatten_fn(cfg: GradCommsConfig, n: int):
    """The flat layout the gradient shards arrive in — per-dtype
    buckets at ``bucket_bytes`` for ZeRO-1 (update-time reduce-scatter),
    per-leaf buffers for ZeRO-2 (scatter hooks fire per leaf). The
    moments MUST live in the same layout."""
    if cfg.update_sharding == "zero2":
        return lambda t: _per_leaf_buffers(t, n)
    return lambda t: flatten_buckets(t, cfg.bucket_bytes, pad_multiple=n)


def zero12_init(
    state: Any, mesh: Any, config: GradCommsConfig, axis_name: Any = "data"
) -> Any:
    """Convert a replicated train state into the persistent-sharded-
    moments carrier for ZeRO-1 (``cross_replica``) / ZeRO-2: every
    param-like optimizer subtree (Adam mu/nu, SGD trace) becomes a
    :class:`MomentShards` of flat buffers placed ``P(axis_name)``
    across the mesh — 1/N optimizer bytes per chip at rest. Params and
    scalars stay replicated; the same ``TrainState`` class carries the
    state (only ``opt_state`` changes shape). Host-side; the inverse is
    :func:`zero12_unshard`.

    A mid-training state converts moment-for-moment (the shards are
    slices of the live moments), so resuming keeps the trajectory.
    Raises when a param-like subtree's dtypes differ from the params'
    — the unshard layout is derived from the param tree.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    if config.update_sharding not in ("cross_replica", "zero2"):
        raise ValueError(
            "zero12_init applies to update_sharding='cross_replica' "
            f"(ZeRO-1) or 'zero2', got {config.update_sharding!r}"
        )
    axes = axis_name if isinstance(axis_name, tuple) else (axis_name,)
    n = math.prod(mesh.shape[a] for a in axes)
    if n == 1:
        return state  # nothing to shard; the replicated update is exact
    flatten_fn = _zero12_flatten_fn(config, n)
    sharded = NamedSharding(mesh, P(axis_name))
    p_dtypes = [jnp.dtype(l.dtype) for l in jax.tree.leaves(state.params)]
    is_param_like = _param_subtree_pred(state.params)
    opt_vals, opt_def = jax.tree.flatten(state.opt_state, is_leaf=is_param_like)
    conv = []
    for v in opt_vals:
        if not is_param_like(v):
            conv.append(v)
            continue
        v_dtypes = [jnp.dtype(l.dtype) for l in jax.tree.leaves(v)]
        if v_dtypes != p_dtypes:
            raise ValueError(
                "zero12_init: optimizer moments must share the param "
                "dtypes (the unshard layout is derived from params); "
                "keep this optimizer on the replicated update"
            )
        bufs, _ = flatten_fn(v)
        conv.append(MomentShards(
            [jax.device_put(np.asarray(b), sharded) for b in bufs]
        ))
    return state.replace(opt_state=jax.tree.unflatten(opt_def, conv))


def zero12_unshard(
    state: Any, config: GradCommsConfig, axis_name: Any = "data"
) -> Any:
    """Host-side inverse of :func:`zero12_init` (eval / checkpoint
    export): dense replicated moments rebuilt from the flat shards via
    the param tree's flatten layout."""
    is_param_like = _param_subtree_pred(state.params)
    opt_vals, opt_def = jax.tree.flatten(
        state.opt_state, is_leaf=lambda x: _is_moment_shards(x) or is_param_like(x)
    )
    if not any(_is_moment_shards(v) for v in opt_vals):
        return state
    # The layout template must use the SAME pad_multiple as init: the
    # replica count of the mesh the shard buffers live on.
    first = next(v for v in opt_vals if _is_moment_shards(v))
    axes = axis_name if isinstance(axis_name, tuple) else (axis_name,)
    n = math.prod(first.buffers[0].sharding.mesh.shape[a] for a in axes)
    flatten_fn = _zero12_flatten_fn(config, n)
    _, playout = flatten_fn(state.params)
    out_vals = []
    for v in opt_vals:
        if _is_moment_shards(v):
            out_vals.append(unflatten_buckets(
                [jnp.asarray(np.asarray(b)) for b in v.buffers], playout
            ))
        else:
            out_vals.append(v)
    return state.replace(opt_state=jax.tree.unflatten(opt_def, out_vals))


def zero12_state_specs(state: Any, axis_name: Any = "data") -> Any:
    """PartitionSpec tree for a ZeRO-1/2 state under ``shard_map``:
    :class:`MomentShards` buffers split over the data axis, everything
    else (params, step, scalars, non-param-like opt entries)
    replicated. For a state with NO sharded moments this degenerates to
    the all-replicated spec — the legacy replicated-contract path."""
    from jax.sharding import PartitionSpec as P

    def opt_spec(v):
        if _is_moment_shards(v):
            return MomentShards([P(axis_name) for _ in v.buffers])
        return jax.tree.map(lambda _: P(), v)

    opt_specs = jax.tree.map(
        opt_spec, state.opt_state, is_leaf=_is_moment_shards
    )
    rep = jax.tree.map(lambda _: P(), state.params)
    kw = {}
    if getattr(state, "rng", None) is not None:
        kw["rng"] = jax.tree.map(lambda _: P(), state.rng)
    if getattr(state, "batch_stats", None) is not None:
        kw["batch_stats"] = jax.tree.map(lambda _: P(), state.batch_stats)
    return state.replace(step=P(), params=rep, opt_state=opt_specs, **kw)


def has_sharded_moments(state: Any) -> bool:
    """True when ``state.opt_state`` carries :class:`MomentShards`
    (the persistent ZeRO-1/2 carrier) — Strategy.step derives per-leaf
    shard_map specs for such states."""
    vals, _ = jax.tree.flatten(
        getattr(state, "opt_state", None), is_leaf=_is_moment_shards
    )
    return any(_is_moment_shards(v) for v in vals)


# -- ZeRO-3: parameters sharded at rest ----------------------------------------


def _flax_struct():
    from flax import struct

    return struct


def _zero3_meta(params: Any, n: int) -> tuple:
    """Static per-leaf layout: (shape, dtype name, size, padded size) in
    tree-leaves order — hashable, rides the state as aux data."""
    meta = []
    for leaf in jax.tree.leaves(params):
        size = int(np.prod(leaf.shape)) if leaf.shape else 1
        padded = size + ((-size) % n)
        meta.append((tuple(leaf.shape), jnp.dtype(leaf.dtype).name, size, padded))
    return tuple(meta)


def zero3_init(state: Any, mesh: Any, axis_name: Any = "data") -> Any:
    """Convert a replicated train state into the ZeRO-3 carrier: every
    param leaf (and its optimizer moments) becomes a flat buffer padded
    to the replica count and placed sharded ``P(axis_name)`` across the
    mesh — 1/N parameter + optimizer bytes per chip at rest. Host-side;
    the inverse is :func:`zero3_unshard`."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    axes = axis_name if isinstance(axis_name, tuple) else (axis_name,)
    n = math.prod(mesh.shape[a] for a in axes)
    meta = _zero3_meta(state.params, n)
    sharded = NamedSharding(mesh, P(axis_name))
    replicated = NamedSharding(mesh, P())

    def _flat(leaf, m):
        flat = np.asarray(leaf).reshape(-1)
        if m[3] != m[2]:
            flat = np.concatenate([flat, np.zeros((m[3] - m[2],), flat.dtype)])
        return jax.device_put(flat, sharded)

    leaves = jax.tree.leaves(state.params)
    shard_params = jax.tree.unflatten(
        jax.tree.structure(state.params),
        [_flat(l, m) for l, m in zip(leaves, meta)],
    )
    # The INCOMING optimizer state converts leaf-for-leaf (param-shaped
    # moments flatten/pad/shard exactly like params, scalars like
    # Adam's count replicate) — a mid-training state resumes on the
    # same trajectory instead of silently re-warming zeroed moments.
    # Padding regions are zeros and only ever see zero gradients, so
    # they stay inert.
    is_param_like = _param_subtree_pred(state.params)
    opt_vals, opt_def = jax.tree.flatten(state.opt_state, is_leaf=is_param_like)
    conv_vals = []
    for v in opt_vals:
        if is_param_like(v):
            vl = jax.tree.leaves(v)
            conv_vals.append(jax.tree.unflatten(
                jax.tree.structure(v),
                [_flat(l, m) for l, m in zip(vl, meta)],
            ))
        else:
            conv_vals.append(jax.device_put(v, replicated))
    opt_state = jax.tree.unflatten(opt_def, conv_vals)
    cls = _make_zero3_state_cls()
    return cls(
        step=jax.device_put(state.step, replicated),
        apply_fn=state.apply_fn,
        params=shard_params,
        tx=state.tx,
        opt_state=opt_state,
        rng=(
            jax.device_put(state.rng, replicated)
            if getattr(state, "rng", None) is not None else None
        ),
        batch_stats=(
            jax.device_put(state.batch_stats, replicated)
            if getattr(state, "batch_stats", None) else None
        ),
        meta=meta,
    )


_ZERO3_CLS = None


def _make_zero3_state_cls():
    """The ZeRO-3 state carrier (built lazily so flax import stays at
    call time): a TrainState twin whose ``params``/``opt_state`` leaves
    are flat 1/N shards; ``meta`` (static) remembers the dense layout."""
    global _ZERO3_CLS
    if _ZERO3_CLS is None:
        struct = _flax_struct()

        class Zero3TrainState(struct.PyTreeNode):
            step: Any
            apply_fn: Callable = struct.field(pytree_node=False)
            params: Any = None
            tx: Any = struct.field(pytree_node=False, default=None)
            opt_state: Any = None
            rng: Any = None
            batch_stats: Any = None
            meta: Any = struct.field(pytree_node=False, default=())

        _ZERO3_CLS = Zero3TrainState
    return _ZERO3_CLS


def zero3_gather_params(shard_params: Any, meta: tuple, axis_name: Any) -> Any:
    """All-gather the flat shards back into dense param leaves — the
    on-demand materialization before forward/backward. Runs inside
    ``shard_map``; autodiff transposes each tiled all-gather into a
    tiled reduce-scatter, which is exactly the ZeRO-3 backward wire
    schedule, launched per leaf as backward produces its gradient."""
    leaves = jax.tree.leaves(shard_params)
    treedef = jax.tree.structure(shard_params)
    out = []
    for leaf, (shape, dtype, size, _padded) in zip(leaves, meta):
        full = lax.all_gather(leaf, axis_name, tiled=True)
        out.append(full[:size].reshape(shape).astype(dtype))
    return jax.tree.unflatten(treedef, out)


def zero3_apply_gradients(
    state: Any,
    shard_grads: Any,
    extra_updates: dict[str, Any] | None = None,
) -> Any:
    """ZeRO-3 update: gradients arrive as the local flat shards (the
    transpose of :func:`zero3_gather_params`), the optimizer runs on
    the resident shards, and nothing is gathered back — the next step's
    forward re-gathers on demand."""
    extra = extra_updates or {}
    updates, new_opt = state.tx.update(shard_grads, state.opt_state, state.params)
    new_params = jax.tree.map(
        lambda p, u: p + u.astype(p.dtype), state.params, updates
    )
    return state.replace(
        step=state.step + 1, params=new_params, opt_state=new_opt, **extra
    )


def zero3_unshard(state: Any) -> Any:
    """Host-side inverse of :func:`zero3_init` for eval / checkpoint
    export: dense replicated params (and param-shaped moments) from the
    flat shard state. Returns ``(params, opt_state)`` pytrees."""
    leaves = jax.tree.leaves(state.params)
    treedef = jax.tree.structure(state.params)

    def _dense(flat, m):
        return np.asarray(flat)[: m[2]].reshape(m[0]).astype(m[1])

    params = jax.tree.unflatten(
        treedef, [_dense(l, m) for l, m in zip(leaves, state.meta)]
    )
    is_param_like = _param_subtree_pred(state.params)
    opt_vals, opt_def = jax.tree.flatten(state.opt_state, is_leaf=is_param_like)
    out_vals = []
    for v in opt_vals:
        if is_param_like(v):
            vl = jax.tree.leaves(v)
            out_vals.append(jax.tree.unflatten(
                jax.tree.structure(v),
                [_dense(l, m) for l, m in zip(vl, state.meta)],
            ))
        else:
            out_vals.append(v)
    return params, jax.tree.unflatten(opt_def, out_vals)


def zero3_state_specs(state: Any, axis_name: Any = "data") -> Any:
    """PartitionSpec tree for a ZeRO-3 state under ``shard_map``: flat
    param/moment shards split over the data axis, scalars (step, Adam
    count, rng, batch_stats) replicated. ``Strategy.step`` derives its
    in/out specs from this on first call."""
    from jax.sharding import PartitionSpec as P

    p_specs = jax.tree.map(lambda _: P(axis_name), state.params)
    is_param_like = _param_subtree_pred(state.params)
    opt_vals, opt_def = jax.tree.flatten(state.opt_state, is_leaf=is_param_like)
    opt_specs = jax.tree.unflatten(
        opt_def,
        [
            jax.tree.map(lambda _: P(axis_name), v)
            if is_param_like(v)
            else jax.tree.map(lambda _: P(), v)
            for v in opt_vals
        ],
    )
    # tree.map mirrors structure exactly (None stays None, {} stays {}),
    # which the shard_map spec tree must do too.
    return state.replace(
        step=P(),
        params=p_specs,
        opt_state=opt_specs,
        rng=jax.tree.map(lambda _: P(), state.rng),
        batch_stats=jax.tree.map(lambda _: P(), state.batch_stats),
    )


# -- mode dispatch -------------------------------------------------------------


def prepare_params(params: Any, config: GradCommsConfig, axis_name: Any,
                   meta: tuple | None = None) -> Any:
    """Per-mode parameter view for the loss function — call INSIDE the
    differentiated function on the argument being differentiated.
    Stage 0/1 without overlap: identity (reduction happens at update
    time). ``overlap``/``zero2``: backward hooks. ``zero3``: ``params``
    are the flat shards; gather them (and install the quantized-wire
    cotangent hook when asked)."""
    if config.local_only:
        return params
    if config.update_sharding == "zero3":
        if meta is None:
            raise ValueError("zero3 needs the state's layout meta "
                             "(build the state with zero3_init)")
        full = zero3_gather_params(params, meta, axis_name)
        if config.quantize:
            full = jax.tree.map(_wire_cotangent_hook(config), full)
        return full
    if config.overlap or config.update_sharding == "zero2":
        return tag_backward_comms(params, axis_name, config)
    return params


def apply_gradients(
    state: Any,
    grads: Any,
    config: GradCommsConfig,
    axis_name: Any = "data",
    extra_updates: dict[str, Any] | None = None,
) -> Any:
    """Explicit-comms replacement for ``TrainState.apply_gradients``.
    ``grads`` must come from differentiating a loss whose params went
    through :func:`prepare_params` with the same config; their meaning
    is mode-dependent (raw per-replica for stage 0/1, reduced for
    overlap, scattered for zero2, shard-shaped for zero3)."""
    extra = extra_updates or {}
    if config.local_only:
        return state.apply_gradients(grads=grads, **extra)
    if config.update_sharding == "zero3":
        n = lax.psum(1, axis_name)
        shard_grads = jax.tree.map(
            lambda g: g / n if jnp.issubdtype(g.dtype, jnp.floating) else g,
            grads,
        )
        return zero3_apply_gradients(state, shard_grads, extra_updates=extra)
    if config.update_sharding == "zero2":
        return zero2_apply_gradients(
            state, grads, axis_name, config, extra_updates=extra
        )
    if config.update_sharding == "cross_replica":
        return sharded_apply_gradients(
            state, grads, axis_name, config, extra_updates=extra
        )
    if config.overlap:  # hooks already reduced + meaned during backward
        return state.apply_gradients(grads=grads, **extra)
    grads = all_reduce_grads(grads, axis_name, config, mean=True)
    return state.apply_gradients(grads=grads, **extra)


# -- telemetry ----------------------------------------------------------------


def wire_bytes(tree: Any, config: GradCommsConfig) -> tuple[int, int]:
    """(pre, post) gradient wire bytes for one reduction pass over
    ``tree``: pre is the uncompressed payload, post the quantized blocks
    plus per-block fp32 scales (equal when not quantizing). Static
    host-side arithmetic — safe to call on shapes every step."""
    pre = post = 0
    q_int = jnp.issubdtype(jnp.dtype(config.qdtype), jnp.integer)
    q_item = jnp.dtype(config.qdtype).itemsize
    for leaf in jax.tree.leaves(tree):
        nbytes = leaf.size * jnp.dtype(leaf.dtype).itemsize
        pre += nbytes
        if config.quantize and jnp.issubdtype(leaf.dtype, jnp.floating):
            n_blocks = math.ceil(leaf.size / config.block_size)
            post += leaf.size * q_item + (4 * n_blocks if q_int else 0)
        else:
            post += nbytes
    return pre, post


def instrument_step(
    step_fn: Callable[..., Any],
    config: GradCommsConfig,
    steps_per_call: int = 1,
) -> Callable[..., Any]:
    """Wrap a compiled grad-comms step with telemetry: per-call pre/post
    byte counters, the compression-ratio gauge, and a
    ``span("grad_comms.all_reduce")`` around the dispatch (async
    dispatch time, not device time — device time is the bench's job).
    ``steps_per_call`` scales the byte counters for steps that fuse
    several optimizer updates per dispatch (``lax.scan`` loops — the
    ``grad_comms_steps`` attribute Strategy.step reads off the fn)."""
    from hops_tpu.telemetry import REGISTRY, span

    mode = config.mode
    pre_c = REGISTRY.counter(
        "hops_tpu_grad_comms_bytes_pre_total",
        "Gradient wire bytes per step before compression",
        labels=("mode",),
    )
    post_c = REGISTRY.counter(
        "hops_tpu_grad_comms_bytes_post_total",
        "Gradient wire bytes per step after compression",
        labels=("mode",),
    )
    ratio_g = REGISTRY.gauge(
        "hops_tpu_grad_comms_compression_ratio",
        "Gradient compression ratio (pre / post wire bytes)",
        labels=("mode",),
    )

    @functools.wraps(step_fn)
    def wrapped(state, *args, **kwargs):
        params = getattr(state, "params", state)
        pre, post = wire_bytes(params, config)
        pre_c.inc(pre * steps_per_call, mode=mode)
        post_c.inc(post * steps_per_call, mode=mode)
        ratio_g.set(pre / post if post else 1.0, mode=mode)
        with span("grad_comms.all_reduce", mode=mode):
            return step_fn(state, *args, **kwargs)

    return wrapped
