"""Parallel host input pipeline: staged, resumable, instrumented.

After the gradient-comms layer (PR 2) the device side of training runs
far ahead of the host side: ``DataFeeder.numpy_iterator`` decodes and
assembles every batch synchronously on the driver thread, which is the
first scaling wall the TPU-pod input work identifies (arXiv:1909.09756)
and the reason tf.data treats input as a first-class pipelined
subsystem (arXiv:1605.08695). This module is that subsystem for the
TPU-native stack:

    source (sharded readers) -> decode/transform (bounded thread pool)
      -> batch assembly (vectorized, optionally pooled host buffers)
      -> feed.prefetch_to_device (H2D double buffer)

Design rules, in priority order:

1. **Determinism** — the threaded pipeline yields the byte-identical
   batch stream of the synchronous one. Work is planned on the consumer
   thread as an ordered sequence of ``(epoch, step)`` batch tasks; the
   pool only *executes* tasks, completion order never reorders the
   stream, and all randomness (epoch permutation, per-batch transform
   RNG) is derived from ``(seed, epoch, step)`` rather than from any
   worker-local state.
2. **Resumability** — an iterator's position is exactly
   ``(seed, epoch, step)``; :meth:`LoaderIterator.state_dict` /
   :meth:`LoaderIterator.load_state_dict` snapshot and restore it, so a
   ``CheckpointManager``/preemption restore replays the exact remaining
   stream (``runtime.preemption.run_preemptible`` does this
   automatically via the checkpoint data-state sidecar).
3. **Observability** — every stage is instrumented: queue-depth gauges
   (``hops_tpu_feed_stage_queue_depth{pipeline,stage}``), a
   decode-latency histogram (``hops_tpu_feed_decode_seconds``), a
   feed-wait histogram (``hops_tpu_feed_wait_seconds``), and the
   starvation counter ``hops_tpu_feed_starved_steps_total`` derived
   from feed-wait vs step wall time.

Per-host sharding mirrors ``DataFeeder.numpy_iterator``: with
``shard_count > 1`` every process plans the SAME seed-derived global
order and materializes only its ``batch_size / shard_count`` slice of
each global batch, so host shards are disjoint by construction.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from pathlib import Path
from typing import Any, Callable, Iterator, Sequence

import numpy as np

from hops_tpu.runtime import faultinject
from hops_tpu.runtime.logging import get_logger
from hops_tpu.telemetry.metrics import REGISTRY

log = get_logger(__name__)

_STATE_VERSION = 1


# -- small structural helpers (dict/tuple/list/array pytrees; no jax) ---------


def _tree_map(fn: Callable, tree: Any) -> Any:
    if isinstance(tree, dict):
        return {k: _tree_map(fn, v) for k, v in tree.items()}
    if isinstance(tree, (tuple, list)):
        return type(tree)(_tree_map(fn, v) for v in tree)
    return fn(tree)


def _tree_leaves(tree: Any) -> list:
    out: list = []
    _tree_map(out.append, tree)
    return out


def _tree_map2(fn: Callable, a: Any, b: Any) -> Any:
    if isinstance(a, dict):
        return {k: _tree_map2(fn, a[k], b[k]) for k in a}
    if isinstance(a, (tuple, list)):
        return type(a)(_tree_map2(fn, x, y) for x, y in zip(a, b))
    return fn(a, b)


def default_collate(examples: Sequence[Any]) -> Any:
    """Stack per-example pytrees (dict/tuple/list/array) into one batch
    pytree with a new leading dimension."""
    first = examples[0]
    if isinstance(first, dict):
        return {k: default_collate([e[k] for e in examples]) for k in first}
    if isinstance(first, (tuple, list)):
        return type(first)(
            default_collate([e[i] for e in examples]) for i in range(len(first))
        )
    return np.stack([np.asarray(e) for e in examples])


# -- sources ------------------------------------------------------------------


class Source:
    """A random-access example store the pipeline can read in parallel.

    Implementations must be thread-safe: ``fetch``/``decode`` (or the
    vectorized ``fetch_batch`` fast path) are called concurrently from
    decode workers. Randomness must NOT live here — the loader derives
    every index and RNG from ``(seed, epoch, step)``.
    """

    def __len__(self) -> int:
        raise NotImplementedError

    def fetch(self, indices: np.ndarray) -> list:
        """Raw records for ``indices`` (the I/O stage)."""
        raise NotImplementedError

    def decode(self, raw: Any) -> Any:
        """One raw record -> one example pytree (the CPU stage)."""
        return raw

    def fetch_batch(self, indices: np.ndarray, out: Any | None = None) -> Any:
        """Optional vectorized fast path: whole batch in one call,
        assembled into ``out`` (a matching preallocated pytree) when
        given. Default: fetch + per-record decode + collate."""
        examples = [self.decode(r) for r in self.fetch(indices)]
        batch = default_collate(examples)
        if out is not None:
            return _tree_map2(lambda dst, src: np.copyto(dst, src) or dst, out, batch)
        return batch


class ArraySource(Source):
    """In-memory pytree of arrays sharing a leading example dimension —
    the whole-split path (``DataFeeder.numpy_arrays``) and the packed-LM
    path (:meth:`from_documents`)."""

    def __init__(self, arrays: Any):
        leaves = _tree_leaves(arrays)
        if not leaves:
            raise ValueError("ArraySource needs at least one array")
        n = len(leaves[0])
        if any(len(a) != n for a in leaves):
            raise ValueError("all arrays must share the leading dimension")
        self.arrays = _tree_map(np.asarray, arrays)
        self._n = n

    @classmethod
    def from_feeder(cls, feeder) -> "ArraySource":
        """Wrap a ``DataFeeder``'s materialized split: ``(x, y)`` with a
        target, bare ``x`` without."""
        x, y = feeder.numpy_arrays()
        return cls(x if y is None else (x, y))

    @classmethod
    def from_documents(
        cls, docs, seq_len: int, eos_id: int, pad_id: int = 0,
        drop_remainder: bool = True, key: str = "tokens",
    ) -> "ArraySource":
        """LM feed: greedy-pack ragged token documents via
        ``feed.pack_documents`` into ``(n, seq_len + 1)`` rows and serve
        them as ``{key: row}`` batches — the pretraining layout
        ``make_lm_train_step`` consumes."""
        from hops_tpu.featurestore.feed import pack_documents

        packed = pack_documents(docs, seq_len=seq_len, eos_id=eos_id,
                                pad_id=pad_id, drop_remainder=drop_remainder)
        return cls({key: packed})

    def __len__(self) -> int:
        return self._n

    def fetch(self, indices: np.ndarray) -> list:
        idx = np.asarray(indices)
        return [_tree_map(lambda a: a[i], self.arrays) for i in idx]

    def fetch_batch(self, indices: np.ndarray, out: Any | None = None) -> Any:
        idx = np.asarray(indices)
        if out is not None:
            return _tree_map2(
                lambda a, dst: np.take(a, idx, axis=0, out=dst),
                self.arrays, out,
            )
        return _tree_map(lambda a: np.take(a, idx, axis=0), self.arrays)


@dataclasses.dataclass
class StreamSpan:
    """One polled span of a streaming topic: decoded examples plus the
    byte-offset bookkeeping the exactly-once span ledger keys on."""

    values: list  #: decoded example values, poison records already skipped
    offsets: list[int]  #: per-record starting byte offset in the topic log
    first: int  #: span start (the pre-poll byte offset; poison bytes count)
    last: int  #: span end (exclusive byte offset — the next poll's start)
    watermark: float  #: newest event/producer timestamp in the span

    @property
    def records(self) -> int:
        return len(self.values)


class StreamingSource:
    """Unbounded pubsub-topic source for continuous training.

    Where the batch sources above are random-access over a FIXED index
    space, this tails a :mod:`~hops_tpu.messaging.pubsub` topic with a
    durable consumer group and yields :class:`StreamSpan`s — batches of
    decoded records annotated with their byte-offset range. The offset
    discipline is the write-through Materializer's, inverted for
    training: delivery is **at-least-once** (the group offset commits
    only after the trained span is durably recorded in the checkpoint
    sidecar ledger — see ``hops_tpu.pipeline.continuous``), and
    convergence to **effectively-once** comes from the span ledger
    deduping replayed offsets, not from the broker.

    Telemetry: ``hops_tpu_streaming_watermark_lag_seconds{stream}``
    (now minus the newest consumed event timestamp — the freshness of
    what training has seen; it rises while the trainer stalls or the
    topic idles) and ``hops_tpu_streaming_records_total{stream}``;
    byte lag rides the consumer's own
    ``hops_tpu_pubsub_consumer_lag{topic,group}`` gauge.

    ``decode(value)`` maps one record's ``value`` payload to an example
    (default: identity). Unparsable records were already skipped (and
    counted) by the consumer; records whose decode RAISES are skipped
    and counted as poison here — a poison record must stall neither the
    stream nor the offset.
    """

    def __init__(
        self,
        topic: str,
        group: str = "continuous-trainer",
        *,
        decode: Callable[[Any], Any] | None = None,
        event_time: str | None = None,
        from_beginning: bool = True,
        name: str | None = None,
    ):
        from hops_tpu.messaging import pubsub

        self.topic = topic
        self.group = group
        self.name = name or topic
        self._consumer = pubsub.Consumer(
            topic, group=group, from_beginning=from_beginning)
        self._decode = decode
        self._event_time = event_time
        self._watermark = 0.0
        labels = {"stream": self.name}
        self._m_watermark = REGISTRY.gauge(
            "hops_tpu_streaming_watermark_lag_seconds",
            "Now minus the newest event timestamp a streaming source has "
            "consumed — the training-side freshness twin of the online "
            "store's materialization lag",
            labels=("stream",)).labels(**labels)
        self._m_records = REGISTRY.counter(
            "hops_tpu_streaming_records_total",
            "Records a streaming source decoded and handed to training",
            labels=("stream",)).labels(**labels)
        self._m_poison = REGISTRY.counter(
            "hops_tpu_streaming_poison_decodes_total",
            "Records whose decode raised and were skipped by a streaming "
            "source (parse-level poison is counted by the consumer)",
            labels=("stream",)).labels(**labels)

    # -- offset discipline (the span ledger drives these) ---------------------

    @property
    def offset(self) -> int:
        """The consumer's in-memory position (uncommitted)."""
        return self._consumer.offset

    @offset.setter
    def offset(self, value: int) -> None:
        self._consumer.offset = int(value)

    def commit(self) -> None:
        """Durably commit the group offset — call ONLY after the spans
        up to :attr:`offset` are recorded in the span ledger."""
        self._consumer.commit()

    def lag(self) -> int:
        """Topic bytes not yet consumed (0 = caught up)."""
        return self._consumer.lag()

    def watermark(self) -> float:
        """Newest event timestamp consumed so far (0.0 = nothing yet)."""
        return self._watermark

    def watermark_lag_s(self) -> float:
        if not self._watermark:
            return 0.0
        return max(0.0, time.time() - self._watermark)

    # -- polling --------------------------------------------------------------

    def poll_span(self, max_records: int = 256) -> StreamSpan | None:
        """Poll the next span (None when nothing was consumed).
        ``first`` is the PRE-poll offset and ``last`` the post-poll
        offset, so ``[first, last)`` covers every consumed byte —
        including parse-level poison records the consumer skipped. A
        poll that consumed ONLY poison returns an empty span (zero
        values, nonzero byte range) rather than None: the caller's
        coverage bookkeeping must still see those bytes."""
        start = self._consumer.offset
        recs = self._consumer.poll_records(max_records)
        if not recs and self._consumer.offset == start:
            self._m_watermark.set(self.watermark_lag_s())
            return None
        first = start
        last = self._consumer.offset
        values: list = []
        offsets: list[int] = []
        for at, rec in recs:
            value = rec.get("value")
            ts = None
            if self._event_time is not None and isinstance(value, dict):
                ts = value.get(self._event_time)
            if ts is None:
                ts = rec.get("ts")
            if isinstance(ts, (int, float)):
                self._watermark = max(self._watermark, float(ts))
            if self._decode is not None:
                try:
                    value = self._decode(value)
                except Exception as e:  # noqa: BLE001 — poison must not wedge the stream
                    self._m_poison.inc()
                    log.warning(
                        "stream %s: skipping record at offset %d whose "
                        "decode raised (%s: %s)", self.name, at,
                        type(e).__name__, e)
                    continue
            values.append(value)
            offsets.append(at)
        self._m_records.inc(len(values))
        self._m_watermark.set(self.watermark_lag_s())
        return StreamSpan(values=values, offsets=offsets, first=first,
                          last=last, watermark=self._watermark)


class RecordIOSource(Source):
    """Sharded RecordIO files read through the native engine's batched
    gather (``native/recordio.read_batch``: pread fan-out, one copy per
    record).

    Global index space is the concatenation of the shards in the given
    order. Each decode worker keeps its own per-shard ``RecordReader``
    (``threading.local``): the native handle is pread-based and
    shareable, but the pure-Python fallback seeks a shared file object —
    per-thread readers are uniformly safe on both paths.
    """

    def __init__(self, paths: Sequence[str | Path],
                 decode: Callable[[bytes], Any] | None = None,
                 n_io_threads: int = 4):
        from hops_tpu.native.recordio import RecordReader

        self.paths = [str(p) for p in paths]
        if not self.paths:
            raise ValueError("RecordIOSource needs at least one shard path")
        self._reader_cls = RecordReader
        lengths = []
        for p in self.paths:
            with RecordReader(p) as r:
                lengths.append(len(r))
        #: per-shard record counts, and exclusive cumulative offsets for
        #: global-index -> (shard, local-index) mapping.
        self.shard_lengths = lengths
        self._starts = np.concatenate([[0], np.cumsum(lengths)])
        self._decode = decode
        self._n_io_threads = n_io_threads
        self._local = threading.local()

    def __len__(self) -> int:
        return int(self._starts[-1])

    def _reader(self, shard: int):
        cache = getattr(self._local, "readers", None)
        if cache is None:
            cache = self._local.readers = {}
        r = cache.get(shard)
        if r is None:
            r = cache[shard] = self._reader_cls(self.paths[shard])
        return r

    def fetch(self, indices: np.ndarray) -> list:
        idx = np.asarray(indices, np.int64)
        shard_ids = np.searchsorted(self._starts, idx, side="right") - 1
        out: list = [None] * len(idx)
        for shard in np.unique(shard_ids):
            pos = np.nonzero(shard_ids == shard)[0]
            local = idx[pos] - self._starts[shard]
            records = self._reader(int(shard)).read_batch(
                local.tolist(), n_threads=self._n_io_threads)
            for p, rec in zip(pos, records):
                out[int(p)] = rec
        return out

    def decode(self, raw: bytes) -> Any:
        return self._decode(raw) if self._decode is not None else raw


# -- reusable host buffers ----------------------------------------------------


class _BufferPool:
    """Free-list of preallocated batch pytrees matching one spec.

    Workers check buffers out concurrently; the consumer recycles them
    once a yielded batch falls ``ring`` yields behind (the validity
    window a ``prefetch_to_device`` consumer, which copies to device
    immediately, never notices)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._free: list = []  # guarded by: self._lock

    def take(self, template: Any) -> Any:
        with self._lock:
            if self._free:
                return self._free.pop()
        return _tree_map(lambda a: np.empty_like(a), template)

    def give(self, buf: Any) -> None:
        with self._lock:
            self._free.append(buf)


# -- the loader ---------------------------------------------------------------


class DataLoader:
    """Staged parallel batch pipeline over a :class:`Source`.

    ``num_workers=0`` is the synchronous reference path (decode inline
    on the consumer thread); ``num_workers>0`` runs decode/assembly in a
    bounded thread pool with at most ``queue_depth`` batches in flight.
    Both yield the identical stream for a given ``seed``.

    Per-host sharding: ``batch_size`` is the GLOBAL batch size;
    ``shard_index``/``shard_count`` (default: this process's
    ``jax.process_index()/process_count()`` when ``process_sharded=True``)
    select the rows this host materializes — disjoint across hosts
    because every host plans the same seed-derived order.

    ``transform(batch, rng)`` runs per batch inside the worker with a
    ``numpy.random.Generator`` derived from ``(seed, epoch, step)`` —
    deterministic under any worker count. Under ``reuse_buffers=True``
    an assembly buffer is only recycled when the transform's output
    does not alias it (checked via ``np.may_share_memory``), so
    pass-through leaves are safe — they just cost the pool a fresh
    allocation.

    ``reuse_buffers=True`` assembles batches into a pooled set of
    preallocated host arrays recycled ``queue_depth + 2`` yields later:
    zero steady-state allocation, but a yielded batch is only valid
    until then — fine for consumers that copy to device immediately
    (``device_iterator``), wrong for consumers that accumulate batches.
    """

    def __init__(
        self,
        source: Source,
        batch_size: int,
        *,
        num_epochs: int | None = 1,
        shuffle: bool = True,
        seed: int = 0,
        drop_remainder: bool = True,
        num_workers: int = 2,
        queue_depth: int = 4,
        transform: Callable[[Any, np.random.Generator], Any] | None = None,
        process_sharded: bool = False,
        shard_index: int | None = None,
        shard_count: int | None = None,
        reuse_buffers: bool = False,
        starved_threshold: float = 0.1,
        name: str = "default",
    ):
        if batch_size < 1:
            raise ValueError(f"batch_size must be >= 1, got {batch_size}")
        if num_workers < 0 or queue_depth < 1:
            raise ValueError("num_workers must be >= 0 and queue_depth >= 1")
        if process_sharded and (shard_index is None or shard_count is None):
            import jax

            shard_index = jax.process_index() if shard_index is None else shard_index
            shard_count = jax.process_count() if shard_count is None else shard_count
        self.shard_index = shard_index or 0
        self.shard_count = shard_count or 1
        if not 0 <= self.shard_index < self.shard_count:
            raise ValueError(
                f"shard_index {self.shard_index} out of range for "
                f"shard_count {self.shard_count}")
        if batch_size % self.shard_count:
            raise ValueError(
                f"global batch {batch_size} not divisible by "
                f"{self.shard_count} shards")
        if self.shard_count > 1 and not drop_remainder:
            raise ValueError(
                "sharded loading requires drop_remainder=True (every "
                "host must hold an equal, full shard)")
        if reuse_buffers and not drop_remainder:
            raise ValueError("reuse_buffers requires drop_remainder=True "
                             "(pooled buffers have one static shape)")
        n = len(source)
        if n < batch_size and drop_remainder:
            raise ValueError(
                f"source holds {n} examples < batch_size {batch_size} "
                "with drop_remainder=True: the stream would be empty")
        self.source = source
        self.batch_size = batch_size
        self.local_batch_size = batch_size // self.shard_count
        self.num_epochs = num_epochs
        self.shuffle = shuffle
        self.seed = seed
        self.drop_remainder = drop_remainder
        self.num_workers = num_workers
        self.queue_depth = queue_depth
        self.transform = transform
        self.reuse_buffers = reuse_buffers
        self.starved_threshold = starved_threshold
        self.process_sharded = process_sharded
        self.name = name

    @property
    def steps_per_epoch(self) -> int:
        n = len(self.source)
        if self.drop_remainder:
            return n // self.batch_size
        return -(-n // self.batch_size)

    def _epoch_order(self, epoch: int) -> np.ndarray:
        """The epoch's global example order — a pure function of
        ``(seed, epoch)``, so restore is O(1) (no sequential RNG stream
        to replay) and every host computes the same order."""
        n = len(self.source)
        if not self.shuffle:
            return np.arange(n)
        gen = np.random.Generator(
            np.random.PCG64(np.random.SeedSequence((self.seed, epoch))))
        return gen.permutation(n)

    def _batch_rng(self, epoch: int, step: int) -> np.random.Generator:
        return np.random.default_rng(
            np.random.SeedSequence((self.seed, epoch, step, 0x7F)))

    def __iter__(self) -> "LoaderIterator":
        return LoaderIterator(self)

    def iter_from(self, state: dict | None) -> "LoaderIterator":
        """An iterator resumed at ``state`` (a
        :meth:`LoaderIterator.state_dict` snapshot; ``None`` = fresh)."""
        return LoaderIterator(self, state=state)

    def __call__(self, start_step: int) -> "LoaderIterator":
        """``run_preemptible``'s callable-batches contract: the stream
        fast-forwarded to global step ``start_step``."""
        spe = self.steps_per_epoch
        state = {
            "version": _STATE_VERSION,
            "seed": self.seed,
            "epoch": start_step // spe,
            "step": start_step % spe,
        }
        return self.iter_from(state)

    def device_iterator(self, size: int = 2, sharding=None,
                        state: dict | None = None) -> Iterator:
        """The full pipeline: this loader behind
        ``feed.prefetch_to_device`` (``size`` batches in flight on
        device; ``sharding`` lands them sharded across the mesh).

        With ``process_sharded=True`` this host's batches are LOCAL
        shards of the global batch: they are assembled into global
        ``jax.Array``s via ``jax.make_array_from_process_local_data``
        (after the one-time :func:`feed.check_process_batch_layout`
        guard), exactly like ``DataFeeder.numpy_iterator(sharding=...)``
        — a plain ``device_put`` of the local shard against a global
        sharding would mis-place or permute rows on a multihost mesh.
        """
        from hops_tpu.featurestore.feed import prefetch_to_device

        it: Iterator = self.iter_from(state)
        if sharding is not None and self.process_sharded:
            it = self._assemble_global(it, sharding)
            sharding = None  # already global+committed; device_put is a no-op
        return prefetch_to_device(it, size=size, sharding=sharding, name=self.name)

    def _assemble_global(self, it: Iterator, sharding) -> Iterator:
        import jax

        from hops_tpu.featurestore.feed import check_process_batch_layout

        lo = self.shard_index * self.local_batch_size
        checked = False
        for batch in it:
            if not checked:
                leaf = _tree_leaves(batch)[0]
                check_process_batch_layout(
                    sharding, (self.batch_size,) + np.shape(leaf)[1:],
                    lo, self.local_batch_size)
                checked = True
            yield _tree_map(
                lambda a: jax.make_array_from_process_local_data(
                    sharding, np.asarray(a)),
                batch)


class LoaderIterator:
    """Ordered, bounded, resumable execution of a :class:`DataLoader`.

    The consumer thread plans batch tasks in stream order and keeps at
    most ``queue_depth`` of them in flight on the worker pool;
    ``__next__`` always completes the OLDEST task, so completion order
    cannot reorder the stream. ``state_dict()`` is the position of the
    next batch the consumer has not yet received — in-flight batches
    are deliberately not part of the state (they are re-derived on
    restore)."""

    def __init__(self, loader: DataLoader, state: dict | None = None):
        self.loader = loader
        self._epoch = 0
        self._step = 0
        if state is not None:
            self._load_state(state)
        self._order: np.ndarray | None = None
        self._order_epoch: int | None = None
        self._plan_epoch = self._epoch  # position of the NEXT task to submit
        self._plan_step = self._step
        self._pool = self._make_pool()
        self._pending: collections.deque[Future] = collections.deque()
        self._buffers = _BufferPool() if loader.reuse_buffers else None
        self._buffer_template: Any | None = None
        self._ring: collections.deque = collections.deque()
        self._last_return: float | None = None
        self._closed = False

        labels = {"pipeline": loader.name}
        self._m_queue = REGISTRY.gauge(
            "hops_tpu_feed_stage_queue_depth",
            "Batches queued per input-pipeline stage",
            labels=("pipeline", "stage"))
        self._m_inflight = self._m_queue.labels(stage="decode", **labels)
        self._m_ready = self._m_queue.labels(stage="ready", **labels)
        self._m_decode = REGISTRY.histogram(
            "hops_tpu_feed_decode_seconds",
            "Per-batch decode + assembly latency in the input pipeline",
            labels=("pipeline",)).labels(**labels)
        self._m_wait = REGISTRY.histogram(
            "hops_tpu_feed_wait_seconds",
            "Time the consumer blocked waiting for the next batch",
            labels=("pipeline",)).labels(**labels)
        self._m_steps = REGISTRY.counter(
            "hops_tpu_feed_pipeline_batches_total",
            "Batches yielded by the parallel input pipeline",
            labels=("pipeline",)).labels(**labels)
        self._m_starved = REGISTRY.counter(
            "hops_tpu_feed_starved_steps_total",
            "Steps whose feed wait exceeded the starvation threshold "
            "fraction of step wall time",
            labels=("pipeline",)).labels(**labels)

    def _make_pool(self) -> ThreadPoolExecutor | None:
        if self.loader.num_workers == 0:
            return None
        return ThreadPoolExecutor(
            max_workers=self.loader.num_workers,
            thread_name_prefix=f"hops-feed-{self.loader.name}")

    # -- state ---------------------------------------------------------------

    def _load_state(self, state: dict) -> None:
        if state.get("version") != _STATE_VERSION:
            raise ValueError(
                f"loader state version {state.get('version')!r} != "
                f"{_STATE_VERSION}")
        if state.get("seed") != self.loader.seed:
            raise ValueError(
                f"loader state was snapshotted under seed "
                f"{state.get('seed')!r}, this loader uses "
                f"{self.loader.seed!r}: the restored stream would differ")
        self._epoch = int(state["epoch"])
        self._step = int(state["step"])

    def state_dict(self) -> dict:
        """JSON-able snapshot of the next-unyielded position. Save it
        alongside the model checkpoint; ``iter_from``/``load_state_dict``
        replays the exact remaining stream."""
        return {
            "version": _STATE_VERSION,
            "seed": self.loader.seed,
            "epoch": self._epoch,
            "step": self._step,
        }

    def load_state_dict(self, state: dict) -> None:
        """Reposition this iterator (discarding any in-flight work).
        Works on an exhausted iterator too: repositioning reopens it
        (fresh worker pool) so the restored stream actually replays."""
        self._cancel_pending()
        self._load_state(state)
        self._plan_epoch, self._plan_step = self._epoch, self._step
        self._last_return = None
        if self._closed:
            self._closed = False
            self._pool = self._make_pool()

    # -- planning ------------------------------------------------------------

    def _next_task(self) -> tuple[int, int, np.ndarray] | None:
        """The next ``(epoch, step, local indices)`` in stream order, or
        None at end of stream."""
        ld = self.loader
        spe = ld.steps_per_epoch
        while True:
            if ld.num_epochs is not None and self._plan_epoch >= ld.num_epochs:
                return None
            if self._plan_step >= spe:
                self._plan_epoch += 1
                self._plan_step = 0
                continue
            epoch, step = self._plan_epoch, self._plan_step
            if self._order_epoch != epoch:
                self._order = ld._epoch_order(epoch)
                self._order_epoch = epoch
            base = step * ld.batch_size + ld.shard_index * ld.local_batch_size
            idx = self._order[base:base + ld.local_batch_size]
            self._plan_step += 1
            return epoch, step, idx

    # -- production ----------------------------------------------------------

    def _produce(self, epoch: int, step: int, idx: np.ndarray) -> Any:
        faultinject.fire("loader.read")  # chaos: transient read failure
        ld = self.loader
        t0 = time.monotonic()
        out = None
        if self._buffers is not None and self._buffer_template is not None:
            out = self._buffers.take(self._buffer_template)
        batch = ld.source.fetch_batch(idx, out=out)
        if self._buffers is not None and self._buffer_template is None:
            # Captured PRE-transform (the spec pooled buffers must
            # match). Benign race: two workers may both build one.
            self._buffer_template = _tree_map(np.empty_like, batch)
        if ld.transform is not None:
            transformed = ld.transform(batch, ld._batch_rng(epoch, step))
            if out is not None:
                # Recycle the assembly buffer — unless the transform
                # passed any of it through (a view/pass-through leaf):
                # recycling would let the next assembly overwrite data
                # the consumer still holds. may_share_memory is the
                # fast conservative test; a false positive only costs
                # one fresh allocation.
                out_leaves = _tree_leaves(out)
                aliased = any(
                    isinstance(t, np.ndarray)
                    and any(np.may_share_memory(t, o) for o in out_leaves)
                    for t in _tree_leaves(transformed)
                )
                if not aliased:
                    self._buffers.give(out)
            batch = transformed
        self._m_decode.observe(time.monotonic() - t0)
        return batch

    def _submit(self) -> None:
        # Synchronous mode produces strictly on demand: planning ahead
        # on the consumer thread would only front-load latency and hold
        # extra batches live without any overlap to buy.
        depth = self.loader.queue_depth if self._pool is not None else 1
        while len(self._pending) < depth:
            task = self._next_task()
            if task is None:
                return
            if self._pool is None:
                f: Future = Future()
                f.set_result(self._produce(*task))
            else:
                f = self._pool.submit(self._produce, *task)
            self._pending.append(f)

    def _cancel_pending(self) -> None:
        for f in self._pending:
            f.cancel()
        self._pending.clear()

    # -- consumption ---------------------------------------------------------

    def __iter__(self) -> "LoaderIterator":
        return self

    def __next__(self) -> Any:
        if self._closed:
            raise StopIteration
        t0 = time.monotonic()
        consumer_s = t0 - self._last_return if self._last_return is not None else None
        # Submit inside the wait window: in synchronous mode this IS
        # the on-demand decode of the batch being returned (so feed
        # wait measures the right batch and nothing is produced ahead);
        # in threaded mode it is a cheap non-blocking enqueue.
        self._submit()
        if not self._pending:
            self.close()
            raise StopIteration
        batch = self._pending.popleft().result()
        if self._pool is not None:
            self._submit()  # refill before returning: keep workers busy
        now = time.monotonic()
        wait_s = now - t0
        self._m_wait.observe(wait_s)
        self._m_inflight.set(len(self._pending))
        self._m_ready.set(sum(1 for f in self._pending if f.done()))
        self._m_steps.inc()
        if consumer_s is not None:
            # Starved step: the consumer's wall time between batches was
            # dominated (beyond the threshold fraction) by feed wait —
            # the host pipeline, not the device step, set the pace.
            step_wall = consumer_s + wait_s
            if step_wall > 0 and wait_s > self.loader.starved_threshold * step_wall:
                self._m_starved.inc()
        # Advance the consumer position AFTER the batch is in hand: the
        # snapshot must never claim a batch the consumer was not given.
        self._step += 1
        if self._step >= self.loader.steps_per_epoch:
            self._epoch += 1
            self._step = 0
        if self._buffers is not None and self.loader.transform is None:
            # Without a transform the yielded batch IS a pool buffer
            # (recycled once it falls out of the validity window); with
            # one, _produce already recycled the assembly buffer and
            # the yield is fresh arrays.
            self._ring.append(batch)
            if len(self._ring) > self.loader.queue_depth + 2:
                self._buffers.give(self._ring.popleft())
        self._last_return = time.monotonic()
        return batch

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._cancel_pending()
        if self._pool is not None:
            self._pool.shutdown(wait=False, cancel_futures=True)
        self._m_inflight.set(0)
        self._m_ready.set(0)

    def __enter__(self) -> "LoaderIterator":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self) -> None:  # best-effort: don't leak worker threads
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass
