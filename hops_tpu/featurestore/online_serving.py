"""Online feature serving: sharded stores, write-through, request-time joins.

The reference platform served features at request time from MySQL NDB
behind hsfs (``td.get_serving_vector`` over JDBC prepared statements,
PAPER.md L0) and kept the online values consistent with training-time
feature groups via Kafka-fed materialization jobs. This module is that
layer for the TPU build, in three pieces:

- :class:`ShardedOnlineStore` — N :class:`~hops_tpu.featurestore.online.
  OnlineStore` shards keyed by ``crc32(primary key) % N``. Point reads
  ride each backend's reader-safe path (never the writer lock), rows
  carry an event-time stamp for TTL eviction and idempotent upserts,
  and :meth:`~ShardedOnlineStore.snapshot` / :meth:`~ShardedOnlineStore.
  restore_snapshot` write/verify checkpoint-layer integrity manifests
  (sizes + SHA-256) so a serving replica can warm-start from a known-
  good snapshot instead of replaying the topic from zero.
- :class:`Materializer` — the write-through daemon: one consumer thread
  tails a ``messaging.pubsub`` topic and upserts each record's row into
  the store. At-least-once (offsets commit *after* the batch flush) with
  idempotent, event-time-guarded upserts, so replays and duplicates
  converge to the same state; the max materialized event time is the
  store's freshness watermark.
- :class:`FeatureJoinPredictor` — the serving-time join step: requests
  carry only entity IDs; the predictor batch-multi-gets across every
  configured feature group's shards, joins the rows into model-ready
  vectors (missing-key policy: ``default`` | ``reject`` |
  ``passthrough``) and hands them to the wrapped predictor. Wired into
  ``modelrepo.serving`` via ``create_or_update(..., feature_config=)``,
  upstream of the existing ``DynamicBatcher`` (coalesced entity batches
  become one multi-get).

Failure semantics: lookups run under the ``online.lookup`` fault point
with an optional deadline and a circuit breaker per shard — a dead
shard degrades to missing keys (the policy decides what that means),
it never fails the request. The daemon runs under
``online.materialize`` and outlives transient broker/store faults with
computed backoff; while it is down the freshness-lag gauge keeps rising
because lag is re-derived from the stalled watermark at every lookup.

Tail semantics (docs/operations.md "Tail latency & QoS"): multi-shard
lookups FAN OUT in parallel on the store's worker pool instead of
probing shards sequentially — one slow shard no longer eats the whole
deadline, it eats only its own keys. A shard attempt still unanswered
after the store's recent p95 lookup latency is HEDGED (a second
attempt on the same reader-safe backend races it; first result wins,
the loser is abandoned without a breaker strike — injected stalls and
page-cache hiccups lose to the hedge, a genuinely dead shard still
feeds its breaker via the deadline). Each attempt passes the
``shard.lookup`` fault point keyed by shard index, so a gray
(slow-not-dead) shard is deterministically injectable. Under brownout
(level >= DEGRADE) the feature-join layer shrinks the deadline it
passes here, converting slow-shard waits into served defaults.

Metrics (docs/operations.md "Online feature serving"):
``hops_tpu_online_lookup_seconds`` / ``hops_tpu_online_join_seconds`` /
``hops_tpu_online_request_seconds`` per-stage latency histograms,
``hops_tpu_online_lookup_total{store,result}`` hit/miss/expired/error,
``hops_tpu_online_freshness_lag_seconds``,
``hops_tpu_online_materialized_rows_total``,
``hops_tpu_online_evicted_rows_total``,
``hops_tpu_online_missing_keys_total{model,policy}``.
"""

from __future__ import annotations

import json
import os
import threading
import time
import zlib
from pathlib import Path
from typing import Any, Callable, Iterator

import pandas as pd

from hops_tpu.featurestore import storage
from hops_tpu.featurestore.online import OnlineStore, _key_of
from hops_tpu.messaging import pubsub
from hops_tpu.runtime import faultinject, qos, wirecodec
from hops_tpu.runtime.checkpoint import CheckpointCorruptError, _file_sha256
from hops_tpu.runtime.logging import get_logger
from hops_tpu.runtime.resilience import CircuitBreaker, with_deadline
from hops_tpu.telemetry import tracing
from hops_tpu.telemetry.metrics import REGISTRY

log = get_logger(__name__)

#: Reserved event-time column (epoch seconds) stamped onto every stored
#: row — the TTL clock and the idempotent-upsert staleness guard. Rows
#: handed back to callers have it stripped.
EVENT_TS_COL = "_hops_event_ts"

MISSING_POLICIES = ("default", "reject", "passthrough")

_m_lookup_seconds = REGISTRY.histogram(
    "hops_tpu_online_lookup_seconds",
    "Online-store point-lookup latency per shard batch",
    labels=("store",),
)
_m_lookup_total = REGISTRY.counter(
    "hops_tpu_online_lookup_total",
    "Online-store key lookups by result (hit | miss | expired | error)",
    labels=("store", "result"),
)
_m_join_seconds = REGISTRY.histogram(
    "hops_tpu_online_join_seconds",
    "Feature-join latency (all group lookups + vector assembly) per "
    "request batch",
    labels=("model",),
)
_m_request_seconds = REGISTRY.histogram(
    "hops_tpu_online_request_seconds",
    "End-to-end feature-joined predict latency (lookup + join + model)",
    labels=("model",),
)
_m_missing_keys = REGISTRY.counter(
    "hops_tpu_online_missing_keys_total",
    "Features absent from the online store at join time, by the policy "
    "that handled them",
    labels=("model", "policy"),
)
_m_freshness = REGISTRY.gauge(
    "hops_tpu_online_freshness_lag_seconds",
    "Now minus the store's last materialized event-time watermark "
    "(re-derived at every lookup, so it rises while the daemon is down)",
    labels=("store",),
)
_m_materialized = REGISTRY.counter(
    "hops_tpu_online_materialized_rows_total",
    "Rows upserted by write-through materialization, per store",
    labels=("store",),
)
_m_evicted = REGISTRY.counter(
    "hops_tpu_online_evicted_rows_total",
    "Rows deleted by a TTL eviction sweep, per store",
    labels=("store",),
)
_m_shard_hedges = REGISTRY.counter(
    "hops_tpu_online_shard_hedges_total",
    "Straggler shard lookups hedged with a second attempt, per store",
    labels=("store",),
)


def _shard_of(key: str, n: int) -> int:
    # crc32, not hash(): stable across processes and PYTHONHASHSEED, so
    # a writer daemon and a serving replica agree on every row's shard.
    return zlib.crc32(key.encode()) % n


class GenerationSupersededError(RuntimeError):
    """A shard server refused the request with a typed 410: the stamped
    ``X-Hops-Generation`` token supersedes the server's own — the
    endpoint is a ZOMBIE, a unit whose slot was re-placed while its
    host was partitioned. Deliberately not an ``OSError``: the shard is
    healthy and answering, so this must bypass the transport-failure
    breaker accounting (no strike — striking would eject the slot while
    the placement layer is already healing it) and degrade to missing
    keys only."""


class _RemoteShard:
    """Client proxy for one placed shard server (``jobs.placement.
    shardd``), shaped exactly like :class:`~hops_tpu.featurestore.
    online.OnlineStore` where the sharded store touches it.

    Transport failures and non-200 answers raise ``OSError`` subclasses
    — precisely what ``multi_get``'s per-shard breaker/hedge/deadline
    machinery already catches, so placed shards inherit the local tail
    semantics without a line of change there. The one exception is a
    410, which raises :class:`GenerationSupersededError` (see its docs).

    ``generation_token`` stamps every exchange with the slot's identity
    (``X-Hops-Generation``): a static ``"slot:gen"`` string, or a
    zero-arg callable re-read per request so the stamp tracks the
    placement client's LIVE generation counter — after a re-placement
    bump, in-flight lookups immediately carry the new token and any
    zombie still holding the old identity 410s.
    """

    def __init__(self, endpoint: str, *, timeout_s: float = 5.0,
                 generation_token: str | Callable[[], str] | None = None):
        from hops_tpu.runtime.httpclient import HTTPPool

        self.endpoint = endpoint.rstrip("/")
        self.timeout_s = float(timeout_s)
        self._pool = HTTPPool(max_idle_per_host=4, identity="store-client")
        self._generation_token = generation_token
        #: Codecs the shard server advertised at handshake; ``None``
        #: until the first ``get_many`` probes ``/healthz``. A server
        #: that predates the handshake field is pinned JSON-only.
        self._codecs: frozenset[str] | None = None

    def _exchange(self, method: str, path: str,
                  payload: dict | None = None,
                  headers: dict[str, str] | None = None,
                  ) -> tuple[bytes, dict]:
        # Shard RPC *requests* (key lists, row batches to put) stay
        # JSON: they are small and schema-free; only the get_many
        # response rides the packed codec.
        body = (json.dumps(payload, default=str).encode()  # graftlint: disable=json-on-hot-wire
                if payload is not None else None)
        hdrs = dict(headers or {})
        if body:
            hdrs.setdefault("Content-Type", "application/json")
        tok = self._generation_token
        if callable(tok):
            tok = tok()
        if tok:
            # Same literal as jobs.placement.client.GENERATION_HEADER
            # (not imported: the featurestore stays decoupled from the
            # placement package's import chain).
            hdrs.setdefault("X-Hops-Generation", tok)
        code, data, resp_hdrs = self._pool.request(
            method, f"{self.endpoint}{path}", body, hdrs or None,
            timeout_s=self.timeout_s,
        )
        if code == 410:
            raise GenerationSupersededError(
                f"shard server {self.endpoint}{path} answered 410 "
                f"(superseded generation — zombie endpoint, stamped "
                f"{tok!r})")
        if code != 200:
            raise ConnectionError(
                f"shard server {self.endpoint}{path} answered {code}")
        return data, resp_hdrs

    def _json_exchange(self, method: str, path: str,
                       payload: dict | None = None) -> dict:
        # Control-plane verbs (healthz/stats/put/delete/scan) are
        # JSON-only by contract; get_many negotiates separately.
        data, _ = self._exchange(method, path, payload)
        return json.loads(data) if data else {}  # graftlint: disable=json-on-hot-wire

    def _handshake(self) -> frozenset[str]:
        """Learn the server's codecs from ``/healthz`` (cached).

        A non-200 answer pins the shard JSON-only (the request path will
        surface the shard's real health); transport errors propagate so
        the caller's breaker/hedge machinery sees them.
        """
        if self._codecs is None:
            try:
                health = self._json_exchange("GET", "/healthz")
            except ConnectionError:
                return frozenset({"json"})  # unhealthy answer — don't cache
            self._codecs = frozenset(health.get("codecs") or ("json",))
        return self._codecs

    def get_many(self, pk_values_list: list[list[Any]]) -> list[dict | None]:
        accept = None
        if "packed" in self._handshake():
            accept = {"Accept": wirecodec.MEDIA_TYPE}
        data, hdrs = self._exchange("POST", "/get_many",
                                    {"pks": pk_values_list}, accept)
        ctype = next((v for k, v in hdrs.items()
                      if k.lower() == "content-type"), "")
        if wirecodec.MEDIA_TYPE in ctype:
            try:
                return wirecodec.decode_rows(data)
            except wirecodec.WireCodecError as e:
                # Fail closed: a malformed frame is breaker food, never
                # silently-wrong rows.
                raise ConnectionError(
                    f"shard server {self.endpoint}/get_many sent a bad "
                    f"packed frame: {e}") from None
        # Negotiated JSON fallback: the shard either answered a JSON
        # Content-Type or predates the packed codec entirely.
        return json.loads(data)["rows"] if data else []  # graftlint: disable=json-on-hot-wire

    def put_dataframe(self, df: pd.DataFrame, primary_key: list[str]) -> int:
        recs = df.to_dict(orient="records")
        return int(self._json_exchange("POST", "/put",
                                  {"records": recs}).get("applied", 0))

    def delete_keys(self, df: pd.DataFrame, primary_key: list[str]) -> None:
        self._json_exchange("POST", "/delete",
                            {"records": df.to_dict(orient="records")})

    def scan(self) -> Iterator[dict]:
        yield from self._json_exchange("GET", "/scan")["rows"]

    def count(self) -> int:
        return int(self._json_exchange("GET", "/stats")["rows"])

    def close(self) -> None:
        self._pool.close()


class ShardedOnlineStore:
    """N ``OnlineStore`` shards keyed by ``crc32(primary key) % N``.

    One instance per (feature group, version). Writers route each row to
    its shard and take only that shard's writer lock; point reads use
    the backends' reader-safe path (sqlite WAL snapshot connections —
    see ``online.OnlineStore``), so serving lookups never queue behind a
    materialization flush. ``ttl_s`` bounds row age: expired rows read
    as misses immediately and :meth:`evict_expired` reclaims them.
    """

    def __init__(
        self,
        name: str,
        version: int = 1,
        *,
        primary_key: list[str],
        shards: int = 4,
        ttl_s: float | None = None,
        root: str | Path | None = None,
        breaker_failures: int = 5,
        breaker_reset_s: float = 5.0,
        fanout: bool = True,
        hedge: bool = True,
        endpoints: list[str] | None = None,
        units: list[Any] | None = None,
        placement: Any = None,
        rpc_timeout_s: float = 5.0,
    ):
        if not primary_key:
            raise ValueError("ShardedOnlineStore needs a primary_key")
        if units is not None and endpoints is not None:
            raise ValueError("units= and endpoints= are exclusive: units "
                             "derive their own endpoints")
        tokens: list[Any] = []
        if units is not None:
            # PLACED mode by PlacedUnit: derive each shard's endpoint
            # AND its generation identity. With a placement client the
            # token is a live read of the slot's current generation
            # (tracks re-placement bumps mid-flight); without one it is
            # pinned to the unit's minted generation.
            if not units:
                raise ValueError("units= must name at least one shard unit")
            endpoints = [f"http://{u.address}:{u.port}" for u in units]
            for u in units:
                slot = getattr(u, "slot", None)
                if slot is None:
                    tokens.append(None)
                elif placement is not None:
                    tokens.append(
                        lambda s=slot: f"{s}:{placement.current_generation(s)}")
                else:
                    tokens.append(f"{slot}:{u.generation}")
        if endpoints is not None and not endpoints:
            raise ValueError("endpoints= must name at least one shard server")
        if shards < 1:
            raise ValueError(f"shards must be >= 1, got {shards}")
        self.name = name
        self.version = int(version)
        self.label = f"{name}_{version}"
        self.primary_key = [k.lower() for k in primary_key]
        self.ttl_s = ttl_s
        d = Path(root) if root is not None else storage.feature_store_root() / "online"
        d.mkdir(parents=True, exist_ok=True)
        self._dir = d
        if endpoints is not None:
            # PLACED mode: each shard is a remote shardd server (placed
            # on some host by the placement layer); the shard count IS
            # the endpoint list — the placement that spawned the
            # servers owns the layout, so the local meta file is not
            # consulted. Everything else (crc32 routing, per-shard
            # breakers, fan-out, hedging) is identical to local mode.
            shards = len(endpoints)
            if not tokens:
                tokens = [None] * shards
            self._shards: list[Any] = [
                _RemoteShard(ep, timeout_s=rpc_timeout_s, generation_token=tok)
                for ep, tok in zip(endpoints, tokens)
            ]
        else:
            # The shard layout is part of the data: crc32(key) % N only
            # finds a row under the N it was written with. The first
            # opener persists its layout; later openers (serving
            # replicas, other processes) ADOPT it — a differing
            # ``shards=`` argument would otherwise silently read misses
            # for most keys.
            meta_path = d / f"{self.label}.meta.json"
            if meta_path.exists():
                meta = json.loads(meta_path.read_text())
                if [k.lower() for k in meta.get("primary_key", [])] != self.primary_key:
                    raise ValueError(
                        f"online store {self.label} was created with primary key "
                        f"{meta.get('primary_key')}, not {self.primary_key}"
                    )
                if int(meta["shards"]) != int(shards):
                    log.info(
                        "online store %s: adopting persisted shard count %d "
                        "(requested %d)", self.label, meta["shards"], shards,
                    )
                shards = int(meta["shards"])
            else:
                tmp = meta_path.with_suffix(".meta.tmp")
                tmp.write_text(json.dumps(
                    {"shards": int(shards), "primary_key": self.primary_key}
                ))
                os.replace(tmp, meta_path)
            self._shards = [
                OnlineStore(d / f"{self.label}.shard{i}")
                for i in range(int(shards))
            ]
        # One breaker per shard: a dead shard fails fast (its keys read
        # as missing) instead of stalling every request that hashes into
        # it; the half-open probe heals it when the backend recovers.
        self._breakers = [
            CircuitBreaker(
                name=f"online-{self.label}-shard{i}",
                failure_threshold=breaker_failures,
                reset_timeout_s=breaker_reset_s,
            )
            for i in range(int(shards))
        ]
        # One per shard: serializes upsert_rows' read-check-merge-write
        # cycle (the shard's own writer lock covers only each put).
        self._upsert_locks = [threading.Lock() for _ in range(int(shards))]
        # Parallel fan-out + straggler hedging for multi-shard reads.
        self.fanout = bool(fanout) and int(shards) > 1
        self.hedge_stragglers = bool(hedge)
        self._pool_lock = threading.Lock()
        self._pool = None  # guarded by: self._pool_lock (lazy: many stores never multi-shard-read)
        # Recent successful shard-lookup latencies — the hedge timer's
        # p95 source. guarded by: self._pool_lock.
        self._recent_lookup_s: "list[float]" = []
        self._meta_lock = threading.Lock()
        self._watermark: float | None = None  # guarded by: self._meta_lock
        # (file value, monotonic read time): the persisted watermark is
        # re-read at most every 50 ms — freshness lag is a seconds-scale
        # signal and an uncached read_text per lookup was ~15% of the
        # join path on the CPU tier.
        self._wm_cache: tuple[float | None, float] | None = None  # guarded by: self._meta_lock
        self._m_lookup = _m_lookup_seconds.labels(store=self.label)
        self._m_hit = _m_lookup_total.labels(store=self.label, result="hit")
        self._m_miss = _m_lookup_total.labels(store=self.label, result="miss")
        self._m_expired = _m_lookup_total.labels(store=self.label, result="expired")
        self._m_error = _m_lookup_total.labels(store=self.label, result="error")
        self._m_fresh = _m_freshness.labels(store=self.label)
        self._m_evict = _m_evicted.labels(store=self.label)

    # -- keys -----------------------------------------------------------------

    @property
    def n_shards(self) -> int:
        return len(self._shards)

    def _pk_values(self, entry: Any) -> list[Any]:
        if isinstance(entry, dict):
            lowered = {str(k).lower(): v for k, v in entry.items()}
            try:
                return [lowered[k] for k in self.primary_key]
            except KeyError as e:
                raise ValueError(
                    f"entity entry {entry!r} is missing primary key "
                    f"{e.args[0]!r} of store {self.label}"
                ) from None
        return list(entry)  # positional, in primary_key order

    def shard_index(self, entry: Any) -> int:
        return _shard_of(_key_of(self._pk_values(entry)), self.n_shards)

    # -- write path -----------------------------------------------------------

    def put_dataframe(self, df: pd.DataFrame, event_ts: str | None = None) -> int:
        """Route a frame's rows to their shards and upsert (see
        :meth:`upsert_rows`)."""
        return self.upsert_rows(df.to_dict(orient="records"), event_ts=event_ts)

    def upsert_rows(self, rows: list[dict], event_ts: str | None = None) -> int:
        """Idempotent keyed upsert-merge; returns rows applied.

        ``event_ts`` names the column carrying each row's event time
        (epoch seconds); absent, rows are stamped with now. A row whose
        event time is OLDER than the stored row's is skipped, and
        duplicates WITHIN the batch fold newest-last before the write —
        so at-least-once delivery, replays, and out-of-order topics
        (across and inside poll batches) all converge to
        last-event-time-wins, and re-running a drained materializer is
        a no-op. A partial row (a subset of columns) merges into the
        stored row rather than replacing it: absent features stay
        served instead of silently turning into misses — and never into
        NaN padding.
        """
        now = time.time()
        # Fold the batch per key in ascending (event time, batch order)
        # before touching any shard: an older duplicate BEHIND a newer
        # row in the same batch must not win just because it was
        # applied later.
        folded: dict[str, dict] = {}
        order: list[str] = []
        max_ts: float | None = None
        for row in rows:
            rec = {str(k).lower(): v for k, v in row.items()}
            ts = now
            if event_ts is not None and rec.get(event_ts.lower()) is not None:
                ts = float(rec[event_ts.lower()])
            rec[EVENT_TS_COL] = ts
            key = _key_of(self._pk_values(rec))
            cur = folded.get(key)
            if cur is None:
                folded[key] = rec
                order.append(key)
            elif ts >= cur[EVENT_TS_COL]:
                folded[key] = {**cur, **rec}
            else:
                folded[key] = {**rec, **cur}
            max_ts = ts if max_ts is None else max(max_ts, ts)
        buckets: dict[int, list[dict]] = {}
        for key in order:
            buckets.setdefault(_shard_of(key, self.n_shards), []).append(folded[key])
        applied = 0
        for idx in sorted(buckets):
            shard = self._shards[idx]
            # The read-check-merge-write cycle must be atomic per shard:
            # without this lock two concurrent upserters (the daemon and
            # a snapshot restore, say) can both read the old row, both
            # pass the staleness guard, and the LAST writer — possibly
            # the older one — wins.
            with self._upsert_locks[idx]:
                currents = shard.get_many(
                    [self._pk_values(rec) for rec in buckets[idx]]
                )
                fresh = []
                for rec, current in zip(buckets[idx], currents):
                    if current is not None:
                        if current.get(EVENT_TS_COL, 0.0) > rec[EVENT_TS_COL]:
                            continue  # stale replay: the store already moved past it
                        rec = {**current, **rec}  # partial update merges
                    fresh.append(rec)
                # Group by column signature: one put per homogeneous
                # slice, so a mixed batch never NaN-pads missing columns
                # into stored rows (NaN would read back as a HIT and
                # bypass the missing-key policy).
                by_cols: dict[frozenset, list[dict]] = {}
                for rec in fresh:
                    by_cols.setdefault(frozenset(rec), []).append(rec)
                for recs in by_cols.values():
                    applied += shard.put_dataframe(
                        pd.DataFrame(recs), self.primary_key
                    )
        if max_ts is not None:
            self.set_watermark(max_ts)
        return applied

    def delete_keys(self, df: pd.DataFrame) -> None:
        buckets: dict[int, list[dict]] = {}
        for row in df.to_dict(orient="records"):
            rec = {str(k).lower(): v for k, v in row.items()}
            key = _key_of(self._pk_values(rec))
            buckets.setdefault(_shard_of(key, self.n_shards), []).append(rec)
        for idx, recs in buckets.items():
            self._shards[idx].delete_keys(pd.DataFrame(recs), self.primary_key)

    # -- read path ------------------------------------------------------------

    @staticmethod
    def _strip(row: dict) -> dict:
        return {k: v for k, v in row.items() if k != EVENT_TS_COL}

    def _expired(self, row: dict, now: float) -> bool:
        if self.ttl_s is None:
            return False
        return now - float(row.get(EVENT_TS_COL, now)) > self.ttl_s

    @staticmethod
    def _shard_lookup(shard: OnlineStore, pk_lists: list[list[Any]]) -> list[dict | None]:
        return shard.get_many(pk_lists)

    def get(self, entry: Any) -> dict | None:
        """Point lookup; None on miss/expiry/shard failure (the caller's
        missing-key policy decides what None means)."""
        return self.multi_get([entry])[0]

    def multi_get(
        self, entries: list[Any], deadline_s: float | None = None
    ) -> list[dict | None]:
        """Batched point lookup across shards, results in entry order.

        Never raises for a failing shard: a lookup error, a
        ``deadline_s`` overrun, or an open breaker turns that shard's
        keys into misses (``result="error"`` on the lookup counter) —
        serving degrades to the missing-key policy instead of failing
        the request.

        With multiple shards touched (and ``fanout`` on, the default),
        shard lookups run in PARALLEL under one shared deadline, and a
        straggler shard is hedged with a second attempt after the
        store's recent p95 lookup latency — see the module docstring's
        tail semantics. Single-shard batches keep the inline path.
        """
        out: list[dict | None] = [None] * len(entries)
        buckets: dict[int, list[tuple[int, list[Any]]]] = {}
        for pos, entry in enumerate(entries):
            pk = self._pk_values(entry)
            buckets.setdefault(_shard_of(_key_of(pk), self.n_shards), []).append(
                (pos, pk)
            )
        now = time.time()
        if self.fanout and len(buckets) > 1:
            self._multi_get_fanout(buckets, out, now, deadline_s)
        else:
            for idx in sorted(buckets):
                items = buckets[idx]
                shard, breaker = self._shards[idx], self._breakers[idx]
                if not breaker.allow():
                    self._m_error.inc(len(items))
                    continue
                t0 = time.perf_counter()
                try:
                    # Chaos points: a lookup error/latency here must
                    # surface as missing keys + breaker pressure,
                    # never a 5xx.
                    faultinject.fire("online.lookup")
                    faultinject.fire("shard.lookup", key=idx)
                    pk_lists = [pk for _, pk in items]
                    if deadline_s is not None:
                        rows = with_deadline(
                            self._shard_lookup, deadline_s, shard, pk_lists,
                            op="online.lookup",
                        )
                    else:
                        rows = self._shard_lookup(shard, pk_lists)
                except GenerationSupersededError as e:
                    # Zombie endpoint (typed 410): degrade to missing
                    # keys with NO breaker strike — the shard answered
                    # healthily, it is the placement layer's job to
                    # swap the endpoint, not the breaker's to eject it.
                    self._m_error.inc(len(items))
                    log.warning(
                        "online store %s shard %d superseded: %s",
                        self.label, idx, e,
                    )
                    continue
                except Exception as e:  # noqa: BLE001 — a dead shard degrades, never raises
                    breaker.record_failure()
                    self._m_error.inc(len(items))
                    log.warning(
                        "online store %s shard %d lookup failed: %s: %s",
                        self.label, idx, type(e).__name__, e,
                    )
                    continue
                breaker.record_success()
                elapsed = time.perf_counter() - t0
                self._m_lookup.observe(elapsed)
                self._note_lookup_latency(elapsed)
                self._fill_rows(out, items, rows, now)
        self._observe_freshness()
        return out

    def _fill_rows(self, out: list, items: list, rows: list,
                   now: float) -> None:
        for (pos, _), row in zip(items, rows):
            if row is None:
                self._m_miss.inc()
            elif self._expired(row, now):
                self._m_expired.inc()
            else:
                self._m_hit.inc()
                out[pos] = self._strip(row)

    # -- parallel fan-out with straggler hedging ------------------------------

    def _executor(self):
        from concurrent.futures import ThreadPoolExecutor

        with self._pool_lock:
            if self._pool is None:
                # 2x shards: a full fan-out plus one hedge per shard
                # can run without queueing behind each other.
                self._pool = ThreadPoolExecutor(
                    max_workers=min(2 * self.n_shards, 16),
                    thread_name_prefix=f"online-{self.label}",
                )
            return self._pool

    def _note_lookup_latency(self, seconds: float) -> None:
        with self._pool_lock:
            self._recent_lookup_s.append(seconds)
            if len(self._recent_lookup_s) > 256:
                del self._recent_lookup_s[:128]

    def _hedge_delay_s(self) -> float | None:
        """p95 of recent successful shard lookups — the straggler
        threshold. None (no hedging) until enough history exists."""
        with self._pool_lock:
            window = sorted(self._recent_lookup_s[-128:])
        if len(window) < 8:
            return None
        return max(window[min(len(window) - 1, int(len(window) * 0.95))],
                   0.002)

    def _multi_get_fanout(
        self,
        buckets: dict[int, list[tuple[int, list[Any]]]],
        out: list,
        now: float,
        deadline_s: float | None,
    ) -> None:
        cv = threading.Condition()
        results: dict[int, tuple[bool, Any, float]] = {}  # guarded by: cv

        def attempt(idx: int, pk_lists: list) -> None:
            t0 = time.perf_counter()
            try:
                # Chaos points, per ATTEMPT: `online.lookup` keeps its
                # error-degrades contract; `shard.lookup` (keyed by
                # shard index) is the gray-shard injection site — a
                # latency fault stalls exactly one attempt, which the
                # hedge races.
                faultinject.fire("online.lookup")
                faultinject.fire("shard.lookup", key=idx)
                rows = self._shard_lookup(self._shards[idx], pk_lists)
                ok = True
            except Exception as e:  # noqa: BLE001 — degrade, never raise
                rows, ok = e, False
            elapsed = time.perf_counter() - t0
            with cv:
                if idx not in results:
                    results[idx] = (ok, rows, elapsed)
                    cv.notify_all()
                # else: abandoned loser (hedge raced it) — discarded,
                # no breaker/metric effects.

        pool = self._executor()
        pending: list[int] = []
        started = time.perf_counter()
        for idx in sorted(buckets):
            if not self._breakers[idx].allow():
                self._m_error.inc(len(buckets[idx]))
                continue
            pool.submit(attempt, idx, [pk for _, pk in buckets[idx]])
            pending.append(idx)
        hedge_delay = (
            self._hedge_delay_s() if self.hedge_stragglers else None)
        deadline = started + deadline_s if deadline_s is not None else None
        hedged: set[int] = set()
        while True:
            with cv:
                done = set(results)
            live = [i for i in pending if i not in done]
            if not live:
                break
            now_pc = time.perf_counter()
            if deadline is not None and now_pc >= deadline:
                break
            waits = [] if deadline is None else [deadline - now_pc]
            if hedge_delay is not None:
                not_hedged = [i for i in live if i not in hedged]
                if not_hedged:
                    hedge_at = started + hedge_delay
                    if now_pc >= hedge_at:
                        for idx in not_hedged:
                            hedged.add(idx)
                            _m_shard_hedges.inc(store=self.label)
                            pool.submit(
                                attempt, idx,
                                [pk for _, pk in buckets[idx]])
                        continue
                    waits.append(hedge_at - now_pc)
            with cv:
                if all(i in results for i in live):
                    continue
                cv.wait(timeout=min(waits) if waits else None)
        with cv:
            settled = dict(results)
        for idx in pending:
            items = buckets[idx]
            res = settled.get(idx)
            if res is None:
                # Deadline overrun: the shard is slow past the budget —
                # breaker pressure plus missing keys, exactly like the
                # sequential path's with_deadline overrun.
                self._breakers[idx].record_failure()
                self._m_error.inc(len(items))
                log.warning(
                    "online store %s shard %d lookup missed the "
                    "%.3fs deadline (hedged=%s)",
                    self.label, idx, deadline_s or -1.0, idx in hedged)
                continue
            ok, rows, elapsed = res
            if not ok:
                if isinstance(rows, GenerationSupersededError):
                    # Zombie endpoint (typed 410): miss-degrade, no
                    # breaker strike — see the sequential path.
                    self._m_error.inc(len(items))
                    log.warning(
                        "online store %s shard %d superseded: %s",
                        self.label, idx, rows)
                    continue
                self._breakers[idx].record_failure()
                self._m_error.inc(len(items))
                log.warning(
                    "online store %s shard %d lookup failed: %s: %s",
                    self.label, idx, type(rows).__name__, rows)
                continue
            self._breakers[idx].record_success()
            self._m_lookup.observe(elapsed)
            self._note_lookup_latency(elapsed)
            self._fill_rows(out, items, rows, now)

    def scan(self) -> Iterator[dict]:
        """Every live (non-expired) row across all shards."""
        now = time.time()
        for shard in self._shards:
            for row in shard.scan():
                if not self._expired(row, now):
                    yield self._strip(row)

    def count(self) -> int:
        """Stored rows across all shards (including TTL-expired rows
        not yet swept — :meth:`evict_expired` reclaims those)."""
        return sum(shard.count() for shard in self._shards)

    def evict_expired(self) -> int:
        """TTL sweep: delete expired rows (each shard's delete runs
        under that shard's writer lock). Returns rows evicted."""
        if self.ttl_s is None:
            return 0
        now = time.time()
        evicted = 0
        for shard in self._shards:
            doomed = [row for row in shard.scan() if self._expired(row, now)]
            if doomed:
                shard.delete_keys(pd.DataFrame(doomed), self.primary_key)
                evicted += len(doomed)
        if evicted:
            self._m_evict.inc(evicted)
        return evicted

    # -- freshness watermark --------------------------------------------------
    #
    # The watermark is persisted beside the shard files (not memory-only)
    # because the writer and the readers are usually DIFFERENT store
    # instances — the materializer daemon advances it, serving replicas
    # (their own ShardedOnlineStore objects, possibly other processes)
    # re-derive lag from it at every lookup. That is also what makes the
    # freshness gauge rise while the daemon is dead: the file stalls,
    # now keeps moving.

    def _watermark_path(self) -> Path:
        return self._dir / f"{self.label}.watermark"

    def _file_watermark(self) -> float | None:
        now = time.monotonic()
        with self._meta_lock:
            cached = self._wm_cache
        if cached is not None and now - cached[1] < 0.05:
            return cached[0]
        try:
            file_wm = float(self._watermark_path().read_text())
        except (OSError, ValueError):
            file_wm = None
        with self._meta_lock:
            self._wm_cache = (file_wm, now)
        return file_wm

    @property
    def watermark(self) -> float | None:
        """Max event time materialized into this store (epoch seconds):
        the newer of this instance's own writes and the persisted file
        (another instance's writes, cached for at most 50 ms)."""
        with self._meta_lock:
            wm = self._watermark
        file_wm = self._file_watermark()
        if file_wm is None:
            return wm
        return file_wm if wm is None else max(wm, file_wm)

    def set_watermark(self, ts: float) -> None:
        ts = float(ts)
        with self._meta_lock:
            try:
                file_wm = float(self._watermark_path().read_text())
            except (OSError, ValueError):
                file_wm = None
            known = max(
                (v for v in (self._watermark, file_wm) if v is not None),
                default=None,
            )
            if known is None or ts > known:
                self._watermark = ts
                tmp = self._watermark_path().with_suffix(".watermark.tmp")
                tmp.write_text(repr(ts))
                os.replace(tmp, self._watermark_path())
                self._wm_cache = (ts, time.monotonic())
            elif self._watermark is None or ts > self._watermark:
                self._watermark = ts  # file already newer; cache ours anyway
        self._observe_freshness()

    def freshness_lag_s(self) -> float:
        """Seconds between now and the watermark — how stale the online
        view is. 0.0 before anything has been materialized."""
        wm = self.watermark
        return max(0.0, time.time() - wm) if wm is not None else 0.0

    def _observe_freshness(self) -> None:
        self._m_fresh.set(self.freshness_lag_s())

    # -- snapshot / warm-start ------------------------------------------------

    def snapshot(self, directory: str | Path) -> Path:
        """Write a warm-start snapshot: one JSONL file per shard plus a
        ``manifest.json`` with per-file sizes and SHA-256 checksums —
        the checkpoint layer's integrity contract (same streaming
        digest, same verify-before-trust restore), so a replica can
        prove a snapshot healthy before serving from it."""
        d = Path(directory)
        d.mkdir(parents=True, exist_ok=True)
        # Captured BEFORE the scans: under concurrent write-through the
        # manifest watermark must be a LOWER bound on what the files
        # hold — claiming event times whose rows were scanned past
        # would make a restored replica report freshness it doesn't have.
        wm = self.watermark
        files: dict[str, dict[str, Any]] = {}
        for i, shard in enumerate(self._shards):
            p = d / f"shard{i}.jsonl"
            tmp = p.with_suffix(".jsonl.tmp")
            with tmp.open("w") as f:
                for row in shard.scan():
                    f.write(json.dumps(row, default=str) + "\n")
            os.replace(tmp, p)
            files[p.name] = {"size": p.stat().st_size, "sha256": _file_sha256(p)}
        manifest = {
            "name": self.name,
            "version": self.version,
            "primary_key": self.primary_key,
            "shards": self.n_shards,
            "watermark": wm,
            "files": files,
        }
        tmp = d / "manifest.json.tmp"
        tmp.write_text(json.dumps(manifest, indent=2))
        os.replace(tmp, d / "manifest.json")
        return d

    def restore_snapshot(self, directory: str | Path) -> int:
        """Verify and load a :meth:`snapshot` into this store (warm
        start). Rows load through the idempotent upsert with their
        snapshotted event times, so restoring on top of newer data never
        rolls a row back; the watermark is restored too. Raises
        :class:`~hops_tpu.runtime.checkpoint.CheckpointCorruptError`
        when a file fails its manifest check. Returns rows applied."""
        d = Path(directory)
        manifest = json.loads((d / "manifest.json").read_text())
        for fname, meta in manifest.get("files", {}).items():
            p = d / fname
            try:
                size = p.stat().st_size
            except OSError as e:
                raise CheckpointCorruptError(
                    f"online snapshot {d}: {fname} unreadable "
                    f"({type(e).__name__}: {e})"
                ) from None
            if size != meta["size"]:
                raise CheckpointCorruptError(
                    f"online snapshot {d}: {fname} size {size} != "
                    f"manifest {meta['size']}"
                )
            if _file_sha256(p) != meta["sha256"]:
                raise CheckpointCorruptError(
                    f"online snapshot {d}: {fname} checksum mismatch"
                )
        rows: list[dict] = []
        for fname in manifest.get("files", {}):
            with (d / fname).open() as f:
                rows.extend(json.loads(line) for line in f if line.strip())
        applied = self.upsert_rows(rows, event_ts=EVENT_TS_COL) if rows else 0
        if manifest.get("watermark") is not None:
            self.set_watermark(float(manifest["watermark"]))
        return applied

    def close(self) -> None:
        with self._pool_lock:
            pool, self._pool = self._pool, None
        if pool is not None:
            # WAIT for in-flight attempts: an abandoned hedge loser may
            # still be inside a native (mmap) read — closing the shards
            # under it is a segfault, not an exception. The wait is
            # bounded by the slowest real lookup still running.
            pool.shutdown(wait=True)
        for shard in self._shards:
            shard.close()


def open_sharded_store(
    name: str, version: int = 1, *, primary_key: list[str], **kwargs: Any
) -> ShardedOnlineStore:
    """Open (or create) the sharded online store of a (feature group,
    version) under the workspace's ``FeatureStore/online`` root."""
    return ShardedOnlineStore(name, version, primary_key=primary_key, **kwargs)


# -- write-through materialization --------------------------------------------


class Materializer:
    """Write-through materialization daemon for one (topic, store) pair.

    A consumer thread tails the pubsub topic with a durable consumer
    group and upserts each record's ``value`` row into the store in
    batched flushes. Delivery is at-least-once — the group offset
    commits only AFTER a batch is flushed — and convergence comes from
    the store's idempotent event-time-guarded upserts, so a crash
    between flush and commit merely replays rows into a no-op.

    ``event_time`` names the row column carrying event time; absent (or
    missing on a row), the producer's ``ts`` stamp is used. The max
    event time applied becomes the store's freshness watermark; rows
    without a usable primary key are skipped with a warning (a poison
    record must not wedge the offset forever — the same contract as the
    consumer's unparsable-record skip).

    ``from_beginning=True`` (the default) makes a NEW group catch up on
    the topic's history; a restarted daemon with a committed offset
    resumes from the commit either way (the consumer's durable-group
    contract), so restarts cost O(uncommitted tail), not O(topic).
    """

    def __init__(
        self,
        store: ShardedOnlineStore,
        topic: str,
        group: str = "online-materializer",
        *,
        event_time: str | None = None,
        batch_size: int = 256,
        poll_interval_s: float = 0.05,
        from_beginning: bool = True,
    ):
        self._store = store
        self._topic = topic
        self._consumer = pubsub.Consumer(topic, group=group, from_beginning=from_beginning)
        self._event_time = event_time.lower() if event_time else None
        self._batch_size = int(batch_size)
        self._poll_s = float(poll_interval_s)
        self._stop = threading.Event()
        self._state_lock = threading.Lock()
        self._busy = False  # guarded by: self._state_lock
        self._errors = 0
        self._m_rows = _m_materialized.labels(store=store.label)
        self._thread = threading.Thread(
            target=self._loop, name=f"materializer-{store.label}", daemon=True
        )

    def start(self) -> "Materializer":
        self._thread.start()
        return self

    def stop(self, timeout_s: float = 5.0) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=timeout_s)

    @property
    def alive(self) -> bool:
        return self._thread.is_alive()

    def lag_bytes(self) -> int:
        """Topic bytes not yet consumed (0 = caught up)."""
        return self._consumer.lag()

    def drain(self, timeout_s: float = 10.0) -> bool:
        """Block until the consumer has caught up to the topic end AND
        the last batch is flushed; False on timeout or a dead daemon.
        Meaningful only while producers are quiet (a live producer can
        re-raise the lag right after the check)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if not self.alive:
                return False
            with self._state_lock:
                busy = self._busy
            if not busy and self._consumer.lag() == 0:
                return True
            time.sleep(min(self._poll_s, 0.02))
        return False

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                with self._state_lock:
                    self._busy = True
                try:
                    # Chaos point: an injected error/latency here must be
                    # survived (logged + retried with backoff), never kill
                    # the daemon — while it stalls, the freshness gauge
                    # rises and serving keeps answering from stale rows.
                    faultinject.fire("online.materialize")
                    records = self._consumer.poll(max_records=self._batch_size)
                    if records:
                        self._apply(records)
                        self._consumer.commit()  # at-least-once: AFTER the flush
                finally:
                    with self._state_lock:
                        self._busy = False
            except Exception as e:  # noqa: BLE001 — the daemon outlives transient faults
                self._errors += 1
                log.warning(
                    "materializer %s -> %s: %s: %s (attempt %d, backing off)",
                    self._topic, self._store.label, type(e).__name__, e,
                    self._errors,
                )
                # Computed exponential backoff (capped), interruptible
                # by stop() — not a naked retry loop.
                self._stop.wait(min(self._poll_s * (2 ** min(self._errors, 6)), 2.0))
                continue
            self._errors = 0
            if not records:
                self._stop.wait(self._poll_s)

    def _apply(self, records: list[dict]) -> None:
        rows: list[dict] = []
        for rec in records:
            value = rec.get("value")
            if not isinstance(value, dict):
                log.warning(
                    "materializer %s: skipping non-row record (%s)",
                    self._topic, type(value).__name__,
                )
                continue
            row = {str(k).lower(): v for k, v in value.items()}
            if any(row.get(k) is None for k in self._store.primary_key):
                log.warning(
                    "materializer %s: skipping row without primary key %s",
                    self._topic, self._store.primary_key,
                )
                continue
            ts = None
            if self._event_time is not None:
                ts = row.get(self._event_time)
            if ts is None:
                ts = rec.get("ts", time.time())
            row[EVENT_TS_COL] = float(ts)
            rows.append(row)
        if rows:
            applied = self._store.upsert_rows(rows, event_ts=EVENT_TS_COL)
            self._m_rows.inc(applied)


# -- serving-time feature joins ------------------------------------------------


def validate_feature_config(cfg: dict[str, Any]) -> dict[str, Any]:
    """Normalize and validate a ``feature_config`` dict at definition
    time (``serving.create_or_update``), so a typo'd policy or a group
    without a primary key fails at create, not at the first request."""
    cfg = dict(cfg)
    missing = cfg.get("missing", "default")
    if missing not in MISSING_POLICIES:
        raise ValueError(
            f"feature_config missing-key policy must be one of "
            f"{MISSING_POLICIES}, got {missing!r}"
        )
    groups = cfg.get("groups")
    if not groups:
        raise ValueError("feature_config needs a non-empty 'groups' list")
    for g in groups:
        if not g.get("name"):
            raise ValueError(f"feature_config group without a name: {g!r}")
        if not g.get("primary_key"):
            raise ValueError(
                f"feature_config group {g['name']!r} needs a primary_key"
            )
        eps = g.get("endpoints")
        if eps is not None and (
            not isinstance(eps, list)
            or not eps
            or not all(isinstance(e, str) and e.startswith("http") for e in eps)
        ):
            raise ValueError(
                f"feature_config group {g['name']!r} endpoints must be a "
                f"non-empty list of http URLs, got {eps!r}"
            )
    if not cfg.get("order") and not all(g.get("features") for g in groups):
        raise ValueError(
            "feature_config needs an explicit 'order' (output feature "
            "order) or per-group 'features' lists to derive it from"
        )
    return cfg


class FeatureJoinPredictor:
    """Request-time feature joins in front of any predictor.

    Instances are entity-key dicts (``{"user_id": 7}``); the predictor
    multi-gets every configured group's rows (one batched lookup per
    group, fanned per shard), merges them per entity, assembles the
    model-ready vector in ``order``, and calls the wrapped predictor on
    the vectors. Composes with the ``DynamicBatcher`` upstream —
    coalesced requests arrive here as one instances list and become one
    join pass.

    ``feature_config`` keys: ``groups`` (list of ``{"name", "version",
    "primary_key", "features", "shards", "ttl_s", "endpoints"}`` —
    ``endpoints`` lists placed shard-server URLs, turning the group's
    store remote; see docs/operations.md "Multi-host placement"),
    ``order`` (output
    feature order; default: concatenation of the groups' ``features``),
    ``missing`` (``default`` — substitute ``defaults[f]`` or
    ``default_value``; ``reject`` — fail the request; ``passthrough`` —
    emit None), ``defaults`` / ``default_value``, ``lookup_deadline_s``
    (the multi-get budget; overruns degrade to the missing policy),
    ``brownout_lookup_deadline_s`` (the budget while the fleet is
    browned out — under SLO burn joins stop waiting on slow shards and
    serve defaults; not applied under the ``reject`` policy, which
    would turn degradation into request failures), ``shards`` /
    ``ttl_s`` / ``root`` / ``fanout`` / ``hedge`` (store defaults).
    """

    def __init__(
        self,
        inner: Any,
        feature_config: dict[str, Any],
        model: str = "",
        stores: dict[str, ShardedOnlineStore] | None = None,
    ):
        cfg = validate_feature_config(feature_config)
        self._inner = inner
        self._model = model
        self._missing = cfg.get("missing", "default")
        self._defaults = {
            str(k).lower(): v for k, v in (cfg.get("defaults") or {}).items()
        }
        self._default_value = cfg.get("default_value", 0.0)
        self._deadline_s = cfg.get("lookup_deadline_s")
        self._brownout_deadline_s = cfg.get("brownout_lookup_deadline_s", 0.05)
        self._groups: list[tuple[ShardedOnlineStore, list[str]]] = []
        for g in cfg["groups"]:
            store = (stores or {}).get(g["name"])
            if store is None:
                store = ShardedOnlineStore(
                    g["name"],
                    g.get("version", 1),
                    primary_key=g["primary_key"],
                    shards=int(g.get("shards", cfg.get("shards", 4))),
                    ttl_s=g.get("ttl_s", cfg.get("ttl_s")),
                    root=cfg.get("root"),
                    fanout=bool(g.get("fanout", cfg.get("fanout", True))),
                    hedge=bool(g.get("hedge", cfg.get("hedge", True))),
                    # Placed shards: the group's shard-server endpoints
                    # (placement wrote them into the serving config, so
                    # subprocess fleet replicas join against the same
                    # remote shards the local path would).
                    endpoints=g.get("endpoints"),
                )
            feats = [str(f).lower() for f in (g.get("features") or [])]
            self._groups.append((store, feats))
        order = [str(f).lower() for f in (cfg.get("order") or [])]
        if not order:
            order = [f for _, feats in self._groups for f in feats]
        self._order = order
        self._m_join = _m_join_seconds.labels(model=model)
        self._m_request = _m_request_seconds.labels(model=model)
        self._m_missing = _m_missing_keys.labels(model=model, policy=self._missing)

    @property
    def order(self) -> list[str]:
        """The model-ready vector's feature order."""
        return list(self._order)

    def join(self, entries: list[Any]) -> list[list[Any]]:
        """Joined model-ready vectors for a batch of entity entries."""
        t0 = time.perf_counter()
        merged: list[dict[str, Any]] = [{} for _ in entries]
        # Child of the request trace when one is active (the batcher
        # runs the coalesced join under the carrier request's context);
        # a no-op outside one.
        # Brownout degrade: stop waiting on slow shards — a tight
        # deadline turns their keys into served defaults. Never under
        # the `reject` policy (degradation must not become failures).
        deadline = self._deadline_s
        if (self._missing != "reject"
                and qos.brownout_level() >= qos.DEGRADE):
            deadline = (self._brownout_deadline_s if deadline is None
                        else min(deadline, self._brownout_deadline_s))
        with tracing.child_span(
            "featurestore.join",
            entities=len(entries), groups=len(self._groups),
        ):
            for store, feats in self._groups:
                rows = store.multi_get(entries, deadline_s=deadline)
                for m, row in zip(merged, rows):
                    if row is None:
                        continue
                    m.update(
                        {k: v for k, v in row.items()
                         if not feats or k in feats}
                    )
        vectors: list[list[Any]] = []
        for entry, m in zip(entries, merged):
            vec: list[Any] = []
            for fname in self._order:
                if fname in m:
                    vec.append(m[fname])
                    continue
                self._m_missing.inc()
                if self._missing == "reject":
                    raise ValueError(
                        f"online feature {fname!r} missing for entity "
                        f"{entry!r} (missing-key policy: reject)"
                    )
                if self._missing == "default":
                    vec.append(self._defaults.get(fname, self._default_value))
                else:  # passthrough
                    vec.append(None)
            vectors.append(vec)
        self._m_join.observe(time.perf_counter() - t0)
        return vectors

    def predict(self, instances: list[Any]) -> list[Any]:
        t0 = time.perf_counter()
        vectors = self.join(instances)
        inner: Callable[[list[list[Any]]], list[Any]]
        inner = self._inner.predict if hasattr(self._inner, "predict") else self._inner
        preds = inner(vectors)
        self._m_request.observe(time.perf_counter() - t0)
        return preds

    def stop(self) -> None:
        """Close the stores and forward stop() to the wrapped predictor
        (the serving teardown path)."""
        for store, _ in self._groups:
            store.close()
        if hasattr(self._inner, "stop"):
            self._inner.stop()
