"""Connection + FeatureStore handles.

Reference: ``hsfs.connection()`` in-cluster and
``hsfs.connection(host, project, engine="hive", api_key_value=...)`` for
external clients (feature_engineering.ipynb:92; aws-sagemaker.ipynb —
SURVEY.md §2.6). Here a "connection" binds to a project workspace on the
shared filesystem; ``engine`` selects the execution engine for query
materialization ("pandas" is the only in-process engine — it plays the
role both Spark and Hive played in the reference).
"""

from __future__ import annotations

from typing import Any

from hops_tpu.featurestore import storage
from hops_tpu.featurestore.feature_group import FeatureGroup, OnDemandFeatureGroup
from hops_tpu.featurestore.query import Query
from hops_tpu.featurestore.training_dataset import TrainingDataset
from hops_tpu.featurestore.validation import Expectation, Rule, RULE_DEFINITIONS
from hops_tpu.runtime import config


class Connection:
    def __init__(self, host: str | None = None, project: str | None = None,
                 engine: str = "pandas", api_key_value: str | None = None):
        if project:
            config.configure(project=project)
        self.host = host
        self.engine = engine
        self._api_key = api_key_value

    def get_feature_store(self, name: str | None = None) -> "FeatureStore":
        return FeatureStore(self, name or config.runtime().project)

    # Reference: connection.get_rules()/get_rule (feature_validation_python.ipynb:249).
    def get_rules(self) -> list[dict]:
        return [dict(name=n, **d) for n, d in RULE_DEFINITIONS.items()]

    def get_rule(self, name: str) -> dict:
        return dict(name=name, **RULE_DEFINITIONS[name.upper()])

    def close(self) -> None:
        pass


def connection(host: str | None = None, project: str | None = None,
               engine: str = "pandas", api_key_value: str | None = None,
               **_ignored: Any) -> Connection:
    """Reference: ``hsfs.connection(...)``."""
    return Connection(host=host, project=project, engine=engine, api_key_value=api_key_value)


class FeatureStore:
    """Project-scoped feature store handle (the reference's ``fs``)."""

    def __init__(self, conn: Connection, project: str):
        self._conn = conn
        self.project = project

    # -- Scala-builder ergonomics (featurestore/builders.py) ------------------

    def createFeatureGroup(self):  # noqa: N802 — Scala client surface
        from hops_tpu.featurestore.builders import FeatureGroupBuilder

        return FeatureGroupBuilder(self)

    def createTrainingDataset(self):  # noqa: N802
        from hops_tpu.featurestore.builders import TrainingDatasetBuilder

        return TrainingDatasetBuilder(self)

    def getFeatureGroup(self, name: str, version: int | None = None):  # noqa: N802
        return self.get_feature_group(name, version)

    def getName(self) -> str:  # noqa: N802
        return self.project

    # -- feature groups -------------------------------------------------------

    def create_feature_group(self, name: str, version: int | None = None, **kwargs) -> FeatureGroup:
        if version is None:
            version = storage.next_version("featuregroups", name)
        return FeatureGroup(self, name, version, **kwargs)

    def get_feature_group(self, name: str, version: int | None = None) -> FeatureGroup:
        if version is None:
            versions = storage.list_versions("featuregroups", name)
            if not versions:
                raise KeyError(f"no feature group named {name!r}")
            version = versions[-1]
        d = storage.entity_dir("featuregroups", name, version)
        if not (d / "metadata.json").exists():
            raise KeyError(f"feature group {name}_{version} does not exist")
        meta = storage.read_metadata(d)
        cls = OnDemandFeatureGroup if meta.get("on_demand") else FeatureGroup
        fg = cls(self, name, version)
        fg._load_meta()
        if meta.get("on_demand"):
            fg.query = meta.get("query", "")
            sc = meta.get("storage_connector")
            fg.storage_connector = self.get_storage_connector(sc) if sc else None
        return fg

    def get_feature_groups(self, name: str) -> list[FeatureGroup]:
        return [self.get_feature_group(name, v) for v in storage.list_versions("featuregroups", name)]

    def create_on_demand_feature_group(
        self, name: str, version: int | None = None, query: str = "",
        storage_connector=None, **kwargs
    ) -> OnDemandFeatureGroup:
        if version is None:
            version = storage.next_version("featuregroups", name)
        return OnDemandFeatureGroup(
            self, name, version, query=query, storage_connector=storage_connector, **kwargs
        )

    # -- training datasets ----------------------------------------------------

    def create_training_dataset(self, name: str, version: int | None = None, **kwargs) -> TrainingDataset:
        if version is None:
            version = storage.next_version("trainingdatasets", name)
        return TrainingDataset(self, name, version, **kwargs)

    def get_training_dataset(self, name: str, version: int | None = None) -> TrainingDataset:
        if version is None:
            versions = storage.list_versions("trainingdatasets", name)
            if not versions:
                raise KeyError(f"no training dataset named {name!r}")
            version = versions[-1]
        td = TrainingDataset(self, name, version)
        td._load_meta()
        return td

    # -- queries --------------------------------------------------------------

    def construct_query(self, d: dict) -> Query:
        return Query.from_dict(self, d)

    def sql(self, query: str, online: bool = False):
        """Ad-hoc SQL over registered feature groups (reference:
        ``fs.sql(...)`` routed to Spark/Hive)."""
        from hops_tpu.sql import gateway

        return gateway.execute(query, feature_store=self)

    # -- expectations (reference: feature_validation_python.ipynb) ------------

    def create_expectation(self, name: str, description: str = "",
                           features: list[str] | None = None,
                           rules: list[Rule] | None = None) -> Expectation:
        return Expectation(self, name, description=description,
                           features=features or [], rules=rules or [])

    def get_expectation(self, name: str) -> Expectation:
        return Expectation.load(self, name)

    def get_expectations(self) -> list[Expectation]:
        d = storage.feature_store_root() / "expectations"
        if not d.exists():
            return []
        return [Expectation.load(self, p.stem) for p in sorted(d.glob("*.json"))]

    def delete_expectation(self, name: str) -> None:
        p = storage.feature_store_root() / "expectations" / f"{name}.json"
        if p.exists():
            p.unlink()

    # -- storage connectors ---------------------------------------------------

    def get_storage_connector(self, name: str, connector_type: str | None = None):
        from hops_tpu.featurestore import connectors

        return connectors.get(name, connector_type)

    def create_storage_connector(self, name: str, connector_type: str, **options):
        from hops_tpu.featurestore import connectors

        return connectors.create(name, connector_type, **options)
