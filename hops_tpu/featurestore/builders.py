"""Fluent builder facade — the Scala client's ergonomics in Python.

The reference's JVM client builds entities with a chained builder
(featurestore_tour/src/.../ComputeFeatures.scala:108-115 feature
groups, :312-327 training datasets; connection via
``HopsworksConnection.builder.build`` Main.scala-side). SURVEY.md §2.6
records this as the one un-twinned component; this module closes it as
a facade over the kwargs APIs — same single implementation underneath,
so reference Scala call shapes translate line for line::

    fg = (fs.createFeatureGroup()
            .name("games_features")
            .version(1)
            .description("Features of games")
            .timeTravelFormat(TimeTravelFormat.HUDI)
            .primaryKeys(["home_team_id"])
            .partitionKeys(["score"])
            .statisticsConfig(StatisticsConfig(True, True, True))
            .build())
    fg.save(df)

    td = (fs.createTrainingDataset()
            .name("tour_td").version(1)
            .dataFormat(DataFormat.TFRECORD)
            .build())
    td.save(query)
"""

from __future__ import annotations

from typing import Any

from hops_tpu.featurestore.statistics import StatisticsConfig


class TimeTravelFormat:
    """Scala enum twin (ComputeFeatures.scala:112,122)."""

    NONE = None
    HUDI = "COMMIT_LOG"  # the commit-log store IS the Hudi role here
    COMMIT_LOG = "COMMIT_LOG"


class DataFormat:
    """Scala enum twin (ComputeFeatures.scala:325)."""

    CSV = "csv"
    TFRECORD = "tfrecord"
    PARQUET = "parquet"
    PETASTORM = "petastorm"
    DELTA = "delta"
    RECORDIO = "recordio"


def _stats_arg(value: Any) -> Any:
    # The Scala-positional tuple form; StatisticsConfig/dict pass through
    # (the entities' from_dict accepts both unchanged).
    if isinstance(value, (tuple, list)):
        return dict(zip(("enabled", "histograms", "correlations"), value))
    return value


class _Builder:
    """Chained-setter base: setters map camelCase -> snake_case kwargs,
    with ``_renames`` only for names the mechanical mapping can't derive
    (plural Scala setters -> singular kwargs)."""

    _renames: dict[str, str] = {}

    def __init__(self, fs=None):
        self._fs = fs
        self._kw: dict[str, Any] = {}

    def __getattr__(self, attr: str):
        if attr.startswith("_"):
            raise AttributeError(attr)
        key = self._renames.get(attr)
        if key is None:
            # camelCase -> snake_case (primaryKeys -> primary_keys)
            key = "".join(f"_{c.lower()}" if c.isupper() else c for c in attr)

        def setter(value):
            self._kw[key] = value
            return self

        return setter


class FeatureGroupBuilder(_Builder):
    """`fs.createFeatureGroup()` — ComputeFeatures.scala:108-115."""

    _renames = {
        "primaryKeys": "primary_key",
        "partitionKeys": "partition_key",
    }

    def build(self):
        kw = dict(self._kw)
        name = kw.pop("name")
        version = kw.pop("version", None)
        if "statistics_config" in kw:
            kw["statistics_config"] = _stats_arg(kw["statistics_config"])
        return self._fs.create_feature_group(name, version=version, **kw)


class TrainingDatasetBuilder(_Builder):
    """`fs.createTrainingDataset()` — ComputeFeatures.scala:320-327."""


    def build(self):
        kw = dict(self._kw)
        name = kw.pop("name")
        version = kw.pop("version", None)
        if "statistics_config" in kw:
            kw["statistics_config"] = _stats_arg(kw["statistics_config"])
        return self._fs.create_training_dataset(name, version=version, **kw)


class _ConnBuilder(_Builder):
    def build(self):
        from hops_tpu.featurestore.connection import connection

        return connection(**self._kw)


class HopsworksConnection:
    """`HopsworksConnection.builder.build()` (Scala Main.scala usage)."""

    # `.builder` is an attribute in the Scala API, not a call.
    class _BuilderDescriptor:
        def __get__(self, obj, objtype=None):
            return _ConnBuilder()

    builder = _BuilderDescriptor()
