"""Schema'd columnar training-data format (the Petastorm role).

The reference materializes training datasets through Petastorm
(notebooks/featurestore/petastorm/PetastormHelloWorld.ipynb:21-44,
``materialize_dataset`` cell 10): parquet plus a *unischema* so tensor
columns (images, sequences) round-trip with dtype and shape, and readers
can project columns and stream shuffled row groups. This is that
capability, TPU-first:

- **schema.json** records every field's dtype, and for tensor fields the
  per-row shape — so the feeder reconstructs device-ready ndarrays
  without Python-object sniffing;
- tensor cells are stored as raw little-endian bytes in parquet binary
  columns (one row = one tensor), scalars as native parquet columns;
- **row groups** are the shuffle/streaming granule: :class:`RowGroupReader`
  yields column-projected, decoded numpy batches one row group at a
  time in (optionally) shuffled order — a windowed shuffle that never
  materializes the dataset, which is what keeps a feed HBM-friendly.

No Spark, no codegen: parquet row groups via pyarrow, numpy decode.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Iterator

import numpy as np
import pandas as pd

_SCHEMA_FILE = "schema.json"


def _infer_schema(df: pd.DataFrame) -> dict[str, dict[str, Any]]:
    schema: dict[str, dict[str, Any]] = {}
    for c in df.columns:
        first = df[c].iloc[0] if len(df) else None
        if isinstance(first, np.ndarray):
            arr = np.asarray(first)
            schema[str(c)] = {
                "kind": "tensor",
                "dtype": str(arr.dtype),
                "shape": list(arr.shape),
            }
        else:
            schema[str(c)] = {"kind": "scalar", "dtype": str(df[c].dtype)}
    return schema


def write_dataset(
    d: Path | str,
    df: pd.DataFrame,
    *,
    row_group_size: int = 1024,
    part: int = 0,
) -> None:
    """Materialize ``df`` under ``d`` as ``part-{part:05d}.parquet`` with
    ``row_group_size``-row groups plus (for part 0) the unischema."""
    d = Path(d)
    d.mkdir(parents=True, exist_ok=True)
    schema = _infer_schema(df)
    cols: dict[str, Any] = {}
    for c, spec in schema.items():
        if spec["kind"] == "tensor":
            want = np.dtype(spec["dtype"])
            shape = tuple(spec["shape"])
            cells = []
            for x in df[c]:
                arr = np.ascontiguousarray(np.asarray(x, dtype=want))
                if arr.shape != shape:
                    raise ValueError(
                        f"tensor column {c!r}: row shape {arr.shape} != "
                        f"schema shape {shape}"
                    )
                cells.append(arr.tobytes())
            cols[c] = pd.Series(cells, dtype=object)
        else:
            cols[c] = df[c].reset_index(drop=True)
    flat = pd.DataFrame(cols)
    flat.to_parquet(
        d / f"part-{part:05d}.parquet", index=False, row_group_size=row_group_size
    )
    schema_path = d / _SCHEMA_FILE
    if part == 0 or not schema_path.exists():
        schema_path.write_text(json.dumps(schema, indent=2))
    elif json.loads(schema_path.read_text()) != schema:
        raise ValueError(f"part {part} schema differs from {schema_path}")


def read_schema(d: Path | str) -> dict[str, dict[str, Any]]:
    return json.loads((Path(d) / _SCHEMA_FILE).read_text())


def _decode(table_df: pd.DataFrame, schema: dict) -> pd.DataFrame:
    out: dict[str, Any] = {}
    for c in table_df.columns:
        spec = schema.get(c, {"kind": "scalar"})
        if spec["kind"] == "tensor":
            dtype, shape = np.dtype(spec["dtype"]), tuple(spec["shape"])
            out[c] = pd.Series(
                [np.frombuffer(b, dtype=dtype).reshape(shape) for b in table_df[c]],
                dtype=object,
            )
        else:
            out[c] = table_df[c]
    return pd.DataFrame(out)


def read_dataset(
    d: Path | str, columns: list[str] | None = None
) -> pd.DataFrame:
    """Full (column-projected) read, tensors reconstructed."""
    d = Path(d)
    schema = read_schema(d)
    frames = [
        _decode(pd.read_parquet(p, columns=columns), schema)
        for p in sorted(d.glob("part-*.parquet"))
    ]
    return pd.concat(frames, ignore_index=True) if frames else pd.DataFrame()


class RowGroupReader:
    """Stream decoded numpy column batches one parquet row group at a
    time — the Petastorm ``make_reader`` role.

    ``shuffle=True`` permutes row-group order per epoch (seeded), so
    feeding shuffles at the granule level with O(row_group) memory.
    """

    def __init__(
        self,
        d: Path | str,
        columns: list[str] | None = None,
        shuffle: bool = False,
        seed: int = 0,
    ):
        import pyarrow.parquet as pq

        self._pq = pq
        self.dir = Path(d)
        self.schema = read_schema(self.dir)
        self.columns = list(columns) if columns is not None else None
        self.shuffle = shuffle
        self.seed = seed
        self._groups: list[tuple[Path, int]] = []
        for p in sorted(self.dir.glob("part-*.parquet")):
            for g in range(pq.ParquetFile(p).num_row_groups):
                self._groups.append((p, g))
        self._epoch = 0

    def __len__(self) -> int:
        return len(self._groups)

    def __iter__(self) -> Iterator[dict[str, np.ndarray]]:
        order = np.arange(len(self._groups))
        if self.shuffle:
            order = np.random.RandomState(self.seed + self._epoch).permutation(order)
        self._epoch += 1
        for i in order:
            path, g = self._groups[i]
            table = self._pq.ParquetFile(path).read_row_group(g, columns=self.columns)
            df = _decode(table.to_pandas(), self.schema)
            batch: dict[str, np.ndarray] = {}
            for c in df.columns:
                spec = self.schema.get(c, {"kind": "scalar"})
                if spec["kind"] == "tensor":
                    batch[c] = np.stack(list(df[c]))
                else:
                    batch[c] = df[c].to_numpy()
            yield batch
