"""Descriptive statistics for feature groups / training datasets.

The reference computed descriptive stats, histograms and correlations as
a Spark job at FG/TD creation, controlled by ``statistics_config``
(feature_engineering.ipynb:177-183, ComputeFeatures.scala:114 —
SURVEY.md §5 "Metrics"). Same knobs here, computed with pandas/NumPy.
"""

from __future__ import annotations

import dataclasses
import json
from pathlib import Path

import numpy as np
import pandas as pd


@dataclasses.dataclass
class StatisticsConfig:
    """Mirrors the reference's ``StatisticsConfig(descriptive, histograms,
    correlations)`` (ComputeFeatures.scala:114)."""

    enabled: bool = True
    histograms: bool = False
    correlations: bool = False
    columns: list[str] | None = None

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d) -> "StatisticsConfig":
        if isinstance(d, StatisticsConfig):
            return d
        if isinstance(d, bool):
            return cls(enabled=d)
        return cls(**d) if d else cls()


def compute_statistics(df: pd.DataFrame, cfg: StatisticsConfig) -> dict:
    """Descriptive stats (+ optional histograms/correlations) as a JSON-able dict."""
    if not cfg.enabled or df.empty:
        return {}
    cols = cfg.columns or list(df.columns)
    out: dict = {"row_count": int(len(df)), "features": {}}
    numeric = df.select_dtypes(include=[np.number])
    for c in cols:
        if c not in df.columns:
            continue
        s = df[c]
        # Tensor columns (petastorm-style object cells) are unhashable;
        # describe their presence only. Sniff the first non-null cell —
        # row 0 may be missing.
        probe = s.dropna()
        if s.dtype == object and len(probe) and isinstance(probe.iloc[0], np.ndarray):
            out["features"][c] = {
                "count": int(s.count()),
                "num_missing": int(s.isna().sum()),
                "tensor_shape": list(np.asarray(probe.iloc[0]).shape),
            }
            continue
        entry: dict = {
            "count": int(s.count()),
            "num_missing": int(s.isna().sum()),
            "distinct": int(s.nunique()),
        }
        if c in numeric.columns:
            desc = s.describe()
            entry.update(
                mean=float(desc["mean"]),
                stddev=float(desc["std"]) if len(s) > 1 else 0.0,
                min=float(desc["min"]),
                max=float(desc["max"]),
                p25=float(desc["25%"]),
                p50=float(desc["50%"]),
                p75=float(desc["75%"]),
            )
            if cfg.histograms:
                counts, edges = np.histogram(s.dropna().to_numpy(dtype=float), bins=10)
                entry["histogram"] = {
                    "counts": counts.tolist(),
                    "edges": [float(e) for e in edges],
                }
        out["features"][c] = entry
    if cfg.correlations and len(numeric.columns) > 1:
        corr = numeric[[c for c in cols if c in numeric.columns]].corr()
        out["correlations"] = {
            a: {b: (None if pd.isna(v) else float(v)) for b, v in row.items()}
            for a, row in corr.to_dict().items()
        }
    return out


def save_statistics(d: Path, name: str, stats: dict) -> None:
    sdir = d / "statistics"
    sdir.mkdir(parents=True, exist_ok=True)
    (sdir / f"{name}.json").write_text(json.dumps(stats, indent=2))


def load_statistics(d: Path, name: str | None = None) -> dict:
    sdir = d / "statistics"
    if not sdir.exists():
        return {}
    files = sorted(sdir.glob("*.json"))
    if not files:
        return {}
    target = sdir / f"{name}.json" if name else files[-1]
    return json.loads(target.read_text())
