"""Slice-based bias and fairness analysis for feature data and models.

Reference role (featurestore/feature-bias/feature-bias-whatif.ipynb):
train a classifier on census data, then inspect it with the What-If
Tool — per-slice performance, acceptance rates across protected groups,
and decision-threshold exploration. The widget itself is a notebook UI;
the capability underneath is slice metrics + disparity measures +
threshold sweeps, which is what this module provides as a plain API
over pandas frames (so it composes with feature groups, training
datasets, and ``modelrepo.batch`` predictions).

All metrics are computed jointly in one pass per slice; predictions may
be hard labels or scores (scores + ``threshold`` give the What-If
threshold-exploration behavior).
"""

from __future__ import annotations

from typing import Any

import numpy as np
import pandas as pd

_METRIC_COLUMNS = (
    "count", "base_rate", "acceptance_rate", "accuracy", "tpr", "fpr", "precision",
)


def _require_binary(vals: np.ndarray, column: str, role: str) -> None:
    """Fail fast on non-0/1 data — e.g. the census labels '<=50K'/'>50K'
    of the reference notebook, which must be binarized first; silent
    all-False comparisons would report zero disparity on disparate data."""
    uniq = pd.unique(vals)
    if not set(np.asarray(uniq, dtype=object)) <= {0, 1, True, False}:
        raise ValueError(
            f"{role} column {column!r} must contain only 0/1, got "
            f"{list(uniq[:5])!r}; binarize it first, e.g. "
            f"df[{column!r}] = (df[{column!r}] == positive_value).astype(int)")


def slice_metrics(
    df: pd.DataFrame,
    label: str,
    prediction: str,
    slice_by: str | list[str],
    threshold: float | None = None,
) -> pd.DataFrame:
    """Per-group confusion metrics.

    Returns one row per slice value with count, base_rate (P(y=1)),
    acceptance_rate (P(yhat=1)), accuracy, tpr (equal-opportunity
    axis), fpr, precision. ``threshold`` binarizes a score column.
    """
    if isinstance(slice_by, str):
        slice_by = [slice_by]
    clash = set(slice_by) & (set(_METRIC_COLUMNS) | {"_y", "_yhat"})
    if clash:
        raise ValueError(
            f"slice column(s) {sorted(clash)} collide with metric/scratch "
            f"column names {_METRIC_COLUMNS + ('_y', '_yhat')}; rename them "
            "before slicing")
    y = df[label].to_numpy()
    _require_binary(y, label, "label")
    yhat = df[prediction].to_numpy()
    if threshold is not None:
        yhat = (yhat >= threshold).astype(int)
    else:
        _require_binary(yhat, prediction, "prediction (pass threshold= for scores)")
    work = df[slice_by].copy()
    work["_y"], work["_yhat"] = y, yhat

    rows = []
    for key, grp in work.groupby(slice_by, dropna=False, observed=True):
        gy, gp = grp["_y"].to_numpy(), grp["_yhat"].to_numpy()
        pos, neg = gy == 1, gy == 0
        tp, fp = int((gp[pos] == 1).sum()), int((gp[neg] == 1).sum())
        rows.append({
            **dict(zip(slice_by, key if isinstance(key, tuple) else (key,))),
            "count": len(gy),
            "base_rate": float(pos.mean()),
            "acceptance_rate": float((gp == 1).mean()),
            "accuracy": float((gp == gy).mean()),
            "tpr": float(tp / pos.sum()) if pos.any() else np.nan,
            "fpr": float(fp / neg.sum()) if neg.any() else np.nan,
            "precision": float(tp / (tp + fp)) if (tp + fp) else np.nan,
        })
    out = pd.DataFrame(rows)
    out.attrs["slice_by"] = list(slice_by)
    return out


def disparity(metrics: pd.DataFrame, metric: str = "acceptance_rate") -> dict[str, Any]:
    """Max-minus-min gap and max/min ratio of ``metric`` across slices.

    ``metric="acceptance_rate"`` is demographic-parity difference;
    ``metric="tpr"`` is the equal-opportunity difference.
    """
    vals = metrics[metric].dropna()
    if vals.empty:
        return {"metric": metric, "gap": np.nan, "ratio": np.nan,
                "max_group": None, "min_group": None}
    # slice_metrics records its slice columns; fall back to exclusion
    # for hand-built frames (collisions are rejected at slice time).
    slice_cols = metrics.attrs.get(
        "slice_by",
        [c for c in metrics.columns if c not in _METRIC_COLUMNS])
    if not slice_cols:
        raise ValueError(
            "metrics frame has no slice columns (every column matches a "
            "metric name); build it with slice_metrics or include the "
            "group column")
    hi, lo = vals.idxmax(), vals.idxmin()
    name = lambda i: tuple(metrics.loc[i, c] for c in slice_cols)  # noqa: E731
    return {
        "metric": metric,
        "gap": float(vals.max() - vals.min()),
        "ratio": float(vals.max() / vals.min()) if vals.min() > 0 else np.inf,
        "max_group": name(hi) if len(slice_cols) > 1 else name(hi)[0],
        "min_group": name(lo) if len(slice_cols) > 1 else name(lo)[0],
    }


def threshold_sweep(
    df: pd.DataFrame,
    label: str,
    score: str,
    slice_by: str | list[str],
    thresholds: np.ndarray | list[float] | None = None,
    parity_metric: str = "acceptance_rate",
) -> pd.DataFrame:
    """The What-If threshold exploration: disparity of ``parity_metric``
    and overall accuracy at each decision threshold."""
    if thresholds is None:
        thresholds = np.linspace(0.1, 0.9, 17)
    y = df[label].to_numpy()
    rows = []
    for t in thresholds:
        m = slice_metrics(df, label, score, slice_by, threshold=float(t))
        d = disparity(m, parity_metric)
        overall = float(((df[score].to_numpy() >= t).astype(int) == y).mean())
        rows.append({"threshold": float(t), "gap": d["gap"],
                     "ratio": d["ratio"], "overall_accuracy": overall})
    return pd.DataFrame(rows)


def bias_report(
    df: pd.DataFrame,
    label: str,
    prediction: str,
    slice_by: str | list[str],
    threshold: float | None = None,
) -> dict[str, Any]:
    """One-call summary: per-slice metrics plus the three standard
    disparities (demographic parity, equal opportunity, accuracy gap)."""
    m = slice_metrics(df, label, prediction, slice_by, threshold=threshold)
    return {
        "slices": m,
        "demographic_parity": disparity(m, "acceptance_rate"),
        "equal_opportunity": disparity(m, "tpr"),
        "accuracy_gap": disparity(m, "accuracy"),
    }
