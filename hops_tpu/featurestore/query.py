"""Lazy query algebra over feature groups.

Reference surface (SURVEY.md §2.6, feature_exploration.ipynb cells
10-31): ``fg.select(...).join(other.select_all(), on=[...],
join_type="left").filter(fg["f"] > 10).as_of(ts)`` → lazy until
``read()``/``show(n)``. Execution here is pandas merges on the host —
feature joins are metadata-scale work; the TPU only sees materialized
training batches.
"""

from __future__ import annotations

import dataclasses
from typing import TYPE_CHECKING, Any

import pandas as pd

from hops_tpu.featurestore.feature import Feature, _Condition

if TYPE_CHECKING:
    from hops_tpu.featurestore.feature_group import FeatureGroup


@dataclasses.dataclass
class Join:
    query: "Query"
    on: list[str]
    left_on: list[str]
    right_on: list[str]
    join_type: str = "inner"
    prefix: str | None = None


class Query:
    """Immutable-ish query tree rooted at one feature group."""

    def __init__(self, feature_group: "FeatureGroup", features: list[Feature]):
        self._fg = feature_group
        self._features = list(features)
        self._joins: list[Join] = []
        self._filters: list[_Condition] = []
        self._as_of: Any = None

    # -- algebra --------------------------------------------------------------

    def join(
        self,
        other: "Query",
        on: list[str] | None = None,
        left_on: list[str] | None = None,
        right_on: list[str] | None = None,
        join_type: str = "inner",
        prefix: str | None = None,
    ) -> "Query":
        """Reference: join on explicit keys or (default) the shared primary
        key of the two root groups (feature_exploration.ipynb cell 27-29)."""
        if on is None and left_on is None:
            shared = [k for k in self._fg.primary_key if k in other._fg.primary_key]
            on = shared or None
            if on is None:
                raise ValueError(
                    "no shared primary key between "
                    f"{self._fg.name} and {other._fg.name}; pass on=/left_on="
                )
        self._joins.append(
            Join(
                query=other,
                on=[k.lower() for k in (on or [])],
                left_on=[k.lower() for k in (left_on or [])],
                right_on=[k.lower() for k in (right_on or [])],
                join_type=join_type,
                prefix=prefix,
            )
        )
        return self

    def filter(self, condition: _Condition) -> "Query":
        self._filters.append(condition)
        return self

    def as_of(self, wallclock_time) -> "Query":
        """Point-in-time read over every group in the tree (reference:
        ``query.as_of``, time_travel_python.ipynb:1222-1272)."""
        self._as_of = wallclock_time
        return self

    @property
    def features(self) -> list[Feature]:
        feats = list(self._features)
        for j in self._joins:
            feats.extend(j.query.features)
        return feats

    @property
    def feature_groups(self) -> list["FeatureGroup"]:
        fgs = [self._fg]
        for j in self._joins:
            fgs.extend(j.query.feature_groups)
        return fgs

    # -- execution ------------------------------------------------------------

    def _base_frame(self, as_of, online: bool) -> pd.DataFrame:
        if online:
            if not self._fg.online_enabled:
                raise ValueError(
                    f"feature group {self._fg.name}_{self._fg.version} is not "
                    "online_enabled; online=True would silently return no rows"
                )
            df = self._fg.read(online=True)
        else:
            df = self._fg.read(wallclock_time=as_of)
        if df.empty:
            return pd.DataFrame(columns=[f.name for f in self._fg.features])
        return df

    def _output_columns(self) -> list[str]:
        """Merged-frame column names of the selected features, in order,
        accounting for join-key dedup, prefixes, and pandas' collision
        suffix ("_right")."""
        cols = [f.name for f in self._features]
        for j in self._joins:
            key_cols = set(j.on or j.right_on)
            for c in j.query._output_columns():
                if j.on and c in key_cols:
                    if c not in cols:
                        cols.append(c)  # merge keeps one copy under the key name
                    continue
                if j.prefix and c not in key_cols:
                    c = f"{j.prefix}{c}"
                cols.append(c if c not in cols else f"{c}_right")
        return cols

    def read(self, online: bool = False, dataframe_type: str = "pandas",
             _extra_keep: tuple = (), _as_of=None, _project: bool = True):
        """Execute the query. ``online=True`` runs the same select/join/
        filter tree against every group's online store (latest values
        only — reference: ``query.show(n, online=True)``,
        feature_exploration.ipynb cell 12); the offline commit log is
        not consulted, so rows committed offline-only are absent.
        """
        # as_of flows down from the root read without mutating children, so
        # a shared sub-query is unaffected by a parent's point-in-time read.
        as_of = self._as_of if self._as_of is not None else _as_of
        if online and as_of is not None:
            raise ValueError(
                "online=True reads latest serving values; it cannot be "
                "combined with as_of() time travel"
            )
        df = self._base_frame(as_of, online)
        # Columns needed for execution: selected + join keys + filter columns
        # (+ anything a parent needs from this side: its join keys AND its
        # filter columns, which may live in this group or deeper).
        filter_cols: set[str] = set()
        for cond in self._filters:
            filter_cols.update(_condition_columns(cond))
        keep = {f.name for f in self._features} | set(_extra_keep) | filter_cols
        for j in self._joins:
            keep.update(j.on or j.left_on)
        df = df[[c for c in df.columns if c in keep]]

        pass_down = tuple(filter_cols) + tuple(_extra_keep)
        for j in self._joins:
            right_keys = tuple(j.on or j.right_on)
            right = j.query.read(
                online=online, _extra_keep=right_keys + pass_down,
                _as_of=as_of, _project=False,
            )
            if j.prefix:
                key_cols = set(j.on or j.right_on)
                right = right.rename(
                    columns={c: f"{j.prefix}{c}" for c in right.columns if c not in key_cols}
                )
            kwargs: dict = {"how": j.join_type}
            if j.on:
                kwargs["on"] = j.on
            else:
                kwargs["left_on"], kwargs["right_on"] = j.left_on, j.right_on
            df = df.merge(right, suffixes=("", "_right"), **kwargs)

        for cond in self._filters:
            df = df[cond.evaluate(df)]
        if _project:
            # Drop execution-only columns (filter cols, join keys) so the
            # result — and any TD schema derived from it — is exactly the
            # selection.
            df = df[[c for c in self._output_columns() if c in df.columns]]
        df = df.reset_index(drop=True)
        return _convert(df, dataframe_type) if _project else df

    def show(self, n: int = 5, online: bool = False) -> pd.DataFrame:
        return self.read(online=online).head(n)

    # -- introspection --------------------------------------------------------

    def to_string(self) -> str:
        """SQL-ish rendering for debugging (reference: query.to_string())."""
        cols = ", ".join(f.name for f in self._features) or "*"
        sql = f"SELECT {cols} FROM {self._fg.name}_{self._fg.version}"
        for j in self._joins:
            keys = j.on or list(zip(j.left_on, j.right_on))
            sql += f" {j.join_type.upper()} JOIN {j.query._fg.name}_{j.query._fg.version} ON {keys}"
        if self._filters:
            sql += " WHERE " + " AND ".join(repr(f) for f in self._filters)
        if self._as_of is not None:
            sql += f" AS OF {self._as_of}"
        return sql

    def to_dict(self) -> dict:
        """Replayable description persisted with training datasets
        (reference: ``td.query`` replay, training_datasets.ipynb cell 14)."""
        return {
            "feature_group": {"name": self._fg.name, "version": self._fg.version},
            "features": [f.name for f in self._features],
            "joins": [
                {
                    "query": j.query.to_dict(),
                    "on": j.on,
                    "left_on": j.left_on,
                    "right_on": j.right_on,
                    "join_type": j.join_type,
                    "prefix": j.prefix,
                }
                for j in self._joins
            ],
            "as_of": (
                self._as_of
                if self._as_of is None or isinstance(self._as_of, (int, float, str))
                else str(self._as_of)
            ),
        }

    @classmethod
    def from_dict(cls, feature_store, d: dict) -> "Query":
        fg = feature_store.get_feature_group(
            d["feature_group"]["name"], d["feature_group"]["version"]
        )
        q = fg.select(d["features"]) if d.get("features") else fg.select_all()
        for j in d.get("joins", []):
            q.join(
                cls.from_dict(feature_store, j["query"]),
                on=j["on"] or None,
                left_on=j["left_on"] or None,
                right_on=j["right_on"] or None,
                join_type=j["join_type"],
                prefix=j.get("prefix"),
            )
        if d.get("as_of") is not None:
            q.as_of(d["as_of"])
        return q

    def __repr__(self) -> str:
        return f"Query({self.to_string()})"


def _convert(df: pd.DataFrame, dataframe_type: str):
    """Result conversion — reference hsfs ``dataframe_type`` values
    pandas/numpy/python (spark has no TPU-side analog)."""
    kind = dataframe_type.lower()
    if kind in ("pandas", "default"):
        return df
    if kind == "numpy":
        return df.to_numpy()
    if kind == "python":
        return df.to_dict("records")
    raise ValueError(
        f"unsupported dataframe_type {dataframe_type!r}; "
        "expected pandas | numpy | python"
    )


def _condition_columns(cond) -> set[str]:
    from hops_tpu.featurestore.feature import Filter, Logic

    if isinstance(cond, Filter):
        return {cond.feature.name}
    if isinstance(cond, Logic):
        return _condition_columns(cond.left) | _condition_columns(cond.right)
    return set()
