"""Feature groups: schema'd, versioned, time-travelable feature tables.

Reference surface (SURVEY.md §2.6; feature_engineering.ipynb:177,267,313;
time_travel_python.ipynb): ``fs.create_feature_group(...)`` → ``.save(df)``,
``fg.insert`` (upsert), ``fg.commit_details()``, ``fg.select/select_all/
filter``, online writes when ``online_enabled``, validation gates via
``validation_type``, schematized tags.
"""

from __future__ import annotations

import json
from typing import TYPE_CHECKING, Any

import pandas as pd

from hops_tpu.featurestore import online as online_mod
from hops_tpu.featurestore import statistics as stats_mod
from hops_tpu.featurestore import storage
from hops_tpu.featurestore.feature import Feature, _Condition, schema_from_dataframe
from hops_tpu.featurestore.query import Query

if TYPE_CHECKING:
    from hops_tpu.featurestore.connection import FeatureStore

_KIND = "featuregroups"


class FeatureGroup:
    """A versioned feature table backed by the Parquet commit log."""

    def __init__(
        self,
        feature_store: "FeatureStore",
        name: str,
        version: int = 1,
        description: str = "",
        primary_key: list[str] | None = None,
        partition_key: list[str] | None = None,
        online_enabled: bool = False,
        time_travel_format: str | None = "COMMIT_LOG",
        statistics_config: Any = None,
        validation_type: str = "NONE",
        expectations: list | None = None,
        event_time: str | None = None,
    ):
        self._fs = feature_store
        self.name = name
        self.version = version
        self.description = description
        self.primary_key = [k.lower() for k in (primary_key or [])]
        self.partition_key = [k.lower() for k in (partition_key or [])]
        self.online_enabled = online_enabled
        self.time_travel_format = time_travel_format
        self.statistics_config = stats_mod.StatisticsConfig.from_dict(statistics_config)
        self.validation_type = validation_type.upper()
        self.expectation_names = [
            e if isinstance(e, str) else e.name for e in (expectations or [])
        ]
        self.event_time = event_time
        self._features: list[Feature] = []
        self._online: online_mod.OnlineStore | None = None

    # -- identity -------------------------------------------------------------

    @property
    def dir(self):
        return storage.entity_dir(_KIND, self.name, self.version)

    @property
    def features(self) -> list[Feature]:
        if not self._features and (self.dir / "metadata.json").exists():
            self._load_meta()
        return self._features

    def __getitem__(self, name: str) -> Feature:
        return self.get_feature(name)

    def get_feature(self, name: str) -> Feature:
        for f in self.features:
            if f.name == name:
                return f
        raise KeyError(f"feature {name!r} not in {self.name}_{self.version}")

    def __repr__(self) -> str:
        return f"FeatureGroup({self.name!r}, version={self.version})"

    # -- persistence ----------------------------------------------------------

    def _save_meta(self) -> None:
        storage.write_metadata(
            self.dir,
            {
                "name": self.name,
                "version": self.version,
                "description": self.description,
                "primary_key": self.primary_key,
                "partition_key": self.partition_key,
                "online_enabled": self.online_enabled,
                "time_travel_format": self.time_travel_format,
                "statistics_config": self.statistics_config.to_dict(),
                "validation_type": self.validation_type,
                "expectations": self.expectation_names,
                "event_time": self.event_time,
                "features": [f.to_dict() for f in self._features],
                "tags": self._load_tags(),
            },
        )

    def _load_meta(self) -> None:
        meta = storage.read_metadata(self.dir)
        self.description = meta.get("description", "")
        self.primary_key = meta.get("primary_key", [])
        self.partition_key = meta.get("partition_key", [])
        self.online_enabled = meta.get("online_enabled", False)
        self.time_travel_format = meta.get("time_travel_format")
        self.statistics_config = stats_mod.StatisticsConfig.from_dict(
            meta.get("statistics_config")
        )
        self.validation_type = meta.get("validation_type", "NONE")
        self.expectation_names = meta.get("expectations", [])
        self.event_time = meta.get("event_time")
        self._features = [Feature.from_dict(f) for f in meta.get("features", [])]

    # -- write path -----------------------------------------------------------

    def save(self, df: pd.DataFrame, write_options: dict | None = None) -> "FeatureGroup":
        """First materialization (reference: ``fg.save(df)``,
        feature_engineering.ipynb cell 13)."""
        df = _normalize(df)
        self._features = schema_from_dataframe(df, self.primary_key, self.partition_key)
        self._save_meta()
        self._commit(df, operation="insert", write_options=write_options)
        return self

    def insert(
        self,
        df: pd.DataFrame,
        overwrite: bool = False,
        operation: str = "upsert",
        write_options: dict | None = None,
    ) -> "FeatureGroup":
        """Upsert new rows as a commit (reference: ``fg.insert``,
        time_travel_python.ipynb:695)."""
        df = _normalize(df)
        if not (self.dir / "metadata.json").exists():
            return self.save(df, write_options)
        if overwrite:
            # Hudi "insert_overwrite": tombstone current state first
            # (through _commit so the online store is purged too).
            current = self.read()
            if len(current):
                self._commit(current, operation="delete")
        self._commit(df, operation=operation, write_options=write_options)
        return self

    def commit_delete_record(self, df: pd.DataFrame, write_options: dict | None = None) -> None:
        """Delete by primary key (reference: time-travel deletes,
        time_travel_python.ipynb cell 24)."""
        df = _normalize(df)
        self._commit(df[self.primary_key] if self.primary_key else df, operation="delete")

    def _commit(self, df: pd.DataFrame, operation: str, write_options: dict | None = None) -> int:
        # Deletes carry only the primary key — expectations don't apply.
        if operation != "delete":
            self._validate_on_write(df)
        # ``before`` feeds both the upsert bookkeeping (needs a primary key)
        # and post-commit statistics (needed even for keyless append FGs,
        # where stats must describe the full table, not just this commit).
        need_before = bool(self.primary_key) or self.statistics_config.enabled
        before = storage.read_as_of(self.dir, self.primary_key) if need_before else None
        cid = storage.write_commit(self.dir, df, operation=operation)
        # Commit bookkeeping mirrors the reference's commit_details fields.
        if operation == "delete":
            counts = {"rows_inserted": 0, "rows_updated": 0, "rows_deleted": int(len(df))}
        elif before is not None and len(before) and self.primary_key:
            existing = before.set_index(self.primary_key).index
            incoming = df.set_index(self.primary_key).index
            updated = int(incoming.isin(existing).sum())
            counts = {
                "rows_inserted": int(len(df) - updated),
                "rows_updated": updated,
                "rows_deleted": 0,
            }
        else:
            counts = {"rows_inserted": int(len(df)), "rows_updated": 0, "rows_deleted": 0}
        meta = storage.read_commit_meta(self.dir, cid)
        meta.update(counts)
        (self.dir / "commits" / f"{cid}.json").write_text(json.dumps(meta, indent=2))
        if self.statistics_config.enabled:
            # Post-commit state derived in memory (no second log replay).
            after = _apply_commit(before, df, operation, self.primary_key)
            stats = stats_mod.compute_statistics(after, self.statistics_config)
            stats_mod.save_statistics(self.dir, str(cid), stats)
        if self.online_enabled and operation != "delete":
            self.online_store().put_dataframe(df, self.primary_key)
        elif self.online_enabled and operation == "delete":
            self.online_store().delete_keys(df, self.primary_key)
        return cid

    def _validate_on_write(self, df: pd.DataFrame) -> None:
        if self.validation_type == "NONE" or not self.expectation_names:
            return
        from hops_tpu.featurestore import validation as val_mod

        report = val_mod.validate_dataframe(self._fs, self, df, persist=True)
        if self.validation_type == "STRICT" and report["status"] != "SUCCESS":
            raise val_mod.DataValidationError(
                f"STRICT validation failed for {self.name}_{self.version}: "
                f"{report['status']}"
            )

    # -- read path ------------------------------------------------------------

    def read(
        self,
        wallclock_time=None,
        online: bool = False,
        dataframe_type: str = "pandas",
    ) -> pd.DataFrame:
        """Current (or point-in-time) state (reference: ``fg.read()`` /
        ``fg.read(wallclock_time)``)."""
        if online:
            return pd.DataFrame(list(self.online_store().scan()))
        ts = storage.resolve_timestamp(wallclock_time)
        return storage.read_as_of(self.dir, self.primary_key, as_of=ts)

    def read_changes(self, start_wallclock_time, end_wallclock_time) -> pd.DataFrame:
        """Incremental pull between two commit times (reference:
        time_travel_python.ipynb incremental reads)."""
        t0 = storage.resolve_timestamp(start_wallclock_time)
        t1 = storage.resolve_timestamp(end_wallclock_time)
        return storage.read_as_of(self.dir, self.primary_key, as_of=t1, exclude_until=t0)

    def show(self, n: int = 5, online: bool = False) -> pd.DataFrame:
        return self.read(online=online).head(n)

    def commit_details(self, limit: int | None = None) -> dict:
        """Reference: ``fg.commit_details()`` (time_travel_python.ipynb:432)."""
        ids = storage.commit_ids(self.dir)
        if limit:
            ids = ids[-limit:]
        out = {}
        for cid in ids:
            m = storage.read_commit_meta(self.dir, cid)
            out[cid] = {
                "committedOn": m.get("committed_on"),
                "rowsInserted": m.get("rows_inserted", m.get("rows", 0)),
                "rowsUpdated": m.get("rows_updated", 0),
                "rowsDeleted": m.get("rows_deleted", 0),
            }
        return out

    # -- query algebra --------------------------------------------------------

    def select_all(self) -> Query:
        return Query(self, list(self.features))

    def select(self, features: list) -> Query:
        feats = [f if isinstance(f, Feature) else self.get_feature(f) for f in features]
        return Query(self, feats)

    def select_except(self, features: list) -> Query:
        drop = {f.name if isinstance(f, Feature) else f for f in features}
        return Query(self, [f for f in self.features if f.name not in drop])

    def filter(self, condition: _Condition) -> Query:
        return self.select_all().filter(condition)

    # -- statistics / validation / tags --------------------------------------

    def get_statistics(self, commit_time=None) -> dict:
        name = None
        if commit_time is not None:
            ts = storage.resolve_timestamp(commit_time)
            ids = [c for c in storage.commit_ids(self.dir) if c <= ts]
            name = str(ids[-1]) if ids else None
        return stats_mod.load_statistics(self.dir, name)

    def compute_statistics(self) -> dict:
        stats = stats_mod.compute_statistics(self.read(), self.statistics_config)
        stats_mod.save_statistics(self.dir, "manual", stats)
        return stats

    def attach_expectation(self, expectation) -> None:
        name = expectation if isinstance(expectation, str) else expectation.name
        if name not in self.expectation_names:
            self.expectation_names.append(name)
            self._save_meta()

    def detach_expectation(self, expectation) -> None:
        name = expectation if isinstance(expectation, str) else expectation.name
        if name in self.expectation_names:
            self.expectation_names.remove(name)
            self._save_meta()

    def get_expectations(self) -> list:
        return [self._fs.get_expectation(n) for n in self.expectation_names]

    def validate(self, df: pd.DataFrame | None = None) -> dict:
        """Run attached expectations (reference: ``fg.validate(df)``,
        feature_validation_python.ipynb:448)."""
        from hops_tpu.featurestore import validation as val_mod

        return val_mod.validate_dataframe(
            self._fs, self, _normalize(df) if df is not None else self.read(), persist=True
        )

    def get_validations(self) -> list[dict]:
        from hops_tpu.featurestore import validation as val_mod

        return val_mod.load_validations(self.dir)

    # -- tags (reference: feature_store_tags.ipynb cells 16-28) ---------------

    def _load_tags(self) -> dict:
        try:
            return storage.read_metadata(self.dir).get("tags", {})
        except FileNotFoundError:
            return {}

    def add_tag(self, name: str, value: Any) -> None:
        meta = storage.read_metadata(self.dir)
        meta.setdefault("tags", {})[name] = value
        storage.write_metadata(self.dir, meta)

    def get_tag(self, name: str) -> Any:
        return self._load_tags().get(name)

    def get_tags(self) -> dict:
        return self._load_tags()

    def delete_tag(self, name: str) -> None:
        meta = storage.read_metadata(self.dir)
        meta.get("tags", {}).pop(name, None)
        storage.write_metadata(self.dir, meta)

    # -- online ---------------------------------------------------------------

    def online_store(self) -> online_mod.OnlineStore:
        if self._online is None:
            self._online = online_mod.open_store(self.name, self.version)
        return self._online

    def get_serving_row(self, keys: dict[str, Any]) -> dict | None:
        return self.online_store().get([keys[k] for k in self.primary_key])

    def delete(self) -> None:
        import shutil

        if self.dir.exists():
            shutil.rmtree(self.dir)


class OnDemandFeatureGroup(FeatureGroup):
    """External (on-demand) feature group: no materialized commits — rows
    come from a storage connector + SQL at read time (reference:
    ``fs.create_on_demand_feature_group``, SURVEY.md §2.6)."""

    def __init__(self, feature_store, name, version=1, query: str = "", storage_connector=None, **kw):
        super().__init__(feature_store, name, version, time_travel_format=None, **kw)
        self.query = query
        self.storage_connector = storage_connector

    def save(self, df=None, write_options=None) -> "OnDemandFeatureGroup":
        sample = self.read().head(100)
        self._features = schema_from_dataframe(sample, self.primary_key, self.partition_key)
        self._save_meta()
        meta = storage.read_metadata(self.dir)
        meta["on_demand"] = True
        meta["query"] = self.query
        meta["storage_connector"] = getattr(self.storage_connector, "name", None)
        storage.write_metadata(self.dir, meta)
        return self

    def read(self, wallclock_time=None, online=False, dataframe_type="pandas") -> pd.DataFrame:
        if self.query:
            if getattr(self.storage_connector, "executes_sql", False):
                # SQL-capable connectors (JDBC over embedded sqlite)
                # execute the query in the external database itself —
                # the reference's external-SQL on-demand FG semantics
                # (ComputeFeatures.scala:179-191, snowflake role).
                try:
                    return self.storage_connector.read(query=self.query)
                except (RuntimeError, NotImplementedError):
                    pass  # config-only connector: fall back to the gateway
            from hops_tpu.sql import gateway

            return gateway.execute(self.query, feature_store=self._fs, connector=self.storage_connector)
        if self.storage_connector is not None:
            return self.storage_connector.read()
        raise ValueError("on-demand feature group needs a query or a storage connector")


def _apply_commit(
    before: pd.DataFrame | None, df: pd.DataFrame, operation: str, primary_key: list[str]
) -> pd.DataFrame:
    """In-memory equivalent of replaying the new commit on top of ``before``."""
    if before is None or not len(before):
        return df if operation != "delete" else pd.DataFrame(columns=df.columns)
    if operation == "delete":
        if not primary_key:
            return before
        doomed = df.set_index(primary_key).index
        return before[~before.set_index(primary_key).index.isin(doomed)]
    merged = pd.concat([before, df], ignore_index=True)
    if primary_key:
        merged = merged.drop_duplicates(subset=primary_key, keep="last")
    return merged.reset_index(drop=True)


def _normalize(df: pd.DataFrame) -> pd.DataFrame:
    """Lowercase column names (the reference's Hive layer is
    case-insensitive; hsfs lowercases feature names)."""
    df = df.copy()
    df.columns = [str(c).lower() for c in df.columns]
    return df
