"""Data feeder: training-dataset → device-ready batches.

The reference fed training through ``td.tf_data(...).tf_record_dataset
(process=True, batch_size, num_epochs)`` (training_datasets.ipynb:
409-429). The TPU-native path is :meth:`DataFeeder.numpy_iterator`:
host-side shuffled batch assembly (NumPy, optionally through the native
record-IO engine) with :func:`prefetch_to_device` overlapping H2D copies
with compute — static shapes, drop_remainder by default, so every batch
jits to the same executable. ``tf_record_dataset``/``tf_csv_dataset``
are provided for tf.data users.
"""

from __future__ import annotations

from typing import Any, Callable, Iterator

import numpy as np

from hops_tpu.telemetry.metrics import REGISTRY


class DataFeeder:
    def __init__(self, td, target_name: str | None = None, split: str | None = None,
                 feature_names: list[str] | None = None, is_training: bool = True):
        self._td = td
        self.target_name = target_name.lower() if target_name else None
        self.split = split
        self.is_training = is_training
        names = [f.name for f in td.features]
        if feature_names:
            self.feature_names = [n.lower() for n in feature_names]
        else:
            self.feature_names = [n for n in names if n != self.target_name]

    # -- JAX-native path ------------------------------------------------------

    def numpy_arrays(self) -> tuple[np.ndarray, np.ndarray | None]:
        """Whole split as (X, y) float arrays (small-data path).

        Non-numeric features are integer-encoded against the sorted
        vocabulary of the column across the WHOLE training dataset (all
        splits), so train/test splits of the same TD agree on the
        encoding even when a split is missing some categories.
        """
        df = self._td.read(split=self.split)
        full = None  # lazy: only read the unsplit TD if a column needs a vocab
        cols = []
        for name in self.feature_names:
            s = df[name]
            try:
                col = s.to_numpy(dtype=np.float32)
            except (ValueError, TypeError):
                if full is None:
                    full = df if self.split is None else self._td.read(split=None)
                vocab = {
                    v: i for i, v in enumerate(sorted(full[name].astype(str).unique()))
                }
                col = s.astype(str).map(vocab).to_numpy(dtype=np.float32)
            cols.append(col)
        x = np.stack(cols, axis=1) if cols else np.zeros((len(df), 0), np.float32)
        y = None
        if self.target_name:
            y = df[self.target_name].to_numpy()
        return x, y

    def numpy_iterator(
        self,
        batch_size: int,
        num_epochs: int | None = 1,
        shuffle: bool | None = None,
        drop_remainder: bool = True,
        seed: int = 0,
        transform: Callable[[np.ndarray, Any], Any] | None = None,
        process_sharded: bool = False,
        sharding: Any = None,
        start_step: int = 0,
    ) -> Iterator:
        """Yield ``(x, y)`` (or ``x`` when no target) NumPy batches.

        ``num_epochs=None`` repeats forever (the tf.data contract).
        Static batch shapes: with ``drop_remainder=True`` every yielded
        batch triggers exactly one XLA compilation.

        Multihost input sharding (``process_sharded=True``):
        ``batch_size`` is the GLOBAL batch size; every process computes
        the same seed-derived epoch permutation and yields only its own
        ``batch_size / process_count`` slice of each global batch — the
        TPU answer to the reference's autoshard-OFF + per-worker
        slicing (multiworkermirroredstrategy_mnist_example.ipynb:184).
        Feed the yielded local shards to
        ``strategy.distribute_batch`` — or pass ``sharding`` (a
        ``jax.sharding.Sharding`` for the GLOBAL batch) and the
        iterator assembles global ``jax.Array``s itself via
        ``jax.make_array_from_process_local_data``, so a
        ``collective_all_reduce`` step consumes the feeder directly.

        Preemption resume (``start_step``): the stream is a pure
        function of ``seed``, so ``start_step=k`` fast-forwards to
        exactly the batch a fresh iterator would yield k-th — restored
        training continues the same shuffle order mid-epoch instead of
        re-seeing early batches (pair with
        ``runtime.preemption.run_preemptible``, which knows the
        restored step count).
        """
        if shuffle is None:
            shuffle = self.is_training
        if sharding is not None and not process_sharded:
            raise ValueError("sharding requires process_sharded=True")
        shard_index, shard_count = 0, 1
        if process_sharded:
            import jax

            shard_index, shard_count = jax.process_index(), jax.process_count()
            if batch_size % shard_count:
                raise ValueError(
                    f"global batch {batch_size} not divisible by "
                    f"{shard_count} processes"
                )
            if not drop_remainder:
                raise ValueError(
                    "process_sharded requires drop_remainder=True "
                    "(every process must hold an equal, full shard)"
                )
        local_bs = batch_size // shard_count
        lo = shard_index * local_bs
        x, y = self.numpy_arrays()
        n = len(x)
        # The permutation stream depends only on the seed, so every
        # process slices the SAME global order — shards are disjoint by
        # construction.
        rng = np.random.RandomState(seed)

        layout_checked = False

        def check_layout(global_shape):
            check_process_batch_layout(sharding, global_shape, lo, local_bs)

        def assemble(batch):
            import jax

            nonlocal layout_checked
            if not layout_checked:
                leaf = jax.tree.leaves(batch)[0]
                check_layout((batch_size,) + np.shape(leaf)[1:])
                layout_checked = True
            return jax.tree.map(
                lambda a: jax.make_array_from_process_local_data(
                    sharding, np.asarray(a)
                ),
                batch,
            )

        end = n - (n % batch_size) if drop_remainder else n
        steps_per_epoch = max(1, (end + batch_size - 1) // batch_size)
        skip_epochs, skip_steps = divmod(start_step, steps_per_epoch)

        # Feed throughput: rate(batches_total) is batches produced/sec,
        # the input-pipeline half of the steps/sec picture.
        m_batches = REGISTRY.counter(
            "hops_tpu_feed_batches_total",
            "Batches yielded by DataFeeder.numpy_iterator",
        ).labels()
        m_examples = REGISTRY.counter(
            "hops_tpu_feed_examples_total",
            "Examples yielded by DataFeeder.numpy_iterator (local rows)",
        ).labels()

        epoch = 0
        while num_epochs is None or epoch < num_epochs:
            order = rng.permutation(n) if shuffle else np.arange(n)
            if epoch < skip_epochs:
                # Consume this epoch's permutation draw and move on —
                # the RNG stream must stay aligned with an unskipped run.
                epoch += 1
                continue
            first = skip_steps * batch_size if epoch == skip_epochs else 0
            for start in range(first, end, batch_size):
                idx = order[start + lo:start + lo + local_bs]
                bx = x[idx]
                by = y[idx] if y is not None else None
                if transform is not None:
                    out = transform(bx, by)
                elif by is None:
                    out = bx
                else:
                    out = (bx, by)
                m_batches.inc()
                m_examples.inc(len(bx))
                yield assemble(out) if sharding is not None else out
            epoch += 1

    # -- parallel pipeline ----------------------------------------------------

    def loader(
        self,
        batch_size: int,
        num_workers: int = 2,
        **kwargs: Any,
    ):
        """The staged parallel pipeline over this feeder's materialized
        split: a :class:`hops_tpu.featurestore.loader.DataLoader` with
        snapshot/restore and per-stage telemetry (shuffle defaults to
        ``is_training``). Its stream is byte-identical across the
        loader's own worker counts — but NOT to
        :meth:`numpy_iterator`'s: the two derive shuffle orders from
        different RNG streams, so a seed that reproduced one does not
        reproduce the other (mid-run migration should resume via the
        loader's own ``state_dict``, not ``start_step``). See
        ``loader.py`` for the knobs (``queue_depth``, ``transform``,
        ``process_sharded``, ``reuse_buffers``...)."""
        from hops_tpu.featurestore.loader import ArraySource, DataLoader

        kwargs.setdefault("shuffle", self.is_training)
        return DataLoader(
            ArraySource.from_feeder(self), batch_size,
            num_workers=num_workers, **kwargs,
        )

    # -- tf.data compatibility ------------------------------------------------

    def tf_record_dataset(self, process: bool = False, batch_size: int | None = None,
                          num_epochs: int | None = None):
        """Reference: ``feeder.tf_record_dataset(process=True, batch_size,
        num_epochs)`` — returns a ``tf.data.Dataset``; with ``process=True``
        it is batched ``(features, label)`` ready for ``model.fit``."""
        import tensorflow as tf

        d = self._td.dir / (self.split or ("data" if not self._td.splits else next(iter(self._td.splits))))
        files = sorted(str(p) for p in d.glob("*.tfrecord"))
        if not files:
            raise FileNotFoundError(f"no tfrecord files in {d}")
        schema = self._tf_schema()
        ds = tf.data.TFRecordDataset(files)
        if not process:
            return ds.map(lambda raw: tf.io.parse_single_example(raw, schema))
        if self.target_name is None:
            raise ValueError("process=True requires target_name on the feeder")

        def to_xy(raw):
            ex = tf.io.parse_single_example(raw, schema)
            xs = [tf.cast(ex[n], tf.float32) for n in self.feature_names]
            x = tf.stack([tf.reshape(v, []) for v in xs])
            y = ex[self.target_name]
            return x, y

        ds = ds.map(to_xy, num_parallel_calls=tf.data.AUTOTUNE)
        if self.is_training:
            ds = ds.shuffle(10_000)
        ds = ds.batch(batch_size or 32, drop_remainder=True)
        ds = ds.repeat(num_epochs)
        return ds.prefetch(tf.data.AUTOTUNE)

    def tf_csv_dataset(self, process: bool = False, batch_size: int | None = None,
                       num_epochs: int | None = None):
        import tensorflow as tf

        d = self._td.dir / (self.split or ("data" if not self._td.splits else next(iter(self._td.splits))))
        files = sorted(str(p) for p in d.glob("*.csv"))
        if not files:
            raise FileNotFoundError(f"no csv files in {d}")
        ds = tf.data.experimental.make_csv_dataset(
            files, batch_size=batch_size or 32, label_name=self.target_name,
            num_epochs=num_epochs, shuffle=self.is_training)
        if process:
            def to_xy(feats, label):
                x = tf.stack([tf.cast(feats[n], tf.float32) for n in self.feature_names], axis=1)
                return x, label
            ds = ds.map(to_xy)
        return ds

    def _tf_schema(self):
        import tensorflow as tf

        schema = {}
        for f in self._td.features:
            if f.type in ("int", "bigint", "boolean"):
                schema[f.name] = tf.io.FixedLenFeature([], tf.int64)
            elif f.type in ("float", "double"):
                schema[f.name] = tf.io.FixedLenFeature([], tf.float32)
            elif f.type.startswith("array"):
                schema[f.name] = tf.io.VarLenFeature(tf.float32)
            else:
                schema[f.name] = tf.io.FixedLenFeature([], tf.string)
        return schema


def check_process_batch_layout(sharding, global_shape, lo: int, local_bs: int) -> None:
    """Validate that ``sharding`` places THIS process's addressable
    shards at exactly global rows ``[lo, lo + local_bs)`` — the rows the
    process-sharded slicing yields. A mismatched layout would silently
    permute the global batch during ``make_array_from_process_local_data``
    assembly. Shared by ``DataFeeder.numpy_iterator`` and the parallel
    ``loader.DataLoader`` pipeline."""
    rows: set[int] = set()
    for idx in sharding.addressable_devices_indices_map(
        tuple(global_shape)
    ).values():
        start, stop, _ = idx[0].indices(global_shape[0])
        rows.update(range(start, stop))
    want = set(range(lo, lo + local_bs))
    if rows != want:
        raise ValueError(
            f"sharding assigns this process global rows "
            f"{sorted(rows)[:4]}.., but process-sharded slicing "
            f"yields rows {lo}..{lo + local_bs - 1}: the batch "
            "sharding must be process-major over the leading dim "
            "(mesh built from jax.devices() order, batch axis "
            "first)"
        )


def prefetch_to_device(
    iterator: Iterator, size: int = 2, sharding=None, name: str = "default"
) -> Iterator:
    """Overlap H2D transfer with compute: keep ``size`` batches in flight
    on device. With ``sharding`` (a ``jax.sharding.Sharding``) batches land
    already sharded across the mesh — the multi-chip input path.

    The queue refills BEFORE each yield, so the pipeline holds ``size``
    in-flight batches throughout (not ``size - 1`` after the first
    yield, which would under-overlap exactly when compute is fastest).
    Depth is exported as the ``hops_tpu_feed_prefetch_depth`` gauge,
    labelled ``pipeline=name`` so concurrent feeds (train + eval) don't
    clobber each other's series.
    """
    import collections

    import jax

    depth = REGISTRY.gauge(
        "hops_tpu_feed_prefetch_depth",
        "Batches currently in flight on device in prefetch_to_device",
        labels=("pipeline",),
    ).labels(pipeline=name)

    queue: collections.deque = collections.deque()
    it = iter(iterator)

    def put(batch):
        if sharding is not None:
            return jax.device_put(batch, sharding)
        return jax.device_put(batch)

    def refill():
        while len(queue) < size:
            try:
                queue.append(put(next(it)))
            except StopIteration:
                return

    refill()
    while queue:
        out = queue.popleft()
        refill()
        depth.set(len(queue))
        yield out
    depth.set(0)


def pack_documents(
    docs: "Iterator | list",
    seq_len: int,
    eos_id: int,
    pad_id: int = 0,
    drop_remainder: bool = True,
) -> np.ndarray:
    """Greedy-pack ragged token documents into ``(n, seq_len + 1)``
    rows for next-token training -- the standard LM pretraining layout:
    documents concatenate into one stream with an ``eos_id`` separator
    after each, and the stream chunks into non-overlapping rows (the
    +1 column is the shifted-target overlap consumed by
    ``make_lm_train_step``). No padding except the final partial row,
    which is ``pad_id``-padded when ``drop_remainder=False`` and
    dropped otherwise -- note ``make_lm_train_step`` computes UNMASKED
    loss over every position, so a kept padded row trains the model to
    emit ``pad_id`` after its true tail; the default drop avoids that,
    and corpora where the remainder matters should mask the loss
    themselves. Static shapes, zero pad waste in the interior -- the
    TPU-friendly alternative to per-document padding, whose waste
    scales with length variance."""
    if seq_len < 1:
        raise ValueError(f"seq_len must be >= 1, got {seq_len}")
    # Vectorized concat: per-token Python loops cost minutes and GBs
    # at pretraining scale; this is one allocation + one copy.
    parts: list[np.ndarray] = []
    for doc in docs:
        parts.append(np.asarray(doc, np.int32).reshape(-1))
        parts.append(np.asarray([eos_id], np.int32))
    stream = np.concatenate(parts) if parts else np.zeros((0,), np.int32)
    row = seq_len + 1
    n_full = len(stream) // row
    packed = stream[: n_full * row].reshape(n_full, row)
    tail = stream[n_full * row:]
    if tail.size and not drop_remainder:
        pad = np.full((1, row), pad_id, np.int32)
        pad[0, : tail.size] = tail
        packed = np.concatenate([packed, pad])
    if not packed.size:
        if not stream.size:
            raise ValueError("no input tokens: docs is empty")
        if drop_remainder:
            raise ValueError(
                f"documents too short to fill one row of {row} tokens "
                "(pass drop_remainder=False to keep a padded partial row)"
            )
        raise ValueError(
            f"documents too short to fill one row of {row} tokens"
        )
    return packed
