"""Data-validation rules + expectations ("unit tests for data").

Reference (SURVEY.md §2.6, feature_validation_python.ipynb):
``connection.get_rules()``, ``fs.create_expectation(name, rules=[
Rule(name="HAS_MIN", level="WARNING", min=0), ...]).save()``,
``fg.attach_expectation``, ``fg.validate(df)``, ``fg.get_validations()``,
and ``validation_type`` NONE/WARNING/STRICT/ALL gating inserts.
"""

from __future__ import annotations

import dataclasses
import json
import time
from pathlib import Path
from typing import Any

import numpy as np
import pandas as pd

from hops_tpu.featurestore import storage


class DataValidationError(RuntimeError):
    """Raised when a STRICT-mode insert fails validation."""


# Rule catalog: predicate(series, rule) -> (ok, observed). Mirrors the
# Deequ-derived names the reference exposes via connection.get_rules().
RULE_DEFINITIONS: dict[str, dict] = {
    "HAS_MIN": {"predicate": "bounds", "accepts": ["min", "max"],
                "description": "column minimum within [min, max]"},
    "HAS_MAX": {"predicate": "bounds", "accepts": ["min", "max"],
                "description": "column maximum within [min, max]"},
    "HAS_MEAN": {"predicate": "bounds", "accepts": ["min", "max"],
                 "description": "column mean within [min, max]"},
    "HAS_SUM": {"predicate": "bounds", "accepts": ["min", "max"],
                "description": "column sum within [min, max]"},
    "HAS_STANDARD_DEVIATION": {"predicate": "bounds", "accepts": ["min", "max"],
                               "description": "column stddev within [min, max]"},
    "HAS_SIZE": {"predicate": "bounds", "accepts": ["min", "max"],
                 "description": "row count within [min, max]"},
    "HAS_COMPLETENESS": {"predicate": "bounds", "accepts": ["min", "max"],
                         "description": "fraction of non-null values within [min, max]"},
    "HAS_UNIQUENESS": {"predicate": "bounds", "accepts": ["min", "max"],
                       "description": "fraction of values appearing exactly once"},
    "HAS_DISTINCTNESS": {"predicate": "bounds", "accepts": ["min", "max"],
                         "description": "fraction of distinct values"},
    "HAS_ENTROPY": {"predicate": "bounds", "accepts": ["min", "max"],
                    "description": "Shannon entropy within [min, max]"},
    "IS_CONTAINED_IN": {"predicate": "membership", "accepts": ["legal_values"],
                        "description": "all values in legal_values"},
    "HAS_DATATYPE": {"predicate": "datatype", "accepts": ["accepted_type"],
                     "description": "column dtype matches accepted_type"},
    "HAS_NUMBER_OF_DISTINCT_VALUES": {"predicate": "bounds", "accepts": ["min", "max"],
                                      "description": "distinct count within [min, max]"},
}


@dataclasses.dataclass
class Rule:
    """One constraint (reference: ``Rule(name="HAS_MIN", level="WARNING",
    min=0)``, feature_validation_python.ipynb:304-311)."""

    name: str
    level: str = "WARNING"  # WARNING | ERROR
    min: float | None = None
    max: float | None = None
    legal_values: list | None = None
    accepted_type: str | None = None

    def to_dict(self) -> dict:
        return {k: v for k, v in dataclasses.asdict(self).items() if v is not None}

    @classmethod
    def from_dict(cls, d: dict) -> "Rule":
        return cls(**d)


def _observe(series_or_df, rule: Rule) -> float | str | None:
    name = rule.name.upper()
    if name == "HAS_SIZE":
        return float(len(series_or_df))
    s: pd.Series = series_or_df
    if name == "HAS_MIN":
        return float(s.min())
    if name == "HAS_MAX":
        return float(s.max())
    if name == "HAS_MEAN":
        return float(s.mean())
    if name == "HAS_SUM":
        return float(s.sum())
    if name == "HAS_STANDARD_DEVIATION":
        return float(s.std()) if len(s) > 1 else 0.0
    if name == "HAS_COMPLETENESS":
        return float(s.notna().mean()) if len(s) else 1.0
    if name == "HAS_UNIQUENESS":
        counts = s.value_counts()
        return float((counts == 1).sum() / len(s)) if len(s) else 1.0
    if name == "HAS_DISTINCTNESS":
        return float(s.nunique() / len(s)) if len(s) else 1.0
    if name == "HAS_NUMBER_OF_DISTINCT_VALUES":
        return float(s.nunique())
    if name == "HAS_ENTROPY":
        p = s.value_counts(normalize=True).to_numpy()
        return float(-(p * np.log2(p)).sum()) if len(p) else 0.0
    if name == "IS_CONTAINED_IN":
        return float(s.isin(rule.legal_values or []).mean()) if len(s) else 1.0
    if name == "HAS_DATATYPE":
        return str(s.dtype)
    return None


def _check(observed, rule: Rule) -> bool:
    name = rule.name.upper()
    if name == "IS_CONTAINED_IN":
        return observed == 1.0
    if name == "HAS_DATATYPE":
        want = (rule.accepted_type or "").lower()
        got = str(observed).lower()
        aliases = {
            "integral": ("int",), "int": ("int",),
            "fractional": ("float", "double"), "float": ("float",),
            "string": ("object", "str", "string"), "boolean": ("bool",),
        }
        return any(got.startswith(p) for p in aliases.get(want, (want,)))
    ok = True
    if rule.min is not None:
        ok = ok and observed >= rule.min
    if rule.max is not None:
        ok = ok and observed <= rule.max
    return ok


@dataclasses.dataclass
class Expectation:
    """A named set of rules over a set of features (reference:
    ``fs.create_expectation(...).save()``)."""

    _fs: Any
    name: str
    description: str = ""
    features: list[str] = dataclasses.field(default_factory=list)
    rules: list[Rule] = dataclasses.field(default_factory=list)

    def save(self) -> "Expectation":
        d = storage.feature_store_root() / "expectations"
        d.mkdir(parents=True, exist_ok=True)
        (d / f"{self.name}.json").write_text(json.dumps({
            "name": self.name,
            "description": self.description,
            "features": self.features,
            "rules": [r.to_dict() for r in self.rules],
        }, indent=2))
        return self

    @classmethod
    def load(cls, fs, name: str) -> "Expectation":
        p = storage.feature_store_root() / "expectations" / f"{name}.json"
        d = json.loads(p.read_text())
        return cls(fs, d["name"], d.get("description", ""), d.get("features", []),
                   [Rule.from_dict(r) for r in d.get("rules", [])])


def validate_dataframe(fs, fg, df: pd.DataFrame, persist: bool = False) -> dict:
    """Evaluate every attached expectation; returns the validation report
    dict (status: SUCCESS | WARNING | FAILURE)."""
    results = []
    worst = "SUCCESS"
    for exp_name in fg.expectation_names:
        exp = Expectation.load(fs, exp_name)
        for feature in (exp.features or [f.name for f in fg.features]):
            for rule in exp.rules:
                size_rule = rule.name.upper() == "HAS_SIZE"
                if not size_rule and feature not in df.columns:
                    status, observed = "FAILURE", "missing column"
                else:
                    observed = _observe(df if size_rule else df[feature], rule)
                    ok = _check(observed, rule)
                    status = "SUCCESS" if ok else ("FAILURE" if rule.level.upper() == "ERROR" else "WARNING")
                results.append({
                    "expectation": exp.name, "feature": feature,
                    "rule": rule.name, "level": rule.level,
                    "observed": observed, "status": status,
                })
                worst = _worse(worst, status)
    report = {
        "validation_time": int(time.time() * 1000),
        "status": worst,
        "expectation_results": results,
    }
    if persist and fg.expectation_names:
        vdir: Path = fg.dir / "validations"
        vdir.mkdir(parents=True, exist_ok=True)
        (vdir / f"{report['validation_time']}.json").write_text(
            json.dumps(report, indent=2, default=str))
    return report


def load_validations(fg_dir: Path) -> list[dict]:
    vdir = fg_dir / "validations"
    if not vdir.exists():
        return []
    return [json.loads(p.read_text()) for p in sorted(vdir.glob("*.json"))]


def _worse(a: str, b: str) -> str:
    order = {"SUCCESS": 0, "WARNING": 1, "FAILURE": 2}
    return a if order[a] >= order[b] else b
