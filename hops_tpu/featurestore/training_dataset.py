"""Training datasets: materialized, split, versioned training data.

Reference surface (SURVEY.md §2.6, training_datasets.ipynb:125,156,
409-429): ``fs.create_training_dataset(name, data_format, splits={...},
version).save(query_or_df)``; ``td.read(split)``; ``td.tf_data(...)``
feeder; ``td.query`` replay; online serving vectors via
``td.init_prepared_statement()`` / ``td.get_serving_vector({pk: v})``.

Formats: parquet, csv, tfrecord (via TensorFlow when present), and
"recordio" — the native engine's format (hops_tpu/native/recordio.cc),
the TPU-first default for shuffled feeding.
"""

from __future__ import annotations

import json
from typing import TYPE_CHECKING, Any

import numpy as np
import pandas as pd

from hops_tpu.featurestore import statistics as stats_mod
from hops_tpu.featurestore import storage
from hops_tpu.featurestore.feature import Feature, schema_from_dataframe
from hops_tpu.featurestore.query import Query

if TYPE_CHECKING:
    from hops_tpu.featurestore.connection import FeatureStore

_KIND = "trainingdatasets"


class _MissingConnector:
    """Stand-in for a storage connector recorded in TD metadata but
    absent from the connector registry. ``resolve`` raises (so reads
    fail with the real cause) with the RuntimeError that
    ``TrainingDataset.delete`` tolerates."""

    type = "MISSING"

    def __init__(self, name: str):
        self.name = name

    def resolve(self, path: str | None = None):
        raise RuntimeError(
            f"storage connector {self.name!r} is recorded in this training "
            "dataset's metadata but missing from the connector registry; "
            "recreate it with fs.create_storage_connector to read the data")

# - petastorm: schema'd columnar with tensor columns + row-group reader
#   (featurestore/columnar.py; reference PetastormHelloWorld.ipynb:21-44)
# - delta: transactional commit-log materialization with append/overwrite
#   history and as_of reads (reference delta/DeltaOnHops.ipynb), reusing
#   the feature-group commit-log machinery (featurestore/storage.py)
_FORMATS = ("parquet", "csv", "tfrecord", "recordio", "petastorm", "delta")
_FORMAT_ALIASES = {"hudi": "delta"}


class TrainingDataset:
    def __init__(
        self,
        feature_store: "FeatureStore",
        name: str,
        version: int = 1,
        description: str = "",
        data_format: str = "parquet",
        splits: dict[str, float] | None = None,
        seed: int | None = None,
        label: list[str] | None = None,
        coalesce: bool = False,
        storage_connector: Any = None,
        statistics_config: Any = None,
        train_split: str | None = None,
    ):
        data_format = _FORMAT_ALIASES.get(data_format.lower(), data_format.lower())
        if data_format not in _FORMATS:
            raise ValueError(
                f"data_format must be one of {_FORMATS} (or aliases "
                f"{tuple(_FORMAT_ALIASES)}), got {data_format!r}"
            )
        self._fs = feature_store
        self.name = name
        self.version = version
        self.description = description
        self.data_format = data_format
        self.splits = dict(splits or {})
        self.seed = seed
        self.label = [l.lower() for l in (label or [])]
        self.coalesce = coalesce
        self.storage_connector = storage_connector
        self.statistics_config = stats_mod.StatisticsConfig.from_dict(statistics_config)
        self.train_split = train_split
        self._features: list[Feature] = []
        self._query_dict: dict | None = None
        self._serving_prepared = False
        self._serving_fgs: list = []

    # -- identity -------------------------------------------------------------

    @property
    def meta_dir(self):
        """Workspace registry entry — metadata always lives here so
        ``get_training_dataset`` finds connector-backed TDs too."""
        return storage.entity_dir(_KIND, self.name, self.version)

    @property
    def dir(self):
        """Data root: the workspace by default, or the storage
        connector's resolved directory when one is set (reference:
        training_datasets.ipynb cell 12 saves a TD through an S3
        connector)."""
        if self.storage_connector is not None:
            if not hasattr(self.storage_connector, "resolve"):
                raise ValueError(
                    f"storage connector {self.storage_connector.name!r} "
                    f"({self.storage_connector.type}) cannot host training "
                    "datasets: only path-backed connectors (HOPSFS, mounted "
                    "S3) materialize files")
            return self.storage_connector.resolve(f"{self.name}_{self.version}")
        return self.meta_dir

    @property
    def features(self) -> list[Feature]:
        if not self._features and (self.meta_dir / "metadata.json").exists():
            self._load_meta()
        return self._features

    @property
    def query(self) -> Query | None:
        """Replay of the query this TD was built from (reference:
        ``td.query``, training_datasets.ipynb cell 14)."""
        if self._query_dict is None and (self.meta_dir / "metadata.json").exists():
            self._load_meta()
        if self._query_dict is None:
            return None
        return Query.from_dict(self._fs, self._query_dict)

    def __repr__(self) -> str:
        return f"TrainingDataset({self.name!r}, version={self.version}, format={self.data_format})"

    def _save_meta(self) -> None:
        storage.write_metadata(self.meta_dir, {
            "name": self.name,
            "version": self.version,
            "description": self.description,
            "data_format": self.data_format,
            "splits": self.splits,
            "seed": self.seed,
            "label": self.label,
            "coalesce": self.coalesce,
            "train_split": self.train_split,
            "storage_connector": getattr(self.storage_connector, "name", None),
            "features": [f.to_dict() for f in self._features],
            "query": self._query_dict,
            # A re-save must not wipe tags set via add_tag.
            "tags": (storage.read_metadata(self.meta_dir).get("tags", {})
                     if (self.meta_dir / "metadata.json").exists() else {}),
        })

    def _load_meta(self) -> None:
        meta = storage.read_metadata(self.meta_dir)
        self.description = meta.get("description", "")
        self.data_format = meta.get("data_format", "parquet")
        self.splits = meta.get("splits", {})
        self.seed = meta.get("seed")
        self.label = meta.get("label", [])
        self.coalesce = meta.get("coalesce", False)
        self.train_split = meta.get("train_split")
        sc = meta.get("storage_connector")
        if sc and self.storage_connector is None:
            try:
                self.storage_connector = self._fs.get_storage_connector(sc)
            except KeyError:
                # Registry entry gone (wiped registry, partial workspace
                # copy): keep the TD loadable — and deletable — with a
                # sentinel that names the problem on any data access.
                self.storage_connector = _MissingConnector(sc)
        self._features = [Feature.from_dict(f) for f in meta.get("features", [])]
        self._query_dict = meta.get("query")

    # -- materialization ------------------------------------------------------

    def save(self, data: Query | pd.DataFrame, write_options: dict | None = None) -> "TrainingDataset":
        if isinstance(data, Query):
            df = data.read()
            self._query_dict = data.to_dict()
        else:
            df = data.copy()
            df.columns = [str(c).lower() for c in df.columns]
        self._features = schema_from_dataframe(df, [], [])
        split_frames = self._split(df)
        for split_name, frame in split_frames.items():
            self._write_split(split_name, frame)
        self._save_meta()
        if self.statistics_config.enabled:
            stats_mod.save_statistics(
                self.meta_dir, "all", stats_mod.compute_statistics(df, self.statistics_config))
        return self

    def insert(self, data: Query | pd.DataFrame, overwrite: bool = True,
               write_options: dict | None = None) -> "TrainingDataset":
        """Re-materialize. For ``delta`` format, ``overwrite=False``
        appends a commit to each split's log instead (DeltaOnHops.ipynb
        append-mode write); ``overwrite=True`` starts a new table version
        that as_of reads can still see past."""
        if self.data_format == "delta" and not overwrite:
            df = data.read() if isinstance(data, Query) else data.copy()
            df.columns = [str(c).lower() for c in df.columns]
            for split_name, frame in self._split(df).items():
                storage.write_commit(self._split_dir(split_name), frame, operation="insert")
            return self
        return self.save(data, write_options)

    def _split(self, df: pd.DataFrame) -> dict[str, pd.DataFrame]:
        if not self.splits:
            return {"": df}
        fractions = np.array(list(self.splits.values()), dtype=float)
        fractions = fractions / fractions.sum()
        rng = np.random.RandomState(self.seed if self.seed is not None else 0)
        perm = rng.permutation(len(df))
        bounds = np.floor(np.cumsum(fractions) * len(df)).astype(int)
        bounds[-1] = len(df)  # float rounding must never drop tail rows
        out, start = {}, 0
        for split_name, end in zip(self.splits, bounds):
            out[split_name] = df.iloc[perm[start:end]].reset_index(drop=True)
            start = end
        return out

    def _split_dir(self, split: str):
        d = self.dir / (split or "data")
        d.mkdir(parents=True, exist_ok=True)
        return d

    def _write_split(self, split: str, df: pd.DataFrame) -> None:
        d = self._split_dir(split)
        if self.data_format == "delta":
            # A save is a truncating commit: history before it survives
            # for as_of reads, current reads start from it.
            storage.write_commit(d, df, operation="insert", extra={"truncate": True})
            return
        # coalesce=True -> single output file (training-data-coalesced.ipynb:61);
        # otherwise shard for parallel reads.
        n_parts = 1 if (self.coalesce or len(df) < 10_000) else 8
        parts = np.array_split(np.arange(len(df)), n_parts)
        for i, idx in enumerate(parts):
            part = df.iloc[idx]
            stem = d / f"part-{i:05d}"
            if self.data_format == "parquet":
                part.to_parquet(f"{stem}.parquet", index=False)
            elif self.data_format == "csv":
                part.to_csv(f"{stem}.csv", index=False)
            elif self.data_format == "tfrecord":
                _write_tfrecord(part, f"{stem}.tfrecord")
            elif self.data_format == "recordio":
                _write_recordio(part, f"{stem}.rio")
            elif self.data_format == "petastorm":
                from hops_tpu.featurestore import columnar

                columnar.write_dataset(d, part, part=i)

    # -- read path ------------------------------------------------------------

    def read(self, split: str | None = None, read_options: dict | None = None) -> pd.DataFrame:
        """``read_options`` (per format): ``{"as_of": ts}`` time-travels a
        delta TD; ``{"columns": [...]}`` column-projects a petastorm TD."""
        opts = read_options or {}
        d = self.dir / (split or ("data" if not self.splits else next(iter(self.splits))))
        if not d.exists():
            raise KeyError(f"split {split!r} of {self.name}_{self.version} not materialized")
        if self.data_format == "delta":
            return _read_delta(d, as_of=opts.get("as_of"))
        if self.data_format == "petastorm":
            from hops_tpu.featurestore import columnar

            return columnar.read_dataset(d, columns=opts.get("columns"))
        frames = []
        for p in sorted(d.iterdir()):
            if p.suffix == ".parquet":
                frames.append(pd.read_parquet(p))
            elif p.suffix == ".csv":
                frames.append(pd.read_csv(p))
            elif p.suffix == ".tfrecord":
                frames.append(_read_tfrecord(p, self.features))
            elif p.suffix == ".rio":
                frames.append(_read_recordio(p))
        return pd.concat(frames, ignore_index=True) if frames else pd.DataFrame()

    def commit_details(self, split: str | None = None) -> dict[int, dict]:
        """Delta-format history: commit id -> metadata, oldest first
        (reference: Delta table history, DeltaOnHops.ipynb)."""
        if self.data_format != "delta":
            raise ValueError(f"commit_details requires delta format, not {self.data_format}")
        d = self.dir / (split or ("data" if not self.splits else next(iter(self.splits))))
        return {c: storage.read_commit_meta(d, c) for c in storage.commit_ids(d)}

    def row_group_reader(self, split: str | None = None,
                         columns: list[str] | None = None,
                         shuffle: bool = True, seed: int = 0):
        """Petastorm-format streaming reader: decoded numpy batches one
        parquet row group at a time, shuffled at row-group granularity
        (the ``make_reader`` role, PetastormHelloWorld.ipynb)."""
        if self.data_format != "petastorm":
            raise ValueError(f"row_group_reader requires petastorm format, not {self.data_format}")
        from hops_tpu.featurestore import columnar

        d = self.dir / (split or ("data" if not self.splits else next(iter(self.splits))))
        return columnar.RowGroupReader(d, columns=columns, shuffle=shuffle, seed=seed)

    def show(self, n: int = 5, split: str | None = None) -> pd.DataFrame:
        return self.read(split=split).head(n)

    def get_statistics(self) -> dict:
        return stats_mod.load_statistics(self.meta_dir)

    # -- feeding (td.tf_data twin) --------------------------------------------

    def tf_data(self, target_name: str | None = None, split: str | None = None,
                feature_names: list[str] | None = None, is_training: bool = True):
        """Reference: ``td.tf_data(target_name, split, is_training)``
        (training_datasets.ipynb:409-429). Returns a :class:`DataFeeder`
        exposing ``numpy_iterator`` (the JAX-native path),
        ``tf_record_dataset`` and ``tf_csv_dataset``."""
        from hops_tpu.featurestore.feed import DataFeeder

        return DataFeeder(self, target_name=target_name, split=split,
                          feature_names=feature_names, is_training=is_training)

    def loader(self, batch_size: int, target_name: str | None = None,
               split: str | None = None, is_training: bool = True,
               **kwargs: Any):
        """The staged parallel input pipeline over this TD
        (``featurestore/loader.py``): sharded readers → threaded decode
        → packed batch assembly → ``prefetch_to_device``, with
        snapshot/restore for preemption resume. Equivalent to
        ``td.tf_data(...).loader(batch_size, ...)``."""
        return self.tf_data(target_name=target_name, split=split,
                            is_training=is_training).loader(batch_size, **kwargs)

    # -- online serving vectors ----------------------------------------------

    @property
    def serving_keys(self) -> list[str]:
        """Union of primary keys of the query's feature groups."""
        q = self.query
        if q is None:
            return []
        keys: list[str] = []
        for fg in q.feature_groups:
            for k in fg.primary_key:
                if k not in keys:
                    keys.append(k)
        return keys

    def init_prepared_statement(self) -> None:
        """Open the online stores of the constituent groups (reference:
        JDBC prepared statements, feature_vector_model_serving.ipynb:175)."""
        q = self.query
        if q is None:
            raise ValueError("training dataset was not built from a query")
        self._serving_fgs = [fg for fg in q.feature_groups if fg.online_enabled]
        if not self._serving_fgs:
            raise ValueError("no online-enabled feature groups in this training dataset")
        for fg in self._serving_fgs:
            fg.online_store()
        self._serving_prepared = True

    def get_serving_vector(self, entry: dict[str, Any]) -> list:
        """Point lookup across the online stores, returned in training-data
        feature order minus the label (the reference's contract)."""
        if not self._serving_prepared:
            self.init_prepared_statement()
        merged: dict[str, Any] = {}
        for fg in self._serving_fgs:
            row = fg.get_serving_row(entry)
            if row is not None:
                merged.update(row)
        order = [f.name for f in self.features if f.name not in self.label]
        return [merged.get(name) for name in order]

    def get_serving_vectors(self, entries: list[dict[str, Any]]) -> list[list]:
        return [self.get_serving_vector(e) for e in entries]

    # -- tags -----------------------------------------------------------------

    def add_tag(self, name: str, value: Any) -> None:
        meta = storage.read_metadata(self.meta_dir)
        meta.setdefault("tags", {})[name] = value
        storage.write_metadata(self.meta_dir, meta)

    def get_tag(self, name: str) -> Any:
        return storage.read_metadata(self.meta_dir).get("tags", {}).get(name)

    def get_tags(self) -> dict:
        return storage.read_metadata(self.meta_dir).get("tags", {})

    def delete_tag(self, name: str) -> None:
        meta = storage.read_metadata(self.meta_dir)
        meta.get("tags", {}).pop(name, None)
        storage.write_metadata(self.meta_dir, meta)

    def delete(self) -> None:
        import shutil

        dirs = {self.meta_dir}
        try:
            dirs.add(self.dir)
        except (ValueError, RuntimeError):
            # Unresolvable connector (SQL-typed, or mount absent on this
            # host): the registry entry must still be removable.
            pass
        for d in dirs:
            if d.exists():
                shutil.rmtree(d)


# -- format codecs ------------------------------------------------------------


def _read_delta(d, as_of=None) -> pd.DataFrame:
    """Replay a delta TD split: commits from the last truncating commit
    at-or-before ``as_of`` (truncate = a fresh save over the table).
    The replay itself is storage.read_as_of — one commit-log codec."""
    ts = storage.resolve_timestamp(as_of)
    ids = [c for c in storage.commit_ids(d) if ts is None or c <= ts]
    truncates = [c for c in ids if storage.read_commit_meta(d, c).get("truncate")]
    exclude_until = truncates[-1] - 1 if truncates else None
    if not ids:
        return pd.DataFrame()
    return storage.read_as_of(d, primary_key=[], as_of=ts, exclude_until=exclude_until)


def _write_tfrecord(df: pd.DataFrame, path: str) -> None:
    try:
        import tensorflow as tf
    except ImportError as e:  # pragma: no cover
        raise RuntimeError("tfrecord format requires tensorflow") from e

    with tf.io.TFRecordWriter(path) as w:
        for rec in df.to_dict(orient="records"):
            feats = {}
            for k, v in rec.items():
                if isinstance(v, (int, np.integer, bool)):
                    feats[k] = tf.train.Feature(int64_list=tf.train.Int64List(value=[int(v)]))
                elif isinstance(v, (float, np.floating)):
                    feats[k] = tf.train.Feature(float_list=tf.train.FloatList(value=[float(v)]))
                elif isinstance(v, (list, np.ndarray)):
                    feats[k] = tf.train.Feature(
                        float_list=tf.train.FloatList(value=[float(x) for x in v]))
                else:
                    feats[k] = tf.train.Feature(
                        bytes_list=tf.train.BytesList(value=[str(v).encode()]))
            w.write(tf.train.Example(features=tf.train.Features(feature=feats)).SerializeToString())


def _read_tfrecord(path, features: list[Feature]) -> pd.DataFrame:
    import tensorflow as tf

    rows = []
    for raw in tf.data.TFRecordDataset(str(path)):
        ex = tf.train.Example()
        ex.ParseFromString(raw.numpy())
        row = {}
        for k, feat in ex.features.feature.items():
            kind = feat.WhichOneof("kind")
            vals = list(getattr(feat, kind).value)
            if kind == "bytes_list":
                vals = [v.decode() for v in vals]
            row[k] = vals[0] if len(vals) == 1 else vals
        rows.append(row)
    return pd.DataFrame(rows)


def _write_recordio(df: pd.DataFrame, path: str) -> None:
    from hops_tpu.native.recordio import RecordWriter

    with RecordWriter(path) as w:
        for rec in df.to_dict(orient="records"):
            w.write(json.dumps(rec, default=str).encode())


def _read_recordio(path) -> pd.DataFrame:
    from hops_tpu.native.recordio import RecordReader

    with RecordReader(path) as r:
        return pd.DataFrame(
            [json.loads(rec) for rec in r.read_batch(range(len(r)))]
        )
