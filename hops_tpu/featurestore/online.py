"""Online feature store — embedded KV store for serving vectors.

The reference's online store was MySQL Cluster (NDB) reached over JDBC
prepared statements (`td.get_serving_vector`,
feature_vector_model_serving.ipynb:175-196 — SURVEY.md §2.6, "implied
native"). The TPU build replaces it with an embedded key-value store:
the native C++ engine in ``hops_tpu/native`` (open-addressing hash index
over an append-only mmap'd log) when built, else a pure-sqlite fallback
with identical semantics. Keys are the JSON-encoded primary-key values
of a row; values are the row — packed struct records behind
``wirecodec.ROW_FORMAT_PACKED`` by default, legacy JSON rows when
``HOPS_TPU_ONLINE_ROW_FORMAT=json`` (and always on read: the format is
sniffed per value, so existing ``.hkv``/``.db`` files keep working and
the two formats coexist in one store).
"""

from __future__ import annotations

import json
import os
import sqlite3
import threading
from pathlib import Path
from typing import Any, Iterator

import pandas as pd

from hops_tpu.featurestore import storage
from hops_tpu.runtime import wirecodec
from hops_tpu.runtime.logging import get_logger

log = get_logger(__name__)


def _key_of(pk_values: list[Any]) -> str:
    return json.dumps(pk_values, default=str, separators=(",", ":"))


def _row_format() -> str:
    """Write-side row format: ``packed`` (default) or ``json``.

    Read paths sniff per value and never consult this — flipping the
    env var mid-life is safe and only affects new writes.
    """
    fmt = os.environ.get("HOPS_TPU_ONLINE_ROW_FORMAT", "packed") \
        .strip().lower()
    if fmt not in ("packed", "json"):
        raise ValueError(
            f"HOPS_TPU_ONLINE_ROW_FORMAT={fmt!r}: pick packed|json")
    return fmt


def _encode_row(rec: dict, fmt: str) -> str:
    if fmt == "packed":
        return wirecodec.pack_row(rec)
    return json.dumps(rec, default=str)


def _decode_row(raw: str) -> dict:
    """Decode one stored row value, sniffing the format byte."""
    if wirecodec.is_packed_row(raw):
        return wirecodec.unpack_row(raw)
    return json.loads(raw)


def _decode_rows(raws: list[str | None]) -> list[dict | None]:
    """Batched row decode for multi-gets: one ``json.loads`` of a
    joined array instead of one parser setup per key. After the native
    backend took the lookup itself to ~10us/key, the per-key Python
    ``json.loads`` became the dominant multi-get cost — joining the
    rows into a single array parses the whole batch in one C call
    (``bench.py --hot-path`` carries the before/after). Packed rows
    (``wirecodec.ROW_FORMAT_PACKED`` sniffed per value) take the
    struct-unpack path instead; a mixed batch decodes each row by its
    own format, so stores written under either setting read back
    correctly. If the joined parse fails (a malformed stored row), fall
    back to the per-row decode so the error points at the guilty row,
    exactly like the pre-batching path."""
    present = [r for r in raws if r is not None]
    if not present:
        return [None] * len(raws)
    if any(wirecodec.is_packed_row(r) for r in present):
        return [_decode_row(r) if r is not None else None for r in raws]
    try:
        decoded = json.loads("[" + ",".join(present) + "]")
    except ValueError:
        decoded = None
    if decoded is None or len(decoded) != len(present):
        # Joined parse failed — or a malformed stored row was a valid
        # JSON *fragment* with a top-level comma ('1,2'), which would
        # silently shift every later row onto the wrong key. Either
        # way, per-row decode restores the pre-batching behavior: the
        # error points at the guilty row, neighbors stay aligned.
        log.warning("online store: batched row decode failed; falling back "
                    "to per-row decode")
        return [json.loads(r) if r is not None else None for r in raws]
    it = iter(decoded)
    return [next(it) if r is not None else None for r in raws]


class OnlineStore:
    """One KV namespace per (feature group, version).

    Concurrency contract: ``self._lock`` is the WRITER lock — it
    serializes the batched put/delete/flush cycles. Reads take a
    backend-dependent path (:meth:`_read`): the sqlite backend is
    reader-safe without any lock (each reader thread gets its own WAL
    snapshot connection, seeing the last committed batch and never a
    half-flushed one), so serving-rate point lookups never queue behind
    a materialization flush; the native mmap log is NOT reader-safe
    mid-compact, so its reads briefly take the writer lock.
    """

    def __init__(self, path: Path):
        self.path = path
        self._impl = _open_backend(path)
        self._lock = threading.Lock()

    # -- write path (fg.insert with online_enabled) --------------------------

    def put_dataframe(self, df: pd.DataFrame, primary_key: list[str]) -> int:
        rows = 0
        fmt = _row_format()
        with self._lock:
            for rec in df.to_dict(orient="records"):
                key = _key_of([rec[k] for k in primary_key])
                self._impl.put(key, _encode_row(rec, fmt))
                rows += 1
            self._impl.flush()
        return rows

    def delete_keys(self, df: pd.DataFrame, primary_key: list[str]) -> None:
        with self._lock:
            for rec in df.to_dict(orient="records"):
                self._impl.delete(_key_of([rec[k] for k in primary_key]))
            self._impl.flush()

    # -- read path (prepared-statement lookups) ------------------------------
    #
    # Reads used to hit self._impl directly with no lock at all, racing
    # put_dataframe's batched flush on both backends (the sqlite
    # connection was shared across threads mid-commit; the native mmap
    # log is not reader-safe mid-compact). The fix keeps reads off the
    # writer lock where the backend can prove a consistent snapshot
    # (sqlite WAL reader connections) and takes the lock where it
    # can't (native).

    def _read(self, fn):
        """Run a read on the backend's reader-safe path, or under the
        writer lock when the backend has none (see class docstring)."""
        if getattr(self._impl, "reader_safe", False):
            return fn()
        with self._lock:
            return fn()

    def get(self, pk_values: list[Any]) -> dict | None:
        raw = self._read(lambda: self._impl.get(_key_of(pk_values)))
        return _decode_row(raw) if raw is not None else None

    def get_many(self, pk_values_list: list[list[Any]]) -> list[dict | None]:
        """Batched point lookup, results in input order (the serving
        multi-get path: one backend round trip per batch where the
        backend supports it, instead of one per key)."""
        keys = [_key_of(pk) for pk in pk_values_list]
        impl = self._impl
        if hasattr(impl, "get_many"):
            raws = self._read(lambda: impl.get_many(keys))
        else:
            raws = self._read(lambda: [impl.get(k) for k in keys])
        return _decode_rows(raws)

    def scan(self) -> Iterator[dict]:
        # Materialized under _read, not yielded lazily: a generator
        # must not hold the writer lock across the caller's loop body —
        # and on the locked path the underlying cursor would otherwise
        # run outside the lock entirely.
        rows = self._read(lambda: [_decode_row(v) for v in self._impl.scan()])
        yield from rows

    def count(self) -> int:
        return self._read(self._impl.count)

    def close(self) -> None:
        self._impl.close()


def open_store(name: str, version: int) -> OnlineStore:
    d = storage.feature_store_root() / "online"
    d.mkdir(parents=True, exist_ok=True)
    return OnlineStore(d / f"{name}_{version}")


def _open_backend(path: Path):
    """Pick the shard backend: the native log-structured engine when
    ``libhops_native.so`` is built, else sqlite.

    ``HOPS_TPU_ONLINE_BACKEND`` overrides: ``auto`` (default — prefer
    native, fall back to sqlite with a logged reason), ``native``
    (required: raise if unbuilt — a deployment that EXPECTS native
    lookup latency must not silently run 10x slower), ``sqlite``
    (force the fallback, e.g. to compare in ``bench.py --hot-path``).

    An existing shard file wins over the preference: a store created
    under one backend must keep reading its own data after the env
    changes (the two formats are not interchangeable on disk).
    """
    import os

    from hops_tpu.native import kvstore

    choice = os.environ.get("HOPS_TPU_ONLINE_BACKEND", "auto").strip().lower()
    if choice not in ("auto", "native", "sqlite"):
        raise ValueError(
            f"HOPS_TPU_ONLINE_BACKEND={choice!r}: pick auto|native|sqlite"
        )
    native_path = Path(str(path) + ".hkv")
    sqlite_path = Path(str(path) + ".db")
    # Existing data pins the backend regardless of preference.
    file_pinned = False
    if native_path.exists() and not sqlite_path.exists():
        if choice == "sqlite":
            log.warning(
                "online store %s: HOPS_TPU_ONLINE_BACKEND=sqlite but an "
                "existing native shard file wins (formats are not "
                "interchangeable on disk)", path.name,
            )
        choice = "native"
        file_pinned = True
    elif sqlite_path.exists() and not native_path.exists():
        if choice == "native":
            log.warning(
                "online store %s: HOPS_TPU_ONLINE_BACKEND=native but an "
                "existing sqlite shard file wins (formats are not "
                "interchangeable on disk)", path.name,
            )
        choice = "sqlite"
    if choice == "sqlite":
        return _SqliteKV(str(sqlite_path))
    if kvstore.available():
        return kvstore.NativeKV(str(native_path))
    if choice == "native":
        reason = (
            f"existing native shard file {native_path.name} requires the "
            "native backend (sqlite cannot read it)"
            if file_pinned
            else "HOPS_TPU_ONLINE_BACKEND=native"
        )
        raise RuntimeError(
            f"{reason}, but libhops_native.so is not built; run "
            "`make -C hops_tpu/native`"
        )
    log.info(
        "online store %s: native kvstore not built, falling back to "
        "sqlite (run `make -C hops_tpu/native` for log-structured "
        "point lookups)", path.name,
    )
    return _SqliteKV(str(sqlite_path))


class _SqliteKV:
    """Fallback backend when the native engine isn't built.

    ``self._db`` is the writer connection (callers serialize writes with
    the store's writer lock). Reads run on per-thread READER connections
    against the same WAL database: a WAL reader sees the last committed
    state for the lifetime of its cursor — never a half-flushed batch,
    never blocked by the writer — which is what makes this backend
    ``reader_safe`` (see ``OnlineStore._read``).
    """

    #: Reads need no lock: WAL snapshot isolation on reader connections.
    reader_safe = True

    def __init__(self, path: str):
        self._path = path
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.execute("CREATE TABLE IF NOT EXISTS kv (k TEXT PRIMARY KEY, v TEXT)")
        # Prepared-statement spirit of the reference: sqlite caches the
        # compiled statement; WAL keeps point reads fast under writes.
        self._db.execute("PRAGMA journal_mode=WAL")
        self._db.commit()  # table + WAL mode durable before any reader opens
        self._local = threading.local()
        self._readers_lock = threading.Lock()
        self._readers: list[sqlite3.Connection] = []  # guarded by: self._readers_lock

    def _reader(self) -> sqlite3.Connection:
        db = getattr(self._local, "db", None)
        if db is None:
            db = self._local.db = sqlite3.connect(self._path, check_same_thread=False)
            with self._readers_lock:
                self._readers.append(db)
        return db

    def put(self, key: str, value: str) -> None:
        self._db.execute("INSERT OR REPLACE INTO kv (k, v) VALUES (?, ?)", (key, value))

    def get(self, key: str) -> str | None:
        row = self._reader().execute(
            "SELECT v FROM kv WHERE k = ?", (key,)
        ).fetchone()
        return row[0] if row else None

    def get_many(self, keys: list[str]) -> list[str | None]:
        found: dict[str, str] = {}
        db = self._reader()
        # 500-key chunks: sqlite's bound-parameter limit is 999 on
        # older builds.
        for i in range(0, len(keys), 500):
            chunk = keys[i:i + 500]
            q = f"SELECT k, v FROM kv WHERE k IN ({','.join('?' * len(chunk))})"
            found.update(db.execute(q, chunk).fetchall())
        return [found.get(k) for k in keys]

    def delete(self, key: str) -> None:
        self._db.execute("DELETE FROM kv WHERE k = ?", (key,))

    def scan(self):
        yield from (v for (v,) in self._reader().execute("SELECT v FROM kv"))

    def count(self) -> int:
        return self._reader().execute("SELECT COUNT(*) FROM kv").fetchone()[0]

    def flush(self) -> None:
        self._db.commit()

    def close(self) -> None:
        self._db.commit()
        self._db.close()
        # Reader connections are per-thread but live on this object too:
        # without closing them here a serving process leaks one open
        # .db/WAL handle per (reader thread, shard) past store close.
        # Callers stop reading before close() — the concurrency contract.
        with self._readers_lock:
            readers, self._readers = list(self._readers), []
        for db in readers:
            try:
                db.close()
            except sqlite3.Error:
                log.debug("closing sqlite reader connection failed", exc_info=True)
