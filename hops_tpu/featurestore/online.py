"""Online feature store — embedded KV store for serving vectors.

The reference's online store was MySQL Cluster (NDB) reached over JDBC
prepared statements (`td.get_serving_vector`,
feature_vector_model_serving.ipynb:175-196 — SURVEY.md §2.6, "implied
native"). The TPU build replaces it with an embedded key-value store:
the native C++ engine in ``hops_tpu/native`` (open-addressing hash index
over an append-only mmap'd log) when built, else a pure-sqlite fallback
with identical semantics. Keys are the JSON-encoded primary-key values
of a row; values are the JSON row.
"""

from __future__ import annotations

import json
import sqlite3
import threading
from pathlib import Path
from typing import Any, Iterator

import pandas as pd

from hops_tpu.featurestore import storage


def _key_of(pk_values: list[Any]) -> str:
    return json.dumps(pk_values, default=str, separators=(",", ":"))


class OnlineStore:
    """One KV namespace per (feature group, version)."""

    def __init__(self, path: Path):
        self.path = path
        self._impl = _open_backend(path)
        self._lock = threading.Lock()

    # -- write path (fg.insert with online_enabled) --------------------------

    def put_dataframe(self, df: pd.DataFrame, primary_key: list[str]) -> int:
        rows = 0
        with self._lock:
            for rec in df.to_dict(orient="records"):
                key = _key_of([rec[k] for k in primary_key])
                self._impl.put(key, json.dumps(rec, default=str))
                rows += 1
            self._impl.flush()
        return rows

    def delete_keys(self, df: pd.DataFrame, primary_key: list[str]) -> None:
        with self._lock:
            for rec in df.to_dict(orient="records"):
                self._impl.delete(_key_of([rec[k] for k in primary_key]))
            self._impl.flush()

    # -- read path (prepared-statement lookups) ------------------------------

    def get(self, pk_values: list[Any]) -> dict | None:
        raw = self._impl.get(_key_of(pk_values))
        return json.loads(raw) if raw is not None else None

    def scan(self) -> Iterator[dict]:
        yield from (json.loads(v) for v in self._impl.scan())

    def count(self) -> int:
        return self._impl.count()

    def close(self) -> None:
        self._impl.close()


def open_store(name: str, version: int) -> OnlineStore:
    d = storage.feature_store_root() / "online"
    d.mkdir(parents=True, exist_ok=True)
    return OnlineStore(d / f"{name}_{version}")


def _open_backend(path: Path):
    from hops_tpu.native import kvstore

    if kvstore.available():
        return kvstore.NativeKV(str(path) + ".hkv")
    return _SqliteKV(str(path) + ".db")


class _SqliteKV:
    """Fallback backend when the native engine isn't built."""

    def __init__(self, path: str):
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.execute("CREATE TABLE IF NOT EXISTS kv (k TEXT PRIMARY KEY, v TEXT)")
        # Prepared-statement spirit of the reference: sqlite caches the
        # compiled statement; WAL keeps point reads fast under writes.
        self._db.execute("PRAGMA journal_mode=WAL")

    def put(self, key: str, value: str) -> None:
        self._db.execute("INSERT OR REPLACE INTO kv (k, v) VALUES (?, ?)", (key, value))

    def get(self, key: str) -> str | None:
        row = self._db.execute("SELECT v FROM kv WHERE k = ?", (key,)).fetchone()
        return row[0] if row else None

    def delete(self, key: str) -> None:
        self._db.execute("DELETE FROM kv WHERE k = ?", (key,))

    def scan(self):
        yield from (v for (v,) in self._db.execute("SELECT v FROM kv"))

    def count(self) -> int:
        return self._db.execute("SELECT COUNT(*) FROM kv").fetchone()[0]

    def flush(self) -> None:
        self._db.commit()

    def close(self) -> None:
        self._db.commit()
        self._db.close()
