"""Feature-store layer — the TPU build's `hsfs` equivalent.

Re-creates the capability surface of the Hopsworks Feature Store client
(reference: notebooks/featurestore/**, SURVEY.md §2.6) on a TPU-native
substrate: feature groups are schema'd, versioned, partitioned Parquet
datasets with a log-structured commit history (Hudi-style time travel);
queries are a lazy select/join/filter/`as_of` algebra executed with
pandas/pyarrow on the host (feature engineering is host-side prep work —
the TPU's MXU only ever sees the materialized training batches); training
datasets materialize query results into split files and feed JAX via
NumPy/grain iterators (the `td.tf_data` twin); online serving vectors
come from an embedded KV store instead of MySQL-NDB.

Usage mirrors the reference (feature_engineering.ipynb:92):

    import hops_tpu.featurestore as hsfs
    conn = hsfs.connection()
    fs = conn.get_feature_store()
    fg = fs.create_feature_group("sales", version=1, primary_key=["id"])
    fg.save(df)
    q = fg.select(["f1", "f2"]).join(other.select_all()).filter(fg["f1"] > 0)
    td = fs.create_training_dataset("dataset", version=1, splits={"train": 0.8, "test": 0.2})
    td.save(q)
"""

from __future__ import annotations

from hops_tpu.featurestore.connection import Connection, connection  # noqa: F401
from hops_tpu.featurestore.feature import Feature, Filter, Logic  # noqa: F401
from hops_tpu.featurestore.feature_group import FeatureGroup  # noqa: F401
from hops_tpu.featurestore.loader import (  # noqa: F401
    ArraySource,
    DataLoader,
    RecordIOSource,
    Source,
    StreamingSource,
    StreamSpan,
)
from hops_tpu.featurestore.online_serving import (  # noqa: F401
    FeatureJoinPredictor,
    Materializer,
    ShardedOnlineStore,
    open_sharded_store,
)
from hops_tpu.featurestore.query import Query  # noqa: F401
from hops_tpu.featurestore.statistics import StatisticsConfig  # noqa: F401
from hops_tpu.featurestore.training_dataset import TrainingDataset  # noqa: F401
from hops_tpu.featurestore.validation import Expectation, Rule  # noqa: F401
from hops_tpu.featurestore import bias  # noqa: F401

__all__ = [
    "Connection",
    "connection",
    "ArraySource",
    "DataLoader",
    "RecordIOSource",
    "Source",
    "StreamingSource",
    "StreamSpan",
    "Feature",
    "Filter",
    "Logic",
    "FeatureGroup",
    "FeatureJoinPredictor",
    "Materializer",
    "ShardedOnlineStore",
    "open_sharded_store",
    "Query",
    "StatisticsConfig",
    "TrainingDataset",
    "Expectation",
    "Rule",
    "bias",
]
