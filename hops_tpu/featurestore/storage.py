"""On-disk layout + log-structured commit store for feature groups.

The reference delegated storage to Hive tables / Hudi datasets on HopsFS
(SURVEY.md §3.5). Here each feature group is a directory of Parquet
commit files plus JSON commit metadata — a merge-on-read log: every
``save``/``insert`` appends one commit; reads replay commits up to a
timestamp and reduce by primary key (last write wins), which is exactly
the upsert + point-in-time (``as_of``) semantics of the reference's HUDI
path (time_travel_python.ipynb:695,432).

Layout under the project root (``fs.project_path()``):

    FeatureStore/featuregroups/<name>_<version>/
        metadata.json             # schema, keys, options, tags
        commits/<id>.parquet      # the rows written by commit <id>
        commits/<id>.json         # {"committed_on", "rows_inserted", ...}
        statistics/<id>.json
        validations/<ts>.json
    FeatureStore/trainingdatasets/<name>_<version>/...
    FeatureStore/online/<name>_<version>.kv
"""

from __future__ import annotations

import json
import time
from pathlib import Path

import pandas as pd

from hops_tpu.runtime import fs as hfs

_DELETE_COL = "_hops_deleted"  # marker column inside delete commits


def feature_store_root() -> Path:
    root = Path(hfs.project_path("FeatureStore"))
    root.mkdir(parents=True, exist_ok=True)
    return root


def entity_dir(kind: str, name: str, version: int) -> Path:
    d = feature_store_root() / kind / f"{name}_{version}"
    return d


def list_versions(kind: str, name: str) -> list[int]:
    base = feature_store_root() / kind
    if not base.exists():
        return []
    out = []
    for p in base.iterdir():
        stem, _, ver = p.name.rpartition("_")
        if stem == name and ver.isdigit():
            out.append(int(ver))
    return sorted(out)


def next_version(kind: str, name: str) -> int:
    versions = list_versions(kind, name)
    return (versions[-1] + 1) if versions else 1


def read_metadata(d: Path) -> dict:
    return json.loads((d / "metadata.json").read_text())


def write_metadata(d: Path, meta: dict) -> None:
    d.mkdir(parents=True, exist_ok=True)
    (d / "metadata.json").write_text(json.dumps(meta, indent=2, default=str))


# -- commit log ---------------------------------------------------------------


def new_commit_id(d: Path) -> int:
    """Millisecond timestamp, bumped past any existing commit id."""
    cid = int(time.time() * 1000)
    existing = commit_ids(d)
    if existing and cid <= existing[-1]:
        cid = existing[-1] + 1
    return cid


def commit_ids(d: Path) -> list[int]:
    cdir = d / "commits"
    if not cdir.exists():
        return []
    return sorted(int(p.stem) for p in cdir.glob("*.json"))


def write_commit(d: Path, df: pd.DataFrame, operation: str, extra: dict | None = None) -> int:
    cid = new_commit_id(d)
    cdir = d / "commits"
    cdir.mkdir(parents=True, exist_ok=True)
    df = df.copy()
    df[_DELETE_COL] = operation == "delete"
    df.to_parquet(cdir / f"{cid}.parquet", index=False)
    meta = {
        "commit_id": cid,
        "committed_on": pd.Timestamp.now().isoformat(),
        "operation": operation,
        "rows": int(len(df)),
        **(extra or {}),
    }
    (cdir / f"{cid}.json").write_text(json.dumps(meta, indent=2))
    return cid


def read_commit_meta(d: Path, cid: int) -> dict:
    return json.loads((d / "commits" / f"{cid}.json").read_text())


def read_as_of(
    d: Path,
    primary_key: list[str],
    as_of: int | None = None,
    exclude_until: int | None = None,
) -> pd.DataFrame:
    """Replay the commit log: concat commits in ``(exclude_until, as_of]``,
    keep the last write per primary key, drop deletions.

    ``as_of=None`` reads the latest state (reference: ``fg.read()``);
    ``as_of=ts`` is the reference's ``query.as_of(ts)``; ``exclude_until``
    gives incremental reads between two commits (``fg.read_changes``).
    """
    ids = commit_ids(d)
    if as_of is not None:
        ids = [c for c in ids if c <= as_of]
    if exclude_until is not None:
        ids = [c for c in ids if c > exclude_until]
    if not ids:
        return pd.DataFrame()
    frames = [pd.read_parquet(d / "commits" / f"{c}.parquet") for c in ids]
    df = pd.concat(frames, ignore_index=True)
    if primary_key:
        df = df.drop_duplicates(subset=primary_key, keep="last")
    if _DELETE_COL in df.columns:
        df = df[~df[_DELETE_COL].fillna(False)].drop(columns=[_DELETE_COL])
    return df.reset_index(drop=True)


def resolve_timestamp(ts) -> int | None:
    """Accept ms epoch ints, datetimes, or the reference's string formats
    (e.g. ``"20210101000000"`` / ISO dates) and return ms epoch."""
    if ts is None:
        return None
    if isinstance(ts, (int, float)):
        return int(ts)
    if isinstance(ts, str) and ts.isdigit():
        if len(ts) == 14:  # reference format yyyymmddHHMMSS
            ts = pd.Timestamp(
                f"{ts[0:4]}-{ts[4:6]}-{ts[6:8]} {ts[8:10]}:{ts[10:12]}:{ts[12:14]}"
            )
        else:  # a stringified ms-epoch commit id
            return int(ts)
    return int(pd.Timestamp(ts).timestamp() * 1000)
