"""Storage connectors: named external data sources.

Reference (SURVEY.md §2.6): ``fs.get_storage_connector(name[, "S3"])``
for S3 training-dataset sinks and ingest
(S3-Ingest-to-Feature-Store-basics.ipynb:100), Snowflake
(``connector.snowflake_connector_options()``), Redshift/JDBC, and the
default HopsFS connector. Here connectors are a persisted registry;
path-based connectors (HOPSFS, S3-via-mounted-path) are fully
functional, network-SQL warehouses are configuration carriers whose
``read()`` is gated on their (absent) client libraries.
"""

from __future__ import annotations

import dataclasses
import json
from pathlib import Path
from typing import Any

import pandas as pd

from hops_tpu.featurestore import storage


def _registry_path() -> Path:
    return storage.feature_store_root() / "connectors.json"


def _load_registry() -> dict:
    p = _registry_path()
    return json.loads(p.read_text()) if p.exists() else {}


def _save_registry(reg: dict) -> None:
    _registry_path().write_text(json.dumps(reg, indent=2))


@dataclasses.dataclass
class StorageConnector:
    name: str
    type: str = "HOPSFS"
    options: dict = dataclasses.field(default_factory=dict)

    #: True when read(query=...) executes SQL in the external system
    #: (JDBC); path-based connectors ignore ``query``.
    executes_sql = False

    def read(self, query: str | None = None, data_format: str | None = None,
             path: str | None = None) -> pd.DataFrame:
        raise NotImplementedError

    def spark_options(self) -> dict:
        return dict(self.options)


class HopsFSConnector(StorageConnector):
    """Default connector: paths inside the project workspace."""

    def resolve(self, path: str | None = None) -> Path:
        from hops_tpu.runtime import fs as hfs

        base = self.options.get("path", "")
        rel = str(Path(base) / path) if path else base
        return Path(hfs.project_path(rel)) if not Path(rel).is_absolute() else Path(rel)

    def read(self, query=None, data_format=None, path=None) -> pd.DataFrame:
        target = self.resolve(path)
        return _read_path(target, data_format)


class S3Connector(StorageConnector):
    """S3 bucket (reference ingest role:
    S3-Ingest-to-Feature-Store-basics.ipynb:100).

    Reads accept both bucket-relative keys and full ``s3://bucket/key``
    URIs. The byte source is ``options["mount_point"]`` — a local
    directory standing in for the bucket root (FUSE mount in
    production, an injected fixture dir in tests), so the whole
    resolve→read→ingest path executes without network egress. A URI
    naming a different bucket, or a read with no mount configured,
    raises honestly.
    """

    def resolve(self, path: str | None = None) -> Path:
        mount = self.options.get("mount_point")
        if not mount:
            raise RuntimeError(
                f"S3 connector {self.name!r}: no mount_point configured and "
                "no S3 client library in this image; mount the bucket or "
                "copy locally")
        key = path or ""
        if key.startswith("s3://") or key.startswith("s3a://"):
            rest = key.split("://", 1)[1]
            uri_bucket, _, key = rest.partition("/")
            if not self.bucket:
                raise ValueError(
                    f"S3 connector {self.name!r} has no bucket configured; "
                    "cannot validate URI reads — pass a bucket-relative key "
                    "or create the connector with bucket=...")
            if uri_bucket != self.bucket:
                raise ValueError(
                    f"S3 connector {self.name!r} is bound to bucket "
                    f"{self.bucket!r}, not {uri_bucket!r}")
        # Keys are bucket-relative by definition: anchor them under the
        # mount and refuse escapes (absolute keys, '..' traversal).
        root = Path(mount).resolve()
        target = (root / key.lstrip("/")).resolve()
        if root != target and root not in target.parents:
            raise ValueError(
                f"S3 key {path!r} escapes the mounted bucket root {root}")
        return target

    def read(self, query=None, data_format=None, path=None) -> pd.DataFrame:
        return _read_path(self.resolve(path), data_format)

    @property
    def bucket(self) -> str:
        return self.options.get("bucket", "")


class JDBCConnector(StorageConnector):
    """JDBC-role connector, functional for embedded sqlite databases.

    The reference ingests from warehouse SQL through JDBC connectors
    (Redshift_pyspark.ipynb:129,138; snowflake/getting-started.ipynb:
    115-124 role). Network drivers aren't in this image, but the
    embedded SQL engine is (sql/gateway.py), so a connection string of
    ``jdbc:sqlite:<path>``, ``sqlite:<path>`` or a bare file path
    executes ``read(query)`` directly against that database — the full
    external-SQL → on-demand FG → training-dataset path runs. Other
    JDBC URLs still raise honestly.
    """

    executes_sql = True

    #: Whether a scheme-less connection string may name a local database
    #: file. True for generic JDBC; Snowflake overrides to False since
    #: its scheme-less account URLs (*.snowflakecomputing.com) must
    #: never be mistaken for a filesystem path.
    _allow_bare_path = True

    def read(self, query=None, data_format=None, path=None) -> pd.DataFrame:
        db_path = self._sqlite_path()
        if db_path is None:
            raise RuntimeError(
                f"{self.type} connector {self.name!r}: connection string "
                f"{self.connection_string()!r} requires a network database "
                "driver not in this image; embedded sqlite "
                "(jdbc:sqlite:<path>) is supported")
        if not Path(db_path).exists():
            raise FileNotFoundError(
                f"{self.type} connector {self.name!r}: database {db_path} does not exist")
        sql = query or self.options.get("query")
        if not sql:
            raise ValueError(f"{self.type} connector {self.name!r}: read() needs a query")
        import sqlite3

        db = sqlite3.connect(db_path)
        try:
            return pd.read_sql_query(sql, db)
        finally:
            db.close()

    def connection_string(self) -> str:
        return self.options.get("connection_string", "")

    def _sqlite_path(self) -> str | None:
        cs = self.connection_string()
        for prefix in ("jdbc:sqlite:", "sqlite:///", "sqlite:"):
            if cs.startswith(prefix):
                return cs[len(prefix):]
        if self._allow_bare_path and cs and ":" not in cs.split("/", 1)[0]:
            return cs  # bare filesystem path
        return None


class SnowflakeConnector(JDBCConnector):
    """Snowflake warehouse connector.

    Carries the full option set the reference's Spark reads consume
    (snowflake/getting-started.ipynb:115-124). ``read(query)`` executes
    when ``url`` names an embedded database (``jdbc:sqlite:<path>`` /
    ``sqlite:<path>`` / a bare file path) — the same warehouse-SQL →
    on-demand-FG → training-dataset path as JDBC/Redshift — and raises
    honestly for real ``*.snowflakecomputing.com`` URLs, whose client
    library is not in this image.
    """

    def snowflake_connector_options(self) -> dict:
        """Reference: snowflake/getting-started.ipynb:115-124."""
        o = self.options
        return {
            "sfURL": o.get("url", ""), "sfUser": o.get("user", ""),
            "sfPassword": o.get("password", ""), "sfDatabase": o.get("database", ""),
            "sfSchema": o.get("schema", ""), "sfWarehouse": o.get("warehouse", ""),
            "sfRole": o.get("role", ""),
        }

    _allow_bare_path = False

    def connection_string(self) -> str:
        return self.options.get("connection_string") or self.options.get("url", "")


class RedshiftConnector(JDBCConnector):
    pass


_TYPES = {
    "HOPSFS": HopsFSConnector,
    "S3": S3Connector,
    "JDBC": JDBCConnector,
    "SNOWFLAKE": SnowflakeConnector,
    "REDSHIFT": RedshiftConnector,
}


def create(name: str, connector_type: str, **options: Any) -> StorageConnector:
    ctype = connector_type.upper()
    if ctype not in _TYPES:
        raise ValueError(f"unknown connector type {connector_type!r}; have {sorted(_TYPES)}")
    reg = _load_registry()
    reg[name] = {"type": ctype, "options": options}
    _save_registry(reg)
    return _TYPES[ctype](name=name, type=ctype, options=options)


def get(name: str, connector_type: str | None = None) -> StorageConnector:
    reg = _load_registry()
    if name not in reg:
        if name.upper() == "HOPSFS" or connector_type == "HOPSFS":
            return HopsFSConnector(name=name, type="HOPSFS", options={})
        raise KeyError(f"no storage connector named {name!r}")
    entry = reg[name]
    if connector_type and entry["type"] != connector_type.upper():
        raise KeyError(f"connector {name!r} is {entry['type']}, not {connector_type}")
    return _TYPES[entry["type"]](name=name, type=entry["type"], options=entry["options"])


def _read_path(target: Path, data_format: str | None) -> pd.DataFrame:
    if target.is_dir():
        frames = []
        for p in sorted(target.iterdir()):
            if p.suffix in (".parquet", ".csv"):
                frames.append(_read_path(p, None))
        if not frames:
            raise FileNotFoundError(f"no readable files under {target}")
        return pd.concat(frames, ignore_index=True)
    fmt = data_format or target.suffix.lstrip(".")
    if fmt == "parquet":
        return pd.read_parquet(target)
    if fmt == "csv":
        return pd.read_csv(target)
    raise ValueError(f"unsupported format {fmt!r} for {target}")
