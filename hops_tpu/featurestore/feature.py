"""Feature metadata and filter expressions.

The reference's query filters compose ``Feature`` comparisons with ``&``
(feature_exploration.ipynb cells 14-16, SURVEY.md §2.6 "Query algebra").
Here a comparison produces a :class:`Filter`, and ``&``/``|`` produce a
:class:`Logic` tree that :meth:`evaluate`s against a pandas DataFrame at
query-execution time.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any

import numpy as np
import pandas as pd

# pandas/pyarrow dtype -> feature-store type string (offline types follow
# the reference's Hive-ish names: feature_engineering.ipynb schema output).
_DTYPE_TO_TYPE = {
    "int8": "int",
    "int16": "int",
    "int32": "int",
    "int64": "bigint",
    "uint8": "int",
    "uint16": "int",
    "uint32": "bigint",
    "uint64": "bigint",
    "float16": "float",
    "float32": "float",
    "float64": "double",
    "bool": "boolean",
    "object": "string",
    "string": "string",
    "str": "string",
}


def infer_type(series: pd.Series) -> str:
    """Map a pandas column dtype to a feature type string."""
    dtype = str(series.dtype)
    if dtype.startswith("datetime"):
        return "timestamp"
    if dtype in _DTYPE_TO_TYPE:
        return _DTYPE_TO_TYPE[dtype]
    if dtype.startswith("category"):
        return "string"
    # array-valued columns (e.g. embeddings stored as lists)
    if len(series) and isinstance(series.iloc[0], (list, np.ndarray)):
        return "array<double>"
    return "string"


@dataclasses.dataclass
class Feature:
    """A named, typed column of a feature group.

    Comparison operators build :class:`Filter` conditions, mirroring the
    reference's ``fg.select_all().filter(fg.feature > 10)`` idiom.
    """

    name: str
    type: str = "double"
    primary: bool = False
    partition: bool = False
    description: str = ""

    def __eq__(self, other: Any) -> "Filter":  # type: ignore[override]
        return Filter(self, "==", other)

    def __ne__(self, other: Any) -> "Filter":  # type: ignore[override]
        return Filter(self, "!=", other)

    def __lt__(self, other: Any) -> "Filter":
        return Filter(self, "<", other)

    def __le__(self, other: Any) -> "Filter":
        return Filter(self, "<=", other)

    def __gt__(self, other: Any) -> "Filter":
        return Filter(self, ">", other)

    def __ge__(self, other: Any) -> "Filter":
        return Filter(self, ">=", other)

    def isin(self, values: list) -> "Filter":
        return Filter(self, "in", list(values))

    def like(self, pattern: str) -> "Filter":
        """SQL-LIKE match; ``%`` wildcards."""
        return Filter(self, "like", pattern)

    def contains(self, values: list) -> "Filter":
        return Filter(self, "in", list(values))

    def __hash__(self) -> int:
        return hash((self.name, self.type))

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "Feature":
        return cls(**{k: d[k] for k in ("name", "type", "primary", "partition", "description") if k in d})


class _Condition:
    """Base: things that evaluate to a boolean mask over a DataFrame."""

    def __and__(self, other: "_Condition") -> "Logic":
        return Logic("AND", self, other)

    def __or__(self, other: "_Condition") -> "Logic":
        return Logic("OR", self, other)

    def evaluate(self, df: pd.DataFrame) -> pd.Series:
        raise NotImplementedError


class Filter(_Condition):
    """A single comparison ``feature <op> value``."""

    def __init__(self, feature: Feature, op: str, value: Any):
        self.feature = feature
        self.op = op
        self.value = value

    def evaluate(self, df: pd.DataFrame) -> pd.Series:
        col = df[self.feature.name]
        v = self.value
        if self.op == "==":
            return col == v
        if self.op == "!=":
            return col != v
        if self.op == "<":
            return col < v
        if self.op == "<=":
            return col <= v
        if self.op == ">":
            return col > v
        if self.op == ">=":
            return col >= v
        if self.op == "in":
            return col.isin(v)
        if self.op == "like":
            regex = "^" + "".join(
                ".*" if c == "%" else ("." if c == "_" else re.escape(c)) for c in v
            ) + "$"
            return col.astype(str).str.match(regex)
        raise ValueError(f"unknown filter op {self.op!r}")

    def __repr__(self) -> str:
        return f"Filter({self.feature.name} {self.op} {self.value!r})"


class Logic(_Condition):
    """AND/OR composition of conditions."""

    def __init__(self, op: str, left: _Condition, right: _Condition):
        self.op = op
        self.left = left
        self.right = right

    def evaluate(self, df: pd.DataFrame) -> pd.Series:
        lhs, rhs = self.left.evaluate(df), self.right.evaluate(df)
        return (lhs & rhs) if self.op == "AND" else (lhs | rhs)

    def __repr__(self) -> str:
        return f"({self.left!r} {self.op} {self.right!r})"


def schema_from_dataframe(
    df: pd.DataFrame,
    primary_key: list[str] | None = None,
    partition_key: list[str] | None = None,
) -> list[Feature]:
    """Infer a feature schema from a DataFrame (reference: implicit in
    ``fg.save(df)`` — the server registered the Spark schema)."""
    primary = set(k.lower() for k in (primary_key or []))
    partition = set(k.lower() for k in (partition_key or []))
    feats = []
    for name in df.columns:
        feats.append(
            Feature(
                name=str(name),
                type=infer_type(df[name]),
                primary=str(name).lower() in primary,
                partition=str(name).lower() in partition,
            )
        )
    return feats
