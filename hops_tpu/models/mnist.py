"""MNIST models — the reference's workhorse examples.

Shapes follow the notebooks (conv-conv-dense CNN in
notebooks/ml/Experiment/Tensorflow/mnist.ipynb cell 2; small FFN in
notebooks/ml/End_To_End_Pipeline/tensorflow/model_repo_and_serving.ipynb)
but are fresh flax implementations with bfloat16 MXU compute.
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import linen as nn


class CNN(nn.Module):
    """Conv(32)-pool-Conv(64)-pool-Dense(128)-dropout-Dense(10)."""

    num_classes: int = 10
    dropout_rate: float = 0.5
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = nn.Conv(32, (3, 3), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (3, 3), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(128, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)


class FFN(nn.Module):
    """Flatten-Dense(128)-Dense(10), the end-to-end-pipeline model."""

    num_classes: int = 10
    hidden: int = 128
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype).reshape((x.shape[0], -1))
        x = nn.Dense(self.hidden, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)
