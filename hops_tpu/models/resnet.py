"""ResNet for the benchmark harness.

The reference benchmarked ResNet-50 on synthetic 224x224x3 batches
(notebooks/ml/Benchmarks/benchmark.ipynb cell 2, SURVEY.md §6). This is
a fresh flax ResNet-v1.5 (stride-2 in the 3x3 of bottlenecks, as the
benchmark model family) tuned for TPU HBM bandwidth, the measured
bottleneck (BENCHMARKS.md roofline):

- bfloat16 conv compute so the FLOPs land on the MXU;
- bfloat16 norm *output* (``norm_dtype``) so the residual stream and
  every BN/relu chain move half the bytes — flax's BatchNorm still
  accumulates mean/var in float32 internally, and running statistics
  and all parameters stay float32 (``param_dtype`` default);
- a space-to-depth stem (``s2d_stem``): the 7x7 stride-2 conv over
  3-channel 224x224 input is algebraically rewritten as a 4x4 stride-1
  conv over the 2x2-space-to-depth input (112x112x12), which uses the
  MXU's input rows 4x better while keeping the parameter a standard
  7x7x3xW kernel (checkpoint-compatible; the rewrite happens at apply
  time);
- optional per-block rematerialization (``remat``): save only the
  residual stream at block boundaries and recompute the 3-4 intra-block
  conv/BN/relu activations during backward — on an HBM-bound step the
  saved activation bytes buy more than the recompute FLOPs cost, since
  the MXU has headroom (gradients are numerically identical; A/B via
  ``bench.py --remat``).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn

Conv = partial(nn.Conv, use_bias=False)


def space_to_depth(x: jax.Array, block: int = 2) -> jax.Array:
    """NHWC space-to-depth: (B, H, W, C) -> (B, H/b, W/b, b*b*C)."""
    b, h, w, c = x.shape
    x = x.reshape(b, h // block, block, w // block, block, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, h // block, w // block, block * block * c)


def _s2d_stem_kernel(kernel: jax.Array) -> jax.Array:
    """Rewrite a 7x7xCxW stride-2 kernel as the equivalent 4x4x(4C)xW
    stride-1 kernel over 2x2-space-to-depth input.

    Derivation: output(i,j) sums In[2i+kr-3, 2j+kc-3]*K[kr,kc]. Writing
    input rows as 2p+a (s2d block row p, sub-row a in {0,1}) gives
    kr = 2*pa + a - 1 for s2d tap pa in 0..3 — i.e. pad the 7x7 kernel
    to 8x8 at the leading edge, then fold the parity bit into channels
    in the same (a, b, c) order ``space_to_depth`` produces.
    """
    kh, kw, c, out = kernel.shape  # 7, 7, C, W
    k8 = jnp.pad(kernel, ((1, 0), (1, 0), (0, 0), (0, 0)))
    k8 = k8.reshape(4, 2, 4, 2, c, out)  # (pa, a, qb, b, c, o)
    k8 = k8.transpose(0, 2, 1, 3, 4, 5)  # (pa, qb, a, b, c, o)
    return k8.reshape(4, 4, 4 * c, out)


class BottleneckBlock(nn.Module):
    filters: int
    strides: tuple[int, int] = (1, 1)
    dtype: jnp.dtype = jnp.bfloat16
    norm: Callable[..., Any] = nn.BatchNorm
    norm_dtype: jnp.dtype | None = None  # None = follow ``dtype``

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = partial(
            self.norm,
            use_running_average=not train,
            momentum=0.9,
            dtype=self.norm_dtype if self.norm_dtype is not None else self.dtype,
        )
        residual = x
        y = Conv(self.filters, (1, 1), dtype=self.dtype)(x)
        y = norm()(y)
        y = nn.relu(y)
        y = Conv(self.filters, (3, 3), self.strides, dtype=self.dtype)(y)
        y = norm()(y)
        y = nn.relu(y)
        y = Conv(self.filters * 4, (1, 1), dtype=self.dtype)(y)
        y = norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = Conv(
                self.filters * 4, (1, 1), self.strides, dtype=self.dtype, name="proj"
            )(residual)
            residual = norm(name="proj_bn")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    num_classes: int = 1000
    width: int = 64
    dtype: jnp.dtype = jnp.bfloat16
    norm_dtype: jnp.dtype | None = None  # None = follow ``dtype``
    s2d_stem: bool = True
    remat: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        # The stem parameter is always the canonical 7x7xCxW kernel; the
        # space-to-depth rewrite is an apply-time algebraic identity.
        stem_kernel = self.param(
            "stem_conv",
            nn.initializers.lecun_normal(),
            (7, 7, x.shape[-1], self.width),
            jnp.float32,
        ).astype(self.dtype)
        if self.s2d_stem and x.shape[1] % 2 == 0 and x.shape[2] % 2 == 0:
            x = jax.lax.conv_general_dilated(
                space_to_depth(x),
                _s2d_stem_kernel(stem_kernel),
                window_strides=(1, 1),
                padding=((2, 1), (2, 1)),
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
            )
        else:
            x = jax.lax.conv_general_dilated(
                x,
                stem_kernel,
                window_strides=(2, 2),
                padding=((3, 3), (3, 3)),
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
            )
        x = nn.BatchNorm(
            use_running_average=not train,
            momentum=0.9,
            dtype=self.norm_dtype if self.norm_dtype is not None else self.dtype,
        )(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        # static_argnums=(2,): the train flag is Python control flow
        # inside the block, not a traceable input. Blocks carry explicit
        # names so the parameter tree is identical with remat on or off
        # (nn.remat would otherwise rename to CheckpointBottleneckBlock_n,
        # making checkpoints non-interchangeable).
        block_cls = nn.remat(BottleneckBlock, static_argnums=(2,)) if self.remat else BottleneckBlock
        n = 0
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = block_cls(
                    self.width * 2**i, strides, self.dtype, norm_dtype=self.norm_dtype,
                    name=f"BottleneckBlock_{n}",
                )(x, train)
                n += 1
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)


def ResNet50(
    num_classes: int = 1000,
    dtype: jnp.dtype = jnp.bfloat16,
    norm_dtype: jnp.dtype | None = None,
    s2d_stem: bool = True,
    remat: bool = False,
) -> ResNet:
    return ResNet(
        [3, 4, 6, 3],
        num_classes=num_classes,
        dtype=dtype,
        norm_dtype=norm_dtype,
        s2d_stem=s2d_stem,
        remat=remat,
    )


def ResNet18ish(
    num_classes: int = 10, dtype: jnp.dtype = jnp.bfloat16, remat: bool = False
) -> ResNet:
    """Small bottleneck variant for CI-scale tests."""
    return ResNet([1, 1, 1, 1], num_classes=num_classes, width=16, dtype=dtype, remat=remat)
