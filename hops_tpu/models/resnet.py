"""ResNet for the benchmark harness.

The reference benchmarked ResNet-50 on synthetic 224x224x3 batches
(notebooks/ml/Benchmarks/benchmark.ipynb cell 2, SURVEY.md §6). This is
a fresh flax ResNet-v1.5 (stride-2 in the 3x3 of bottlenecks, as the
benchmark model family) with bfloat16 compute so conv FLOPs land on the
MXU, float32 batch-norm statistics for stability.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence

import jax.numpy as jnp
from flax import linen as nn

Conv = partial(nn.Conv, use_bias=False)


class BottleneckBlock(nn.Module):
    filters: int
    strides: tuple[int, int] = (1, 1)
    dtype: jnp.dtype = jnp.bfloat16
    norm: Callable[..., Any] = nn.BatchNorm

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = partial(
            self.norm, use_running_average=not train, momentum=0.9, dtype=jnp.float32
        )
        residual = x
        y = Conv(self.filters, (1, 1), dtype=self.dtype)(x)
        y = norm()(y)
        y = nn.relu(y)
        y = Conv(self.filters, (3, 3), self.strides, dtype=self.dtype)(y)
        y = norm()(y)
        y = nn.relu(y)
        y = Conv(self.filters * 4, (1, 1), dtype=self.dtype)(y)
        y = norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = Conv(
                self.filters * 4, (1, 1), self.strides, dtype=self.dtype, name="proj"
            )(residual)
            residual = norm(name="proj_bn")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    num_classes: int = 1000
    width: int = 64
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = Conv(self.width, (7, 7), (2, 2), padding=[(3, 3), (3, 3)], dtype=self.dtype)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9, dtype=jnp.float32)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = BottleneckBlock(self.width * 2**i, strides, self.dtype)(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)


def ResNet50(num_classes: int = 1000, dtype: jnp.dtype = jnp.bfloat16) -> ResNet:
    return ResNet([3, 4, 6, 3], num_classes=num_classes, dtype=dtype)


def ResNet18ish(num_classes: int = 10, dtype: jnp.dtype = jnp.bfloat16) -> ResNet:
    """Small bottleneck variant for CI-scale tests."""
    return ResNet([1, 1, 1, 1], num_classes=num_classes, width=16, dtype=dtype)
